"""Shared log formatting: plain text (default, unchanged) or JSON lines.

``--log-format json`` on the service CLIs swaps the root handler's
formatter for :class:`JsonLogFormatter`: one JSON object per record, with
``trace_id``/``span_id`` included whenever the logging call happens under
an active span (``obs.trace`` thread-local context). Handlers emit on the
calling thread, so resolving the context inside the formatter is exact.

The default text path deliberately stays ``logging.basicConfig``: logs
scraped by existing tooling must not change shape until the operator
opts in.
"""

from __future__ import annotations

import json
import logging
import time

from predictionio_tpu.obs import trace

LOG_FORMATS = ("text", "json")


class TraceContextFilter(logging.Filter):
    """Stamp ``trace_id``/``span_id`` (or None) onto every record so any
    formatter -- including user-supplied text formats with
    ``%(trace_id)s`` -- can reference them."""

    def filter(self, record: logging.LogRecord) -> bool:
        ctx = trace.current_context()
        record.trace_id = ctx[0] if ctx else None
        record.span_id = ctx[1] if ctx else None
        return True


class JsonLogFormatter(logging.Formatter):
    """One JSON object per record; trace ids only when a span is active."""

    def format(self, record: logging.LogRecord) -> str:
        obj = {
            "ts": time.strftime(
                "%Y-%m-%dT%H:%M:%S", time.gmtime(record.created)
            ) + f".{int(record.msecs):03d}Z",
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        # the filter normally stamps these; resolve here too so the
        # formatter works on handlers without the filter attached
        ctx = (
            (record.__dict__.get("trace_id"), record.__dict__.get("span_id"))
            if "trace_id" in record.__dict__
            else (trace.current_context() or (None, None))
        )
        if ctx[0]:
            obj["trace_id"], obj["span_id"] = ctx[0], ctx[1]
        if record.exc_info:
            obj["exc"] = self.formatException(record.exc_info)
        return json.dumps(obj, default=str)


def configure_logging(log_format: str = "text", level: int | str = logging.INFO) -> None:
    """Install the chosen format on the root logger (service CLI entry).

    ``text`` keeps stdlib ``basicConfig`` behavior untouched; ``json``
    replaces the root handlers with one stderr handler emitting JSON
    lines (idempotent: calling twice reconfigures in place).
    """
    if log_format not in LOG_FORMATS:
        raise ValueError(
            f"log_format must be one of {LOG_FORMATS}, got {log_format!r}"
        )
    root = logging.getLogger()
    if log_format == "text":
        logging.basicConfig(level=level)
        return
    handler = logging.StreamHandler()
    handler.setFormatter(JsonLogFormatter())
    handler.addFilter(TraceContextFilter())
    root.handlers[:] = [handler]
    root.setLevel(level)


def add_logging_arguments(parser) -> None:
    """The shared ``--log-format`` flag every service CLI exposes."""
    parser.add_argument(
        "--log-format",
        choices=LOG_FORMATS,
        default="text",
        help="log output format: 'json' emits one JSON object per record"
        " with trace_id/span_id when a span is active (default: text,"
        " unchanged stdlib format)",
    )
