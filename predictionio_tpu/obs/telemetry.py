"""Per-step training telemetry journal (``pio train --profile``).

The ALX paper (arxiv 2112.02194) treats per-step achieved bandwidth as
the primary training metric; the ``jax.profiler`` trace gives the deep
view but needs tensorboard/xprof to open. This journal is the cheap,
always-parseable companion: one JSON line per training step with wall
time, edges/sec, and the achieved HBM GB/s implied by the bytes-moved
model (``ops.als_gram.half_step_bytes``), plus the jit recompile count so
a shape-instability regression (recompiling every step) is visible as a
climbing integer instead of a mysteriously slow run.

Lines are flushed as written: a crashed or preempted run keeps every
completed step's record.
"""

from __future__ import annotations

import json
import os
import time


class TrainTelemetry:
    """JSONL step journal. First line is a ``meta`` record (edge count,
    modeled bytes/iter, run shape); each ``record_step`` appends a
    ``step`` record. Single-writer (the training loop)."""

    def __init__(
        self,
        path: str,
        *,
        edges: int | None = None,
        modeled_bytes_per_iter: float | None = None,
        meta: dict | None = None,
    ):
        self.path = path
        self.edges = edges
        self.modeled_bytes_per_iter = modeled_bytes_per_iter
        self.steps = 0
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "w")
        self._write(
            {
                "event": "meta",
                "edges": edges,
                "modeled_bytes_per_iter": modeled_bytes_per_iter,
                **(meta or {}),
            }
        )

    def _write(self, obj: dict) -> None:
        obj["ts"] = round(time.time(), 3)
        self._f.write(json.dumps(obj) + "\n")
        self._f.flush()

    def record_step(
        self,
        step: int,
        wall_s: float,
        *,
        recompile_count: int | None = None,
        extra: dict | None = None,
    ) -> dict:
        """Append one step record; returns the object written."""
        obj: dict = {
            "event": "step",
            "step": int(step),
            "wall_s": round(float(wall_s), 6),
        }
        if self.edges is not None and wall_s > 0:
            obj["edges_per_sec"] = round(self.edges / wall_s, 1)
        if self.modeled_bytes_per_iter is not None and wall_s > 0:
            obj["achieved_gbps"] = round(
                self.modeled_bytes_per_iter / wall_s / 1e9, 3
            )
        if recompile_count is not None:
            obj["recompile_count"] = int(recompile_count)
        if extra:
            obj.update(extra)
        self._write(obj)
        self.steps += 1
        return obj

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self) -> "TrainTelemetry":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def jit_cache_size(fn) -> int | None:
    """Compiled-program count of a ``jax.jit`` callable (the recompile
    counter's source), or None where the private API is absent."""
    try:
        return int(fn._cache_size())
    except Exception:
        return None
