"""``pio top``: live terminal view over ``/metrics`` + ``/traces.json``.

Polls one or more services and renders, per poll interval: request rate
(qps), error rate, latency quantiles (p50/p99, interpolated from the
``pio_http_request_duration_seconds`` histogram DELTA between polls --
point-in-time behavior, not lifetime averages), ingest queue depth,
micro-batch occupancy, and the current slowest traces.

Everything rate-like is computed from counter deltas between consecutive
snapshots, so the numbers answer "what is happening NOW", which is the
question the aggregate `/metrics` endpoint alone cannot.

Stdlib only; importable pieces (``parse_prometheus``, ``compute_stats``,
``render``) are pure functions so the view is testable without sockets.
"""

from __future__ import annotations

import json
import re
import time
import urllib.request

_SAMPLE_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_prometheus(text: str) -> dict[str, dict[tuple, float]]:
    """Prometheus text exposition -> ``{name: {label-kv-tuple: value}}``."""
    out: dict[str, dict[tuple, float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name, labels_raw, value = m.group(1), m.group(2) or "", m.group(3)
        labels = tuple(
            (k, v.replace('\\"', '"').replace("\\\\", "\\"))
            for k, v in _LABEL_RE.findall(labels_raw)
        )
        try:
            out.setdefault(name, {})[labels] = float(value)
        except ValueError:
            continue
    return out


def fetch_snapshot(url: str, timeout: float = 3.0) -> dict:
    """One poll of a service: parsed /metrics + /traces.json (either may
    be missing; a dead endpoint yields an ``error`` entry, not a crash)."""
    snap: dict = {"url": url, "time": time.perf_counter()}
    try:
        with urllib.request.urlopen(f"{url}/metrics", timeout=timeout) as r:
            snap["metrics"] = parse_prometheus(r.read().decode("utf-8"))
    except Exception as exc:
        snap["metrics"] = None
        snap["error"] = f"/metrics: {exc}"
    try:
        with urllib.request.urlopen(
            f"{url}/traces.json?limit=5", timeout=timeout
        ) as r:
            snap["traces"] = json.loads(r.read().decode("utf-8"))
    except Exception:
        snap["traces"] = None
    return snap


#: routes `pio top` itself hits every poll -- excluded from qps/error/latency
#: or an idle service would show nothing but the tool's own scrape traffic
_SELF_ROUTES = frozenset(("/metrics", "/traces.json"))


def _total(series: dict[tuple, float] | None, **match: str) -> float:
    if not series:
        return 0.0
    total = 0.0
    for labels, value in series.items():
        d = dict(labels)
        if all(d.get(k) == v for k, v in match.items()):
            total += value
    return total


def _histogram_delta(prev: dict, cur: dict, name: str) -> list[tuple[float, float]]:
    """Sorted ``(le, cumulative-count-delta)`` for one histogram, buckets
    summed across label sets (routes)."""
    pb = (prev or {}).get(f"{name}_bucket", {})
    cb = (cur or {}).get(f"{name}_bucket", {})
    by_le: dict[float, float] = {}
    for labels, value in cb.items():
        d = dict(labels)
        le = d.get("le")
        if le is None or d.get("route") in _SELF_ROUTES:
            continue
        le_f = float("inf") if le == "+Inf" else float(le)
        by_le[le_f] = by_le.get(le_f, 0.0) + value - pb.get(labels, 0.0)
    return sorted(by_le.items())


def _quantile_ms(buckets: list[tuple[float, float]], q: float) -> float | None:
    """Linear-interpolated quantile (ms) from cumulative bucket deltas --
    the standard histogram_quantile() estimate."""
    if not buckets:
        return None
    total = buckets[-1][1]
    if total <= 0:
        return None
    rank = q * total
    lo_le, lo_count = 0.0, 0.0
    for le, count in buckets:
        if count >= rank:
            if le == float("inf"):
                return round(lo_le * 1000.0, 2)  # open bucket: lower bound
            span = count - lo_count
            frac = (rank - lo_count) / span if span > 0 else 1.0
            return round((lo_le + (le - lo_le) * frac) * 1000.0, 2)
        lo_le, lo_count = le, count
    return round(lo_le * 1000.0, 2)


def compute_stats(prev: dict, cur: dict) -> dict:
    """Point-in-time stats for one service from two consecutive snapshots."""
    stats: dict = {"url": cur["url"]}
    if cur.get("error"):
        stats["error"] = cur["error"]
        return stats
    pm, cm = prev.get("metrics") or {}, cur.get("metrics") or {}
    dt = max(cur["time"] - prev["time"], 1e-9)
    req = {
        k: v
        for k, v in cm.get("pio_http_requests_total", {}).items()
        if dict(k).get("route") not in _SELF_ROUTES
    }
    preq = pm.get("pio_http_requests_total", {})
    d_total = sum(v - preq.get(k, 0.0) for k, v in req.items())
    d_err = sum(
        v - preq.get(k, 0.0)
        for k, v in req.items()
        if dict(k).get("status", "").startswith(("4", "5"))
    )
    stats["qps"] = round(d_total / dt, 1)
    stats["error_rate"] = round(d_err / d_total, 4) if d_total > 0 else 0.0
    lat = _histogram_delta(pm, cm, "pio_http_request_duration_seconds")
    stats["p50_ms"] = _quantile_ms(lat, 0.50)
    stats["p99_ms"] = _quantile_ms(lat, 0.99)
    depth = cm.get("pio_ingest_queue_depth")
    if depth:
        stats["ingest_queue_depth"] = int(sum(depth.values()))
    serving_depth = cm.get("pio_serving_queue_depth")
    if serving_depth:
        stats["ingest_queue_depth"] = stats.get(
            "ingest_queue_depth", 0
        ) + int(sum(serving_depth.values()))
    wpr = cm.get("pio_scorer_wakeups_per_request")
    if wpr:
        # the scorer's measured dispatch cost: cross-thread wakeups per
        # query (async fast path <= 2, sync dispatcher chain ~4)
        stats["wakeups_per_request"] = round(max(wpr.values()), 2)
    workers = cm.get("pio_frontend_workers")
    if workers:
        # the multi-process serving tier: configured frontend count plus
        # the per-worker forwarded totals (aggregated across processes)
        stats["frontend_workers"] = int(sum(workers.values()))
        fw_req = cm.get("pio_frontend_requests_total", {})
        pfw_req = pm.get("pio_frontend_requests_total", {})
        # clamp per series: a respawned worker restarts its counters at
        # zero while the scrape stays healthy, so an un-clamped delta
        # would render a large negative qps for that poll interval
        d_fw = sum(
            max(v - pfw_req.get(k, 0.0), 0.0) for k, v in fw_req.items()
        )
        stats["frontend_qps"] = round(d_fw / dt, 1)
    parts = cm.get("pio_ingest_partitions")
    if parts:
        # the partitioned ingest tier: WAL partition count in the PART
        # column (per-partition queue depth and commit latency live in
        # pio_ingest_partition_depth{part=} / pio_ingest_commit_seconds{part=})
        stats["wal_partitions"] = int(max(parts.values()))
    shards = cm.get("pio_scorer_shard_count")
    if shards:
        # the sharded serving fabric: scorer shard count in the SHARD
        # column. pio_model_version carries a shard label there, so the
        # MODEL column below (max across series) briefly leads by one
        # version mid-swap -- exactly the fabric's allowed skew window
        stats["scorer_shards"] = int(max(shards.values()))
    # continuous-learning gauges (pio retrain --follow): which model
    # version is live, how long ago it swapped in, and how many seconds of
    # ingested events are not yet reflected in it
    mv = cm.get("pio_model_version")
    if mv:
        stats["model_version"] = int(max(mv.values()))
    swap_ts = cm.get("pio_model_last_swap_timestamp_seconds")
    if swap_ts:
        stats["swap_age_s"] = round(max(0.0, time.time() - max(swap_ts.values())), 1)
    lag = cm.get("pio_foldin_lag_seconds")
    if lag:
        stats["foldin_lag_s"] = round(max(lag.values()), 1)
    d_batches = _total(cm.get("pio_serving_batch_size_count")) - _total(
        pm.get("pio_serving_batch_size_count")
    )
    d_batched = _total(cm.get("pio_serving_batch_size_sum")) - _total(
        pm.get("pio_serving_batch_size_sum")
    )
    if d_batches > 0:
        stats["batch_occupancy"] = round(d_batched / d_batches, 2)
    build = cm.get("pio_build_info")
    if build:
        stats["build"] = dict(next(iter(build)))
    return stats


def _fmt(value, suffix: str = "") -> str:
    return "-" if value is None else f"{value}{suffix}"


def render(stats_list: list[dict], snapshots: list[dict], width: int = 100) -> str:
    """One text frame for the terminal (also the format tests assert on)."""
    lines = [
        time.strftime("pio top — %H:%M:%S", time.localtime()),
        "",
        f"{'SERVICE':<32}{'QPS':>8}{'P50MS':>9}{'P99MS':>9}"
        f"{'ERR%':>7}{'QUEUE':>7}{'BATCH':>7}{'WKR':>5}{'SHARD':>6}"
        f"{'PART':>6}{'WAKE':>6}{'MODEL':>7}{'SWAP':>8}{'LAG':>7}",
    ]
    for s in stats_list:
        if s.get("error"):
            lines.append(f"{s['url']:<32}  unreachable: {s['error']}")
            continue
        lines.append(
            f"{s['url']:<32}"
            f"{_fmt(s.get('qps')):>8}"
            f"{_fmt(s.get('p50_ms')):>9}"
            f"{_fmt(s.get('p99_ms')):>9}"
            f"{_fmt(round(s.get('error_rate', 0.0) * 100, 1)):>7}"
            f"{_fmt(s.get('ingest_queue_depth')):>7}"
            f"{_fmt(s.get('batch_occupancy')):>7}"
            f"{_fmt(s.get('frontend_workers')):>5}"
            f"{_fmt(s.get('scorer_shards')):>6}"
            f"{_fmt(s.get('wal_partitions')):>6}"
            f"{_fmt(s.get('wakeups_per_request')):>6}"
            f"{_fmt(s.get('model_version')):>7}"
            f"{_fmt(s.get('swap_age_s'), 's'):>8}"
            f"{_fmt(s.get('foldin_lag_s'), 's'):>7}"
        )
    slowest: list[tuple[float, str, dict]] = []
    for snap in snapshots:
        traces = (snap.get("traces") or {}).get("slowest") or []
        for t in traces:
            slowest.append((t.get("durationMs", 0.0), snap["url"], t))
    slowest.sort(key=lambda e: -e[0])
    if slowest:
        lines.append("")
        lines.append("SLOWEST TRACES")
        for dur, url, t in slowest[:8]:
            ops = " > ".join(s["op"] for s in t.get("spans", [])[:6])
            lines.append(
                f"  {dur:>9.1f}ms  {t.get('status', '?'):<5} "
                f"{t.get('traceId', '')[:16]}  {t.get('op', '')}"
            )
            if ops:
                lines.append(f"{'':>14}{ops[: width - 14]}")
    return "\n".join(lines)


def run_top(
    urls: list[str],
    interval: float = 2.0,
    iterations: int = 0,
    clear: bool = True,
    out=print,
) -> None:
    """The polling loop. ``iterations=0`` runs until interrupted; tests
    pass a finite count and a capture ``out``. The first frame needs two
    snapshots (rates are deltas), so the loop primes once silently."""
    prev = [fetch_snapshot(u) for u in urls]
    n = 0
    while iterations <= 0 or n < iterations:
        time.sleep(interval)
        cur = [fetch_snapshot(u) for u in urls]
        stats = [compute_stats(p, c) for p, c in zip(prev, cur)]
        frame = render(stats, cur)
        if clear:
            out("\x1b[2J\x1b[H" + frame)
        else:
            out(frame)
        prev = cur
        n += 1
