"""Low-overhead span tracer with W3C ``traceparent`` propagation.

Design constraints, in order:

1. **The tracing-off path allocates nothing.** ``Tracer.span`` on a
   disabled tracer returns one shared no-op singleton; hot paths can be
   instrumented unconditionally.
2. **Bounded memory.** Finished traces land in a ring buffer
   (``recent``); eviction there must not lose the traces an operator
   actually wants, so slow and error traces are ALSO retained in two
   small tail-keep buffers (top-N by duration, last-N errors) that fast
   traffic cannot wash out.
3. **Cross-thread fan-out.** The batching tiers (micro-batcher, ingest
   group commit) do one unit of device/disk work for many coalesced
   requests. ``record_span`` writes an explicitly-timed span into ANY
   live trace, and a shared ``span_id`` lets one batch-level span appear
   in every participating request's trace (the "which batch did my
   request ride" join).

Context propagation is a module-global thread-local stack shared by all
tracers: one thread has one active span, regardless of which service's
tracer opened it, so log records (``obs.logs``) can resolve ids without a
tracer reference. Remote context arrives/leaves as the W3C trace-context
``traceparent`` header (``00-<trace32>-<span16>-<flags>``).

Durations come from ``time.perf_counter()``; wall-clock display times are
derived once via a process-constant offset so spans timed on different
threads line up on one axis.
"""

from __future__ import annotations

import heapq
import logging
import os
import random
import re
import threading
import time
from collections import deque
from dataclasses import dataclass, field

logger = logging.getLogger("pio.trace")

#: perf_counter -> epoch-seconds offset, captured once at import so every
#: span in the process shares one time axis
_PC_TO_WALL = time.time() - time.perf_counter()

_TRACEPARENT_RE = re.compile(
    r"^[0-9a-f]{2}-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)

#: spans retained per live trace; a runaway instrumentation loop must cap
#: at this, not grow without bound
MAX_SPANS_PER_TRACE = 256

_tls = threading.local()


def _stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def current_context() -> "tuple[str, str] | None":
    """(trace_id, span_id) of the calling thread's active span, or None.
    Module-level (not per-tracer) so log formatters need no tracer ref."""
    stack = getattr(_tls, "stack", None)
    if not stack:
        return None
    span = stack[-1]
    return (span.trace_id, span.span_id)


#: id source: a Mersenne twister seeded once from the OS. ``os.urandom``
#: per id costs a syscall (~15us on sandboxed kernels -- measured 25x the
#: rest of the span lifecycle combined); ids need collision resistance,
#: not unpredictability. getrandbits is one C call, atomic under the GIL.
_id_rand = random.Random(os.urandom(16))


def new_trace_id() -> str:
    return f"{_id_rand.getrandbits(128) or 1:032x}"  # all-zero id is invalid


def new_span_id() -> str:
    return f"{_id_rand.getrandbits(64) or 1:016x}"


def format_traceparent(trace_id: str, span_id: str) -> str:
    return f"00-{trace_id}-{span_id}-01"


def parse_traceparent(header: str | None) -> tuple[str, str, bool] | None:
    """(trace_id, parent_span_id, sampled) from a W3C traceparent header,
    or None for anything malformed (a bad header must start a fresh
    trace, never error a request). ``sampled`` is the trace-flags 01
    bit: the caller's own sampling decision."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    trace_id, span_id = m.group(1), m.group(2)
    # all-zero ids are explicitly invalid per the spec
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id, bool(int(m.group(3), 16) & 0x01)


@dataclass(slots=True)
class SpanRecord:
    """One finished span (immutable once recorded)."""

    trace_id: str
    span_id: str
    parent_id: str | None
    op: str
    start_s: float      # epoch seconds
    duration_s: float
    status: str = "ok"  # ok | error
    attrs: dict = field(default_factory=dict)
    thread: str = ""
    #: True when this record must NOT flow through the span->histogram
    #: bridge at root finish: per-request ``batch.queue_wait`` already
    #: aggregates natively as ``pio_serving_batch_queue_wait_seconds``,
    #: and the shared batch-level spans are bridged exactly once per
    #: batch by ``record_fanout`` -- bridging the per-trace copies too
    #: would count one device batch N times
    bridged: bool = False

    def to_json_obj(self, trace_start_s: float) -> dict:
        obj = {
            "op": self.op,
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "offsetMs": round((self.start_s - trace_start_s) * 1000.0, 3),
            "durationMs": round(self.duration_s * 1000.0, 3),
            "status": self.status,
            "thread": self.thread,
        }
        if self.attrs:
            obj["attrs"] = self.attrs
        return obj


class _NullSpan:
    """The shared no-op span: disabled tracers hand this out so the
    tracing-off hot path allocates no objects at all."""

    __slots__ = ()
    trace_id = None
    span_id = None

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set_op(self, op: str) -> None:
        pass

    def set_attr(self, key: str, value) -> None:
        pass

    def set_status(self, status: str) -> None:
        pass

    def attach(self) -> "_NullSpan":
        return self

    def detach(self) -> None:
        pass

    def finish(self) -> None:
        pass


NULL_SPAN = _NullSpan()


class _SampledOutRoot:
    """The shared root handed out when a headerless root loses the
    sampling coin flip. Entering it raises a thread-local suppression
    flag so every nested ``span()`` call returns the no-op singleton
    instead of opening a fresh root trace of its own -- the whole
    request costs one boolean, no allocations. Only roots sample (a
    suppressed thread cannot open a second root before exiting), so one
    shared instance is safe."""

    __slots__ = ()
    trace_id = None
    span_id = None

    def __enter__(self) -> "_SampledOutRoot":
        _tls.suppress = True
        return self

    def __exit__(self, *exc) -> bool:
        _tls.suppress = False
        return False

    def set_op(self, op: str) -> None:
        pass

    def set_attr(self, key: str, value) -> None:
        pass

    def set_status(self, status: str) -> None:
        pass

    def attach(self) -> "_SampledOutRoot":
        """Handle-style suppression (the async fast path carries this
        sentinel across threads like a real Span): raise the calling
        thread's suppress flag so nested ``span()`` calls stay no-ops
        instead of opening fresh roots. Pair with ``detach()``."""
        _tls.suppress = True
        return self

    def detach(self) -> None:
        _tls.suppress = False

    def finish(self) -> None:
        pass


SAMPLED_OUT_ROOT = _SampledOutRoot()


class Span:
    """A live span: context manager that pushes itself on the thread's
    context stack and reports to its tracer on exit.

    Spans are also EXPLICIT HANDLES for code whose request does not stay
    on one thread (the async scorer fast path): ``attach()``/``detach()``
    manage the calling thread's context stack without ending the span,
    and ``finish()`` records the end from ANY thread -- start a root on
    the ring consumer, attach around the micro-batcher submit so
    ``current_context()`` captures it, detach, and finish from the
    flusher's ``Future.add_done_callback``. ``__enter__``/``__exit__``
    are exactly ``attach()`` + (``detach()``; ``finish()``)."""

    __slots__ = (
        "_tracer", "op", "trace_id", "span_id", "parent_id", "attrs",
        "status", "_start_pc", "_root", "_finished",
    )

    def __init__(self, tracer: "Tracer", op: str, trace_id: str,
                 parent_id: str | None, root: bool, attrs: dict | None):
        self._tracer = tracer
        self.op = op
        self.trace_id = trace_id
        self.span_id = new_span_id()
        self.parent_id = parent_id
        self.attrs = attrs
        self.status = "ok"
        self._root = root
        self._finished = False
        if root:
            # register the trace as live IMMEDIATELY: record_span from
            # another thread can attach to it for the root's whole lifetime
            with tracer._lock:
                tracer._begin_trace(trace_id)
        self._start_pc = time.perf_counter()

    def set_op(self, op: str) -> None:
        self.op = op

    def set_attr(self, key: str, value) -> None:
        if self.attrs is None:
            self.attrs = {}
        self.attrs[key] = value

    def set_status(self, status: str) -> None:
        self.status = status

    def attach(self) -> "Span":
        """Push this span onto the CALLING thread's context stack (so
        ``current_context()`` and nested ``tracer.span()`` calls see it)
        without affecting its lifetime. Pair with ``detach()``."""
        _stack().append(self)
        return self

    def detach(self) -> None:
        """Pop this span off the calling thread's context stack WITHOUT
        finishing it -- the span stays live and can be finished later
        from another thread."""
        stack = _stack()
        if stack and stack[-1] is self:
            stack.pop()
        elif self in stack:  # defensive: mis-nested exits must not corrupt
            stack.remove(self)

    def finish(self) -> None:
        """Record the span's end. Thread-agnostic and idempotent (a
        double finish records once); does NOT touch any context stack --
        callers that attached must detach themselves."""
        if self._finished:
            return
        self._finished = True
        end_pc = time.perf_counter()
        record = SpanRecord(
            trace_id=self.trace_id,
            span_id=self.span_id,
            parent_id=self.parent_id,
            op=self.op,
            start_s=self._start_pc + _PC_TO_WALL,
            duration_s=end_pc - self._start_pc,
            status=self.status,
            attrs=self.attrs or {},
            thread=threading.current_thread().name,
        )
        self._tracer._span_finished(record, self._root)

    def __enter__(self) -> "Span":
        return self.attach()

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.detach()
        if exc_type is not None:
            self.status = "error"
            if self.attrs is None:
                self.attrs = {}
            self.attrs.setdefault("error", f"{exc_type.__name__}: {exc}")
        self.finish()
        return False


class Tracer:
    """Span factory + bounded trace retention + ``/traces.json`` source.

    ``on_spans(records)`` runs OUTSIDE the tracer lock with a LIST of
    finished spans (the span->histogram bridge; see
    ``utils.metrics.span_bridge``). It fires once per COMPLETED trace
    with every span of that trace, and once per standalone record --
    batching matters: per-span bridge calls meant one metrics-lock
    round-trip per span, and on a GIL-bound serving box the resulting
    lock convoy across 32 handler threads cost more than the spans
    themselves."""

    def __init__(
        self,
        enabled: bool = True,
        recent_cap: int = 128,
        keep_cap: int = 32,
        live_cap: int = 512,
        on_spans=None,
        sample: float = 1.0,
    ):
        self.enabled = enabled
        #: head-sampling rate for SELF-INITIATED roots (no inbound
        #: traceparent): full per-request tracing costs ~0.4 ms of python
        #: on the GIL-bound serving path (~10% qps on the 2-core box),
        #: so the service routers default to a sampled rate
        #: (``tracing_sample_default``) while remote-initiated requests
        #: -- where the caller already decided to trace -- always record.
        #: Direct construction (tests, training) defaults to 1.0.
        self.sample = min(max(float(sample), 0.0), 1.0)
        self.on_spans = on_spans
        self._lock = threading.Lock()
        #: trace_id -> list[SpanRecord] for traces whose root is still open
        self._live: dict[str, list] = {}
        self._live_cap = live_cap
        #: every finished trace, newest last (plain ring: fast traffic
        #: evicts old entries)
        self._recent: deque = deque(maxlen=recent_cap)
        #: tail-based keep: top-N slowest traces ever (min at index 0)
        self._slow: list = []
        self._slow_cap = keep_cap
        self._seq = 0
        #: last-N error traces (eviction-proof like _slow)
        self._errors: deque = deque(maxlen=keep_cap)
        #: (op_prefix, seconds) slow-log thresholds, longest prefix wins
        self._slow_log: list[tuple[str, float]] = []

    # -- span creation ------------------------------------------------------
    def span(self, op: str, attrs: dict | None = None):
        """Start a child of the calling thread's active span (or a new
        root trace). Returns the shared no-op singleton when disabled."""
        if not self.enabled:
            return NULL_SPAN
        if getattr(_tls, "suppress", False):
            return NULL_SPAN
        stack = getattr(_tls, "stack", None)
        if stack:
            parent = stack[-1]
            return Span(self, op, parent.trace_id, parent.span_id, False, attrs)
        if self.sample < 1.0 and _id_rand.random() >= self.sample:
            return SAMPLED_OUT_ROOT
        return Span(self, op, new_trace_id(), None, True, attrs)

    def start_remote(self, op: str, traceparent: str | None,
                     attrs: dict | None = None):
        """Root span for an inbound request: joins the caller's trace when
        a valid ``traceparent`` header arrived with the sampled flag set
        (ALWAYS recorded -- the caller decided to trace; sampling is
        theirs). A header with the flag CLEAR (e.g. a mesh proxy that
        stamps every request with ``-00``) must not force 100% tracing:
        it is subject to this tracer's ``sample`` rate like a headerless
        request, though a sampled-in trace still joins the caller's ids
        so logs correlate. No header starts a fresh sampled trace."""
        if not self.enabled:
            return NULL_SPAN
        remote = parse_traceparent(traceparent)
        if remote is not None and remote[2]:
            return Span(self, op, remote[0], remote[1], True, attrs)
        if self.sample < 1.0 and _id_rand.random() >= self.sample:
            return SAMPLED_OUT_ROOT
        if remote is not None:
            return Span(self, op, remote[0], remote[1], True, attrs)
        return Span(self, op, new_trace_id(), None, True, attrs)

    def record_span(
        self,
        trace_id: str,
        op: str,
        start_pc: float,
        end_pc: float,
        *,
        parent_id: str | None = None,
        span_id: str | None = None,
        attrs: dict | None = None,
        status: str = "ok",
    ) -> str | None:
        """Record an explicitly-timed span (timestamps from
        ``time.perf_counter()``) into a trace by id -- the cross-thread
        fan-out primitive. Passing the same ``span_id`` into several
        traces makes them share one batch-level span. If the trace is not
        live (e.g. WAL replay of a trace from a previous process) the
        span is retained as a standalone single-span trace. Returns the
        span id, or None when disabled."""
        if not self.enabled:
            return None
        record = SpanRecord(
            trace_id=trace_id,
            span_id=span_id or new_span_id(),
            parent_id=parent_id,
            op=op,
            start_s=start_pc + _PC_TO_WALL,
            duration_s=max(end_pc - start_pc, 0.0),
            status=status,
            attrs=attrs or {},
            thread=threading.current_thread().name,
        )
        if not self._attach(record) and self.on_spans is not None:
            # attached to a live trace -> bridged at that trace's root
            # finish; retained standalone -> bridge it now
            try:
                self.on_spans([record])
            except Exception:
                logger.warning("span bridge failed", exc_info=True)
        return record.span_id

    def live_spans(self, trace_id: str) -> "list | None":
        """The live span list for ``trace_id``, or None. A batch tier
        captures this AT SUBMIT (while the request's root is guaranteed
        open) and hands it to ``record_fanout`` AFTER resolving the
        request -- appends to the captured list still land in the right
        trace even once the root has finished, because retention keeps
        the SAME list object."""
        if not self.enabled:
            return None
        return self._live.get(trace_id)

    def record_fanout(
        self,
        items: "list[tuple[tuple[str, str], float, list | None]]",
        exec_ops: "list[tuple]",
        attrs: dict | None = None,
        status: str = "ok",
        queue_op: str = "batch.queue_wait",
        bridge_queue: bool = False,
        extra: "tuple[str, str | None, list | None] | None" = None,
    ) -> None:
        """The batch-tier fan-out, amortized and OFF the latency path:
        for every coalesced request ``((trace_id, parent_id),
        enqueued_pc, live_spans(trace_id))`` write one per-request
        ``queue_op`` span plus the shared batch-level spans ``(op,
        start_pc, end_pc[, attrs])`` -- each with ONE span id shared
        across the whole batch. This runs on the flusher thread after
        the batch's futures resolve, appending into the span lists
        captured at submit: no tracer lock, no liveness race with roots
        that already finished. Per-span ``record_span`` before
        resolution cost ~100us of ack latency per request (lock
        round-trips plus flusher-thread work ahead of the future
        wake-up). The per-trace copies are marked ``bridged``
        (queue-wait aggregates natively as
        ``pio_serving_batch_queue_wait_seconds``, or set
        ``bridge_queue`` to histogram it once per request); the shared
        batch-level spans bridge into ``pio_span_duration_seconds{op}``
        exactly ONCE PER BATCH here, so dashboards can trend
        assemble/execute (or one physical WAL fsync) without one batch
        counting N times. ``extra`` -- ``(trace_id, parent_id,
        live_spans)`` -- additionally lands the shared spans in a
        flusher-owned trace (the ingest writer's commit root)."""
        if not self.enabled or not exec_ops or (not items and extra is None):
            return
        shared = [
            SpanRecord(
                trace_id=items[0][0][0] if items else extra[0],
                span_id=new_span_id(),
                parent_id=None,
                op=e[0],
                start_s=e[1] + _PC_TO_WALL,
                duration_s=max(e[2] - e[1], 0.0),
                status=status,
                attrs=(e[3] if len(e) > 3 else attrs) or {},
                bridged=True,
            )
            for e in exec_ops
        ]
        bridge = list(shared)
        flush_pc = exec_ops[0][1]

        def _copies(trace_id: str, parent_id: "str | None") -> list:
            return [SpanRecord(
                trace_id=trace_id,
                span_id=rep.span_id,
                parent_id=parent_id,
                op=rep.op,
                start_s=rep.start_s,
                duration_s=rep.duration_s,
                status=status,
                attrs=rep.attrs,
                bridged=True,
            ) for rep in shared]

        def _land(records: list, spans: "list | None") -> None:
            if spans is not None:
                if len(spans) < MAX_SPANS_PER_TRACE:
                    spans.extend(records)
            else:
                for record in records:
                    self._attach(record)

        for (trace_id, parent_id), enqueued_pc, spans in items:
            queue_rec = SpanRecord(
                trace_id=trace_id,
                span_id=new_span_id(),
                parent_id=parent_id,
                op=queue_op,
                start_s=enqueued_pc + _PC_TO_WALL,
                duration_s=max(flush_pc - enqueued_pc, 0.0),
                bridged=True,
            )
            if bridge_queue:
                bridge.append(queue_rec)
            _land([queue_rec] + _copies(trace_id, parent_id), spans)
        if extra is not None:
            _land(_copies(extra[0], extra[1]), extra[2])
        if self.on_spans is not None:
            try:
                self.on_spans(bridge)
            except Exception:
                logger.warning("span bridge failed", exc_info=True)

    def _attach(self, record: SpanRecord) -> bool:
        """Append a finished span to its live trace (returns True), or
        retain it as a standalone trace when none is live (e.g. WAL
        replay of a trace from a previous process; returns False). The
        live-trace path is LOCK-FREE: ``dict.get`` and ``list.append``
        are each atomic under the GIL, entries are only ever removed by
        the root's finish (which retains the SAME list object, so a
        straggler append still lands in the retained trace), and the
        spans-per-trace cap is deliberately approximate -- two racing
        appends at the cap cost two extra records, not corruption."""
        spans = self._live.get(record.trace_id)
        if spans is not None:
            if len(spans) < MAX_SPANS_PER_TRACE:
                spans.append(record)
            return True
        with self._lock:
            self._retain_locked(record, [record])
        return False

    # -- retention ----------------------------------------------------------
    def _span_finished(self, record: SpanRecord, root: bool) -> None:
        if not root:
            # the serving hot path: every child span in every handler
            # thread lands here, so it must not take the tracer lock; a
            # live attach defers the bridge to the trace's root finish
            if self._attach(record):
                return
            bridge, slow_entry = [record], None
        else:
            slow_entry = None
            with self._lock:
                spans = self._live.pop(record.trace_id, [])
                spans.append(record)
                self._retain_locked(record, spans)
                slow_s = self._slow_log_threshold(record.op)
                if slow_s is not None and record.duration_s >= slow_s:
                    slow_entry = (record, spans)
            # slice-copy: a straggler child append (lock-free _attach)
            # must not resize the list while the bridge iterates it
            bridge = [s for s in spans[:] if not s.bridged]
        if self.on_spans is not None:
            try:
                self.on_spans(bridge)
            except Exception:
                logger.warning("span bridge failed", exc_info=True)
        if slow_entry is not None:
            # exactly one record per slow trace, emitted outside the lock
            root_rec, spans = slow_entry
            logger.warning(
                "slow op: %s took %.1f ms (trace=%s, %d span(s): %s)",
                root_rec.op,
                root_rec.duration_s * 1000.0,
                root_rec.trace_id,
                len(spans),
                ", ".join(
                    f"{s.op}={s.duration_s * 1000.0:.1f}ms"
                    for s in spans[:8]
                ),
            )

    def _trace_obj(self, root: SpanRecord, spans: list) -> dict:
        """Serialize one retained trace -- called at snapshot time only;
        the hot path retains raw records."""
        # slice-copy first: a straggler child finishing after its root
        # appends to this very list lock-free (see _attach)
        spans = spans[:]
        start = min(s.start_s for s in spans)
        status = "error" if any(s.status == "error" for s in spans) else "ok"
        return {
            "traceId": root.trace_id,
            "op": root.op,
            "startTime": time.strftime(
                "%Y-%m-%dT%H:%M:%S", time.gmtime(start)
            ) + f".{int((start % 1) * 1000):03d}Z",
            "durationMs": round(root.duration_s * 1000.0, 3),
            "status": status,
            "spans": [s.to_json_obj(start) for s in spans],
        }

    def _retain_locked(self, root: SpanRecord, spans: list) -> None:
        entry = (root, spans)
        self._recent.append(entry)
        if root.status == "error" or any(s.status == "error" for s in spans):
            self._errors.append(entry)
        self._seq += 1
        heap_entry = (root.duration_s, self._seq, entry)
        if len(self._slow) < self._slow_cap:
            heapq.heappush(self._slow, heap_entry)
        elif self._slow and heap_entry > self._slow[0]:
            heapq.heapreplace(self._slow, heap_entry)

    def _begin_trace(self, trace_id: str) -> None:
        if len(self._live) >= self._live_cap:
            # drop the oldest live trace (dict preserves insertion order):
            # a leaked root must not grow memory forever
            self._live.pop(next(iter(self._live)), None)
        self._live.setdefault(trace_id, [])

    # -- slow-op log --------------------------------------------------------
    def set_slow_threshold(self, op_prefix: str, seconds: float | None) -> None:
        """Log one summary line for any finished trace whose root op
        starts with ``op_prefix`` and whose duration >= ``seconds``
        (None removes the threshold)."""
        with self._lock:
            self._slow_log = [
                (p, s) for p, s in self._slow_log if p != op_prefix
            ]
            if seconds is not None:
                self._slow_log.append((op_prefix, float(seconds)))
                self._slow_log.sort(key=lambda e: -len(e[0]))  # longest first

    def _slow_log_threshold(self, op: str) -> float | None:
        for prefix, seconds in self._slow_log:
            if op.startswith(prefix):
                return seconds
        return None

    # -- exposure -----------------------------------------------------------
    def snapshot(
        self,
        op: str | None = None,
        min_ms: float | None = None,
        limit: int = 50,
    ) -> dict:
        """The ``/traces.json`` payload: recent + slowest + error traces,
        filterable by root-op substring and minimum duration. Retained
        entries are raw records; serialization happens here (poll rate),
        never on the request path."""
        with self._lock:
            recent = list(self._recent)
            slow = [e for _, _, e in sorted(self._slow, reverse=True)]
            errors = list(self._errors)

        def keep(root: SpanRecord) -> bool:
            if op and op not in root.op:
                return False
            if min_ms is not None and root.duration_s * 1000.0 < min_ms:
                return False
            return True

        def serialize(entries) -> list[dict]:
            return [
                self._trace_obj(root, spans)
                for root, spans in entries
                if keep(root)
            ][:limit]

        return {
            "enabled": self.enabled,
            "recent": serialize(reversed(recent)),
            "slowest": serialize(slow),
            "errors": serialize(reversed(errors)),
        }


#: the always-off tracer: code paths take ``tracer or NULL_TRACER`` and
#: instrument unconditionally without None checks
NULL_TRACER = Tracer(enabled=False)

_global_lock = threading.Lock()
_global: Tracer | None = None


def global_tracer() -> Tracer:
    """Process-wide tracer for code that runs outside any service router
    (training loops, CLI verbs). Enabled unless ``PIO_TRACING=0``; spans
    bridge into ``utils.metrics.global_registry()``."""
    global _global
    with _global_lock:
        if _global is None:
            from predictionio_tpu.utils.metrics import global_registry, span_bridge

            _global = Tracer(
                enabled=tracing_enabled_default(),
                on_spans=span_bridge(global_registry()),
            )
        return _global


def tracing_enabled_default() -> bool:
    """The process default: on, unless ``PIO_TRACING=0`` opts out."""
    return os.environ.get("PIO_TRACING", "1") != "0"


#: default head-sampling rate for service routers: 1-in-8 headerless
#: roots. Full tracing costs ~0.4 ms/request of python, ~10% qps on the
#: GIL-bound 2-core box; 1/8 lands it under the 2% acceptance bar while
#: /traces.json stays live even at dev-traffic rates
DEFAULT_SAMPLE = 0.125


def tracing_sample_default() -> float:
    """Service-router sampling default: ``PIO_TRACE_SAMPLE`` (0..1, e.g.
    ``1`` = trace everything, ``0.125`` = 1-in-8 headerless roots), falling
    back to :data:`DEFAULT_SAMPLE`. Malformed values fall back rather than
    erroring -- a bad env var must not take a service down."""
    try:
        rate = float(os.environ.get("PIO_TRACE_SAMPLE", DEFAULT_SAMPLE))
    except ValueError:
        return DEFAULT_SAMPLE
    return min(max(rate, 0.0), 1.0)
