"""Observability substrate: tracing, structured logs, training telemetry.

The aggregate Prometheus layer (``utils/metrics``) answers "how is the
service doing on average"; this package answers "where did THIS request
spend its 12 ms" and "what is the achieved bandwidth of THIS training
step" -- the per-operation visibility 1612.01437 shows dominating
distributed-ML debugging, rebuilt without the Spark UI:

- ``obs.trace``   -- low-overhead span tracer (W3C ``traceparent`` in/out,
  thread-local context, bounded ring buffers with tail-based keep for
  slow/error traces); every service exposes ``GET /traces.json``.
- ``obs.logs``    -- shared log formatters; ``--log-format json`` emits
  one JSON object per record with ``trace_id``/``span_id`` when a span is
  active.
- ``obs.telemetry`` -- per-step training journal (wall time, edges/sec,
  modeled-bytes achieved GB/s, recompile count) behind
  ``pio train --profile``.
- ``obs.top``     -- the ``pio top`` live terminal view over ``/metrics``
  + ``/traces.json``.
"""

from predictionio_tpu.obs.trace import (  # noqa: F401
    NULL_TRACER,
    Tracer,
    current_context,
    format_traceparent,
    global_tracer,
    parse_traceparent,
)
