"""DASE component base classes.

Behavioral model: reference ``core/.../core/Base*.scala`` +
``core/.../controller/{PDataSource,LDataSource,PPreparator,LPreparator,
PAlgorithm,P2LAlgorithm,LAlgorithm,LServing,Params,SanityCheck,
PersistentModel}.scala`` (apache/predictionio layout, unverified --
SURVEY.md section 2.3 #16-#22).

Key redesign decisions (TPU-first, not a translation):

- One ``DataSource``/``Preparator``/``Algorithm``/``Serving`` hierarchy
  instead of the P/L/P2L triplets: the P-vs-L split existed to pick RDD vs
  driver-local execution; here data is columnar on the host and compute is
  jitted on the mesh, so the split is meaningless. ``TPUAlgorithm`` is the
  ``PAlgorithm``-analogue whose ``train`` is expected to run pjit'd
  computations over the context's mesh.
- ``Params`` are plain dicts by convention (engine.json JSON objects),
  wrapped in an attribute-access helper. No reflection-based Doer
  construction: components are constructed with their params directly.
- Model persistence matrix (reference Engine.prepareDeploy semantics,
  SURVEY.md section 3.2): a model implementing :class:`PersistentModel`
  saves/loads itself; otherwise the model is pickled into the Models blob
  store; an algorithm declaring ``persist_model = False`` is retrained on
  deploy.
"""

from __future__ import annotations

import abc
from typing import Any, Generic, Mapping, Sequence, TypeVar


class Params(dict):
    """Engine-component parameters: a dict with attribute access.

    Mirrors the role of the reference ``Params`` marker trait while staying
    JSON-native (engine.json fragments deserialize straight into it).
    """

    def __getattr__(self, name: str) -> Any:
        try:
            return self[name]
        except KeyError:
            raise AttributeError(name) from None

    def get_or(self, name: str, default: Any) -> Any:
        return self.get(name, default)


class EmptyParams(Params):
    pass


TD = TypeVar("TD")  # TrainingData
PD = TypeVar("PD")  # PreparedData
Q = TypeVar("Q")    # Query
P = TypeVar("P")    # PredictedResult
A = TypeVar("A")    # ActualResult
M = TypeVar("M")    # Model


class EvalInfo(Params):
    """Per-fold metadata returned by ``DataSource.read_eval``."""


class Component:
    """Shared construction: every DASE component takes its params dict."""

    def __init__(self, params: Mapping[str, Any] | None = None):
        self.params = params if isinstance(params, Params) else Params(params or {})


class SanityCheck(abc.ABC):
    """Optional post-stage hook (reference SanityCheck trait): raise to abort."""

    @abc.abstractmethod
    def sanity_check(self) -> None: ...


class DataSource(Component, Generic[TD, Q, A]):
    """Reads TrainingData from the event store.

    ``read_training`` is the train path; ``read_eval`` yields
    ``(training_data, eval_info, [(query, actual)])`` folds for evaluation
    (reference PDataSource.readTraining/readEval).
    """

    @abc.abstractmethod
    def read_training(self, ctx) -> TD: ...

    def read_eval(self, ctx) -> list[tuple[TD, EvalInfo, list[tuple[Q, A]]]]:
        raise NotImplementedError(
            f"{type(self).__name__} does not implement read_eval; "
            "evaluation is unavailable for this engine"
        )

    def read_replay(self, ctx, spec):
        """Time-travel replay split (``pio eval --replay``): train on
        events strictly before the boundary, hold out interactions
        at-or-after it. ``spec`` is an ``eval.split.SplitSpec``; returns
        an ``eval.split.ReplayFold`` whose pairs are per-held-out-user
        ``(query, [actual item ids])``. Default: unsupported."""
        raise NotImplementedError(
            f"{type(self).__name__} does not implement read_replay; "
            "`pio eval --replay` is unavailable for this engine"
        )

    def online_handle(self):
        """Describe this datasource's interaction scan for the
        continuous-learning loop (``pio retrain --follow``): a
        ``models._streaming.StreamingHandle``-shaped object carrying
        app/channel/event-name/rating-key identity, or None (default) when
        the datasource cannot be followed online."""
        return None


class Preparator(Component, Generic[TD, PD]):
    @abc.abstractmethod
    def prepare(self, ctx, training_data: TD) -> PD: ...


class IdentityPreparator(Preparator):
    """Pass-through preparator (reference IdentityPreparator)."""

    def prepare(self, ctx, training_data):
        return training_data


class Algorithm(Component, Generic[PD, M, Q, P]):
    """Algorithm contract: train on prepared data, answer queries.

    ``persist_model = False`` opts into retrain-on-deploy (the reference's
    PAlgorithm-without-persistence path).
    """

    persist_model: bool = True

    #: True when :meth:`fold_in` is implemented -- the continuous-learning
    #: loop escalates to a full retrain for algorithms that are not
    supports_fold_in: bool = False

    @abc.abstractmethod
    def train(self, ctx, prepared_data: PD) -> M: ...

    def fold_in(self, model: M, delta) -> M | None:
        """Incrementally absorb a delta window (``online.foldin.
        FoldinDelta``) into ``model``, returning a NEW model (the swap
        protocol needs immutability -- never mutate the argument) or None
        when the window holds nothing to absorb. May raise
        ``online.foldin.StalenessExceeded`` to demand a full retrain."""
        raise NotImplementedError(
            f"{type(self).__name__} does not implement fold_in"
        )

    @abc.abstractmethod
    def predict(self, model: M, query: Q) -> P: ...

    def batch_predict(self, model: M, queries: Sequence[tuple[int, Q]]) -> list[tuple[int, P]]:
        """Default: loop predict. Override with a vectorized/jitted version."""
        return [(qid, self.predict(model, q)) for qid, q in queries]

    def warm_up(self, model: M) -> None:
        """Called once at deploy after the model is rehydrated. Override to
        build serving caches (device-resident tables, compiled programs) so
        the first query doesn't pay for them. Must be safe to skip."""

    def shard_model(self, model: M, shard: int, num_shards: int) -> M:
        """Restrict ``model`` to the user partition ``serving.shardmap.
        shard_of(user, num_shards) == shard`` owns, returning a NEW model
        (the swap protocol needs immutability). Item-side and other
        replicated state must stay intact: every shard answers userless /
        item-only queries identically, and a query routed to the owning
        shard must be answered byte-for-byte as the unsharded model would.

        Default: return the model unchanged (full replication) -- correct
        for any algorithm, it just forgoes the memory win.
        """
        return model

    # -- query/result wire serde (CustomQuerySerializer parity role) --------
    def query_from_json(self, obj: Any) -> Q:
        """Deserialize a /queries.json body. Default: pass the dict through."""
        return obj

    def result_to_json(self, prediction: P) -> Any:
        """Serialize a prediction for the wire. Default: JSON-able as-is,
        with dataclass support."""
        import dataclasses

        if dataclasses.is_dataclass(prediction) and not isinstance(prediction, type):
            return dataclasses.asdict(prediction)
        return prediction


class TPUAlgorithm(Algorithm[PD, M, Q, P]):
    """Marker base for algorithms whose train() runs on the device mesh.

    The workflow guarantees ``ctx.mesh`` is populated before ``train`` is
    called (mesh of 1 on CPU dev machines; ICI mesh on a pod). This is the
    BASELINE.json "TPUAlgorithm base whose train() is a pjit'd function over
    an ICI mesh".
    """

    @staticmethod
    def mesh_or_none(ctx):
        """``ctx.mesh``, degrading to None (unsharded training) when mesh
        construction fails -- with the failure logged, not swallowed: a
        misconfigured pod coordinator should not silently train on one
        host. The common benign case is a context with no devices at all
        (pure-host tests)."""
        try:
            return ctx.mesh
        except Exception:
            import logging

            logging.getLogger("pio.controller").warning(
                "mesh unavailable; training unsharded", exc_info=True
            )
            return None


class Serving(Component, Generic[Q, P]):
    @abc.abstractmethod
    def serve(self, query: Q, predictions: Sequence[P]) -> P: ...

    def serve_batch(
        self, queries: Sequence[Q], predictions: Sequence[Sequence[P]]
    ) -> list[P]:
        """Combine per-algorithm predictions for a whole micro-batch.

        ``predictions[i]`` holds query ``i``'s per-algorithm predictions
        (same shape ``serve`` receives). Default: loop ``serve``. Override
        when the combination itself vectorizes; the query server falls
        back to per-query ``serve`` if this raises, so an override only
        needs to handle the all-good path.
        """
        return [
            self.serve(q, preds) for q, preds in zip(queries, predictions)
        ]


class PersistentModel(abc.ABC):
    """User-managed model persistence (reference PersistentModel[+Loader]).

    ``save`` returns True if the model was persisted; returning False falls
    back to the pickled-blob path. ``load`` is a classmethod resolved on
    deploy.
    """

    @abc.abstractmethod
    def save(self, instance_id: str, params: Params) -> bool: ...

    @classmethod
    @abc.abstractmethod
    def load(cls, instance_id: str, params: Params) -> "PersistentModel": ...


class EngineFactory:
    """Engines are built by factory callables named in engine.json
    (``engineFactory``); subclassing this class is optional sugar."""

    def apply(self):  # pragma: no cover - template-defined
        raise NotImplementedError


def component_name(obj: Any) -> str:
    cls = obj if isinstance(obj, type) else type(obj)
    return f"{cls.__module__}.{cls.__qualname__}"
