"""Engine: the central class tying DASE components + params together.

Behavioral model: reference ``core/.../controller/Engine.scala`` +
``EngineParams.scala`` (apache/predictionio layout, unverified -- SURVEY.md
section 2.3 #17, section 3.1/3.2 call stacks). Responsibilities kept:

- ``train(ctx, engine_params)``: D -> P -> per-algorithm train -> models
- ``eval(ctx, engine_params)``: k-fold read_eval -> train -> batch predict
  -> (query, prediction, actual) triples per fold
- ``prepare_deploy(ctx, engine_params, instance_id)``: model rehydration
  matrix (PersistentModel load | blob unpickle | retrain-on-deploy)
- serialization of models into the Models blob store

The class-registry role of EngineFactory reflection is played by dotted-path
resolution in ``predictionio_tpu.workflow.json_extractor``.
"""

from __future__ import annotations

import io
import logging
import pickle
from dataclasses import dataclass, field
from typing import Any, Mapping, Sequence, Type

from predictionio_tpu.controller.base import (
    Algorithm,
    DataSource,
    Params,
    PersistentModel,
    Preparator,
    SanityCheck,
    Serving,
    component_name,
)
from predictionio_tpu.controller.serving import FirstServing

logger = logging.getLogger("pio.engine")


@dataclass
class EngineParams:
    """Deserialized engine.json parameter block (reference EngineParams)."""

    data_source_params: Params = field(default_factory=Params)
    preparator_params: Params = field(default_factory=Params)
    algorithm_params_list: list[tuple[str, Params]] = field(default_factory=list)
    serving_params: Params = field(default_factory=Params)

    @classmethod
    def from_json_obj(cls, obj: Mapping[str, Any]) -> "EngineParams":
        algorithms = [
            (a.get("name", "default"), Params(a.get("params", {})))
            for a in obj.get("algorithms", [{"name": "default", "params": {}}])
        ]
        return cls(
            data_source_params=Params(obj.get("datasource", {}).get("params", {})),
            preparator_params=Params(obj.get("preparator", {}).get("params", {})),
            algorithm_params_list=algorithms,
            serving_params=Params(obj.get("serving", {}).get("params", {})),
        )

    def to_json_obj(self) -> dict[str, Any]:
        return {
            "datasource": {"params": dict(self.data_source_params)},
            "preparator": {"params": dict(self.preparator_params)},
            "algorithms": [
                {"name": name, "params": dict(params)}
                for name, params in self.algorithm_params_list
            ],
            "serving": {"params": dict(self.serving_params)},
        }


class Engine:
    """Binds DASE component classes; instantiates them per run with params."""

    def __init__(
        self,
        data_source_class: Type[DataSource],
        preparator_class: Type[Preparator],
        algorithm_class_map: Mapping[str, Type[Algorithm]],
        serving_class: Type[Serving] = FirstServing,
    ):
        self.data_source_class = data_source_class
        self.preparator_class = preparator_class
        self.algorithm_class_map = dict(algorithm_class_map)
        self.serving_class = serving_class

    # -- construction helpers ----------------------------------------------
    def _algorithms(self, engine_params: EngineParams) -> list[Algorithm]:
        algorithms = []
        for name, params in engine_params.algorithm_params_list:
            if name not in self.algorithm_class_map:
                raise KeyError(
                    f"algorithm {name!r} not registered in engine"
                    f" (available: {sorted(self.algorithm_class_map)})"
                )
            algorithms.append(self.algorithm_class_map[name](params))
        if not algorithms:
            raise ValueError("engine_params names no algorithms")
        return algorithms

    def serving(self, engine_params: EngineParams) -> Serving:
        return self.serving_class(engine_params.serving_params)

    @staticmethod
    def _maybe_sanity_check(stage: str, obj: Any, skip: bool) -> None:
        if not skip and isinstance(obj, SanityCheck):
            logger.info("sanity check: %s", stage)
            obj.sanity_check()

    # -- train --------------------------------------------------------------
    def train(
        self,
        ctx,
        engine_params: EngineParams,
        skip_sanity_check: bool = False,
    ) -> list[Any]:
        import time

        timings = getattr(ctx, "timings", {})

        t0 = time.perf_counter()
        data_source = self.data_source_class(engine_params.data_source_params)
        training_data = data_source.read_training(ctx)
        timings["read"] = time.perf_counter() - t0
        self._maybe_sanity_check("training data", training_data, skip_sanity_check)

        t0 = time.perf_counter()
        preparator = self.preparator_class(engine_params.preparator_params)
        prepared_data = preparator.prepare(ctx, training_data)
        timings["prepare"] = time.perf_counter() - t0
        self._maybe_sanity_check("prepared data", prepared_data, skip_sanity_check)

        models = []
        for algorithm, (name, _) in zip(
            self._algorithms(engine_params), engine_params.algorithm_params_list
        ):
            logger.info("training algorithm %r (%s)", name, component_name(algorithm))
            t0 = time.perf_counter()
            model = algorithm.train(ctx, prepared_data)
            timings[f"train[{name}]"] = time.perf_counter() - t0
            self._maybe_sanity_check(f"model[{name}]", model, skip_sanity_check)
            models.append(model)
        if timings:
            logger.info(
                "stage timings: %s",
                ", ".join(f"{k}={v:.3f}s" for k, v in timings.items()),
            )
        return models

    # -- serialization + deploy rehydration ---------------------------------
    def serialize_models(
        self, ctx, engine_params: EngineParams, instance_id: str, models: Sequence[Any]
    ) -> bytes:
        """Encode the per-algorithm persistence choice into one blob."""
        entries = []
        for model, algorithm, (name, params) in zip(
            models, self._algorithms(engine_params), engine_params.algorithm_params_list
        ):
            if isinstance(model, PersistentModel):
                if model.save(instance_id, params):
                    entries.append(("persistent", component_name(model)))
                    continue
            if not algorithm.persist_model:
                entries.append(("retrain", None))
                continue
            buf = io.BytesIO()
            pickle.dump(model, buf, protocol=pickle.HIGHEST_PROTOCOL)
            entries.append(("pickle", buf.getvalue()))
        return pickle.dumps(entries, protocol=pickle.HIGHEST_PROTOCOL)

    def prepare_deploy(
        self,
        ctx,
        engine_params: EngineParams,
        instance_id: str,
        model_blob: bytes | None,
        shard: int | None = None,
        num_shards: int = 1,
    ) -> list[Any]:
        """Rehydrate per-algorithm models for serving (reference semantics:
        PersistentModelLoader -> load; pickled blob -> deserialize;
        persist_model=False -> retrain now). With ``shard``/``num_shards``
        set, the rehydrated models are partitioned through
        :meth:`shard_models` BEFORE warm-up, so serving caches are built
        against the shard's slice, never the full table."""
        algorithms = self._algorithms(engine_params)
        entries = pickle.loads(model_blob) if model_blob else [("retrain", None)] * len(
            algorithms
        )
        if len(entries) != len(algorithms):
            raise ValueError(
                f"model blob has {len(entries)} entries but engine_params names"
                f" {len(algorithms)} algorithms -- retrain required"
            )
        retrained: list[Any] | None = None
        models = []
        for i, (entry, algorithm, (name, params)) in enumerate(
            zip(entries, algorithms, engine_params.algorithm_params_list)
        ):
            kind, payload = entry
            if kind == "persistent":
                model_cls = _resolve_class(payload)
                models.append(model_cls.load(instance_id, params))
            elif kind == "pickle":
                models.append(pickle.loads(payload))
            elif kind == "retrain":
                if retrained is None:
                    logger.info("retrain-on-deploy: running engine.train")
                    retrained = self.train(ctx, engine_params, skip_sanity_check=True)
                models.append(retrained[i])
            else:  # pragma: no cover - corrupted blob
                raise ValueError(f"unknown model persistence kind {kind!r}")
        if shard is not None and num_shards > 1:
            models = self.shard_models(engine_params, models, shard, num_shards)
        for algorithm, model in zip(algorithms, models):
            # serving caches (device-resident scorers, compiled programs)
            # build at deploy time, not on the unlucky first query. STRICTLY
            # best-effort: a model trained for an accelerator may deploy
            # onto a CPU-fallback host (wedged plugin) where the cache
            # build raises -- serving must still come up; the failing path
            # surfaces per-query instead
            try:
                algorithm.warm_up(model)
            except Exception:
                logger.warning(
                    "warm_up failed for %s; first queries will build serving"
                    " caches lazily",
                    type(algorithm).__name__,
                    exc_info=True,
                )
        return models

    def shard_models(
        self,
        engine_params: EngineParams,
        models: Sequence[Any],
        shard: int,
        num_shards: int,
    ) -> list[Any]:
        """Per-algorithm :meth:`Algorithm.shard_model` over rehydrated
        models -- the deploy-side fallback when a registry generation has
        no per-shard blobs, and the publish-side partition step when the
        continuous-learning loop writes them."""
        if num_shards <= 1:
            return list(models)
        if not (0 <= shard < num_shards):
            raise ValueError(
                f"shard {shard} out of range for num_shards={num_shards}"
            )
        return [
            algorithm.shard_model(model, shard, num_shards)
            for algorithm, model in zip(self._algorithms(engine_params), models)
        ]

    # -- eval ---------------------------------------------------------------
    def eval(
        self, ctx, engine_params: EngineParams
    ) -> list[tuple[Any, list[tuple[Any, Any, Any]]]]:
        """Run evaluation folds.

        Returns ``[(eval_info, [(query, prediction, actual), ...]), ...]``.
        """
        data_source = self.data_source_class(engine_params.data_source_params)
        preparator = self.preparator_class(engine_params.preparator_params)
        serving = self.serving(engine_params)
        folds = data_source.read_eval(ctx)
        results = []
        for training_data, eval_info, qa_pairs in folds:
            prepared_data = preparator.prepare(ctx, training_data)
            algorithms = self._algorithms(engine_params)
            models = [a.train(ctx, prepared_data) for a in algorithms]
            indexed = list(enumerate(q for q, _ in qa_pairs))
            per_algo = [dict(a.batch_predict(m, indexed)) for a, m in zip(algorithms, models)]
            triples = []
            for qid, (query, actual) in enumerate(qa_pairs):
                predictions = [pa[qid] for pa in per_algo]
                triples.append((query, serving.serve(query, predictions), actual))
            results.append((eval_info, triples))
        return results


def _resolve_class(dotted: str):
    from predictionio_tpu.workflow.json_extractor import resolve_dotted

    return resolve_dotted(dotted)
