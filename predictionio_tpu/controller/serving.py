"""Stock serving combinators (reference FirstServing/AverageServing,
SURVEY.md section 2.3 #20)."""

from __future__ import annotations

from typing import Sequence

from predictionio_tpu.controller.base import Serving


class FirstServing(Serving):
    """Return the first algorithm's prediction."""

    def serve(self, query, predictions: Sequence):
        if not predictions:
            raise ValueError("FirstServing received no predictions")
        return predictions[0]

    def serve_batch(self, queries, predictions: Sequence[Sequence]):
        # the dominant combinator on the serving hot path: one list
        # comprehension for the whole micro-batch, no per-query dispatch
        if any(not p for p in predictions):
            raise ValueError("FirstServing received no predictions")
        return [p[0] for p in predictions]


class AverageServing(Serving):
    """Average numeric predictions across algorithms.

    Works on plain numbers or on dicts with a numeric field per key.
    """

    def serve(self, query, predictions: Sequence):
        if not predictions:
            raise ValueError("AverageServing received no predictions")
        first = predictions[0]
        if isinstance(first, (int, float)):
            return sum(predictions) / len(predictions)
        if isinstance(first, dict):
            keys = set(first)
            out = {}
            for k in keys:
                values = [p[k] for p in predictions if isinstance(p.get(k), (int, float))]
                out[k] = sum(values) / len(values) if values else first[k]
            return out
        raise TypeError(
            f"AverageServing cannot average predictions of type {type(first).__name__}"
        )
