"""L3 Controller API: the DASE abstractions engine templates implement.

Behavioral model: reference ``core/.../controller/`` (apache/predictionio
layout, unverified -- SURVEY.md section 2.3). The DASE lifecycle and its
contracts are kept; the Spark-specific split (PAlgorithm/P2LAlgorithm/
LAlgorithm over RDDs) collapses into a single :class:`TPUAlgorithm` whose
``train`` receives a :class:`~predictionio_tpu.workflow.context.RuntimeContext`
carrying the JAX device mesh -- the TPU-native replacement for SparkContext
(BASELINE.json north star).
"""

from predictionio_tpu.controller.base import (
    Algorithm,
    DataSource,
    EmptyParams,
    EngineFactory,
    EvalInfo,
    IdentityPreparator,
    Params,
    PersistentModel,
    Preparator,
    SanityCheck,
    Serving,
    TPUAlgorithm,
)
from predictionio_tpu.controller.engine import Engine, EngineParams
from predictionio_tpu.controller.serving import AverageServing, FirstServing
from predictionio_tpu.controller.metrics import (
    AverageMetric,
    Evaluation,
    EngineParamsGenerator,
    Metric,
    MetricEvaluator,
    OptionAverageMetric,
    StdevMetric,
    SumMetric,
    ZeroMetric,
)

__all__ = [
    "Algorithm",
    "AverageMetric",
    "AverageServing",
    "DataSource",
    "EmptyParams",
    "Engine",
    "EngineFactory",
    "EngineParams",
    "EngineParamsGenerator",
    "EvalInfo",
    "Evaluation",
    "FirstServing",
    "IdentityPreparator",
    "Metric",
    "MetricEvaluator",
    "OptionAverageMetric",
    "Params",
    "PersistentModel",
    "Preparator",
    "SanityCheck",
    "Serving",
    "StdevMetric",
    "SumMetric",
    "TPUAlgorithm",
    "ZeroMetric",
]
