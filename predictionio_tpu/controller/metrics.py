"""Evaluation API: Metric combinators + MetricEvaluator.

Behavioral model: reference ``core/.../controller/{Evaluation,Metric,
MetricEvaluator}.scala`` (apache/predictionio layout, unverified -- SURVEY.md
section 2.3 #23): Metric[EI,Q,P,A,R] with ``calculate``; Average/
OptionAverage/Stdev/Sum/Zero combinators; MetricEvaluator runs an
EngineParams grid and pretty-prints a leaderboard.
"""

from __future__ import annotations

import abc
import json
import math
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence

from predictionio_tpu.controller.engine import Engine, EngineParams


class Metric(abc.ABC):
    """Computes a score over per-fold (query, prediction, actual) triples."""

    #: larger is better by default; metrics may flip this
    higher_is_better: bool = True

    @abc.abstractmethod
    def calculate(
        self, per_fold: Sequence[tuple[Any, Sequence[tuple[Any, Any, Any]]]]
    ) -> float: ...

    def header(self) -> str:
        return type(self).__name__

    def compare(self, a: float, b: float) -> int:
        if a == b:
            return 0
        better = a > b if self.higher_is_better else a < b
        return 1 if better else -1


class _PointwiseMetric(Metric):
    """Base for metrics that score each (q, p, a) triple independently."""

    def __init__(self, score: Callable[[Any, Any, Any, Any], Optional[float]] | None = None):
        if score is not None:
            self._score = score

    def score(self, eval_info, query, prediction, actual) -> Optional[float]:
        return self._score(eval_info, query, prediction, actual)

    def _all_scores(self, per_fold) -> list[Optional[float]]:
        return [
            self.score(eval_info, q, p, a)
            for eval_info, triples in per_fold
            for q, p, a in triples
        ]


class AverageMetric(_PointwiseMetric):
    """Mean of per-triple scores (None scores count as 0 -- use
    OptionAverageMetric to skip them)."""

    def calculate(self, per_fold) -> float:
        scores = [s if s is not None else 0.0 for s in self._all_scores(per_fold)]
        return sum(scores) / len(scores) if scores else float("nan")


class OptionAverageMetric(_PointwiseMetric):
    """Mean of non-None per-triple scores."""

    def calculate(self, per_fold) -> float:
        scores = [s for s in self._all_scores(per_fold) if s is not None]
        return sum(scores) / len(scores) if scores else float("nan")


class StdevMetric(_PointwiseMetric):
    """Population standard deviation of per-triple scores."""

    def calculate(self, per_fold) -> float:
        scores = [s if s is not None else 0.0 for s in self._all_scores(per_fold)]
        if not scores:
            return float("nan")
        mean = sum(scores) / len(scores)
        return math.sqrt(sum((s - mean) ** 2 for s in scores) / len(scores))


class SumMetric(_PointwiseMetric):
    """Sum of per-triple scores."""

    def calculate(self, per_fold) -> float:
        return float(sum(s for s in self._all_scores(per_fold) if s is not None))


class ZeroMetric(Metric):
    """Always 0 (placeholder, reference parity)."""

    def calculate(self, per_fold) -> float:
        return 0.0


@dataclass
class Evaluation:
    """Binds an engine to metrics (reference Evaluation).

    ``metric`` drives parameter selection; ``metrics`` (optional extras) are
    reported alongside.
    """

    engine: Engine
    metric: Metric
    metrics: list[Metric] = field(default_factory=list)


class EngineParamsGenerator:
    """Supplies the grid of candidate EngineParams (reference parity)."""

    def __init__(self, engine_params_list: Sequence[EngineParams]):
        self.engine_params_list = list(engine_params_list)


@dataclass
class MetricEvaluatorResult:
    best_score: float
    best_engine_params: EngineParams
    best_index: int
    #: per-candidate: (engine_params, primary score, extra metric scores)
    results: list[tuple[EngineParams, float, list[float]]]

    def leaderboard(self, metric: Metric, extras: Sequence[Metric]) -> str:
        lines = ["Metric Evaluator leaderboard:", ""]
        header = [metric.header()] + [m.header() for m in extras]
        for i, (params, score, extra_scores) in enumerate(self.results):
            marker = " <= BEST" if i == self.best_index else ""
            scores = ", ".join(
                f"{h}={s:.6f}" for h, s in zip(header, [score] + list(extra_scores))
            )
            lines.append(f"  [{i}] {scores}{marker}")
            lines.append(f"      params: {json.dumps(params.to_json_obj())}")
        return "\n".join(lines)

    def to_json(self, metric: Metric, extras: Sequence[Metric]) -> str:
        return json.dumps(
            {
                "bestScore": self.best_score,
                "bestIndex": self.best_index,
                "bestEngineParams": self.best_engine_params.to_json_obj(),
                "metric": metric.header(),
                "results": [
                    {
                        "engineParams": p.to_json_obj(),
                        "score": s,
                        "extraScores": dict(
                            zip([m.header() for m in extras], extra)
                        ),
                    }
                    for p, s, extra in self.results
                ],
            }
        )


class MetricEvaluator:
    """Runs the engine over each candidate EngineParams and ranks by metric
    (reference MetricEvaluator + NameParamsEvaluator role)."""

    def __init__(self, evaluation: Evaluation):
        self.evaluation = evaluation

    def run(self, ctx, generator: EngineParamsGenerator) -> MetricEvaluatorResult:
        if not generator.engine_params_list:
            raise ValueError("engine params generator produced no candidates")
        metric = self.evaluation.metric
        extras = self.evaluation.metrics
        results = []
        best_index, best_score = 0, None
        for i, engine_params in enumerate(generator.engine_params_list):
            per_fold = self.evaluation.engine.eval(ctx, engine_params)
            score = metric.calculate(per_fold)
            extra_scores = [m.calculate(per_fold) for m in extras]
            results.append((engine_params, score, extra_scores))
            if best_score is None or metric.compare(score, best_score) > 0:
                best_index, best_score = i, score
        return MetricEvaluatorResult(
            best_score=best_score,
            best_engine_params=results[best_index][0],
            best_index=best_index,
            results=results,
        )
