"""Per-function lockset dataflow, joined over call paths.

Phase 1 tracked "which locks does THIS function lexically hold" -- enough
for fsync-under-the-same-``with`` but blind to the two shapes that
actually bit later PRs: a helper that blocks while EVERY caller holds a
lock (the lock lives N frames up), and two threads touching a field
where each side's lock set is non-empty but DISJOINT.

Three layers:

- **Lock identity** is package-qualified by *declaration site class*:
  ``self._lock`` in ``MicroBatcher`` is ``workflow/microbatch.py:
  MicroBatcher._lock`` -- all instances of one class share an identity,
  matching lockwatch's construction-site keying, so the static and
  runtime views can be cross-referenced. Receiver types are resolved
  through the call graph's inference (``w.cmp_lock`` with ``w: _Worker``
  annotates to ``_Worker.cmp_lock``).
- **Local facts** per function: lock acquisitions, blocking calls, calls
  made, and ``self.*`` field reads/writes -- each annotated with the
  lockset *lexically held* at that statement (``with`` nesting, the
  phase-1 region walk generalized).
- **Entry contexts**: a fixpoint over the call graph computing, for each
  function, the distinct non-empty locksets callers can hold around a
  call to it, with one witness call chain per lockset. ``join`` is
  set-union along a path (locks accumulate) and set-of-locksets across
  paths (alternatives stay distinct -- intersecting them would erase the
  exact disjointness C006 needs to see).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from predictionio_tpu.analysis.astutil import call_name, dotted, keyword
from predictionio_tpu.analysis.callgraph import CallGraph, FunctionInfo

_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "Lock", "RLock", "Condition",
}

#: attribute calls that mutate a container in place (writes to the field)
_MUTATORS = {
    "append", "extend", "insert", "pop", "remove", "clear", "add",
    "discard", "update", "setdefault", "popitem",
}

#: per-function cap on tracked caller locksets (fixpoint bound; real code
#: has 1-3)
_MAX_CONTEXTS = 6


def blocking_reason(call: ast.Call) -> str | None:
    """The C002 catalog: calls that can park the calling thread. Returns
    a short human reason, or None."""
    name = call_name(call)
    if name == "os.fsync":
        return "os.fsync"
    if isinstance(call.func, ast.Attribute):
        attr = call.func.attr
        if attr == "fsync":
            return "fsync"
        # span/trace export under a lock serializes every instrumented hot
        # path behind the exporter's I/O (obs/ policy: ring-buffer under
        # the lock, export outside). Bare .flush() only counts on
        # tracing-shaped receivers so file/stream flushes stay unflagged.
        if attr in ("export", "export_spans", "force_flush"):
            return f"span export .{attr}()"
        if attr == "flush":
            recv = (dotted(call.func.value) or "").lower()
            if any(
                s in recv for s in ("trace", "span", "exporter", "telemetry")
            ):
                return f"span export .{attr}()"
        if attr in ("execute", "executemany", "commit", "rollback"):
            return f"SQL .{attr}()"
        if attr in ("connect", "sendall", "recv", "accept", "makefile"):
            return f"socket .{attr}()"
        if attr in ("put", "get"):
            recv = (dotted(call.func.value) or "").lower()
            if "queue" in recv or recv in ("q", "self.q"):
                if keyword(call, "timeout") is not None:
                    return None
                block_kw = keyword(call, "block")
                if block_kw is not None and isinstance(
                    block_kw.value, ast.Constant
                ) and block_kw.value.value is False:
                    return None
                return f"blocking queue .{attr}() without timeout"
    if name == "time.sleep":
        return "time.sleep"
    if name in ("urllib.request.urlopen", "urlopen"):
        return "urlopen"
    return None


@dataclass
class Access:
    attr: str
    kind: str         # "read" | "write"
    line: int
    held: frozenset   # qualified lock keys lexically held


@dataclass
class FuncFacts:
    info: FunctionInfo
    #: (lock key, held-before frozenset, line)
    acquisitions: list = field(default_factory=list)
    #: (reason, held frozenset, line, call node)
    blocking: list = field(default_factory=list)
    #: (call node, held frozenset, line)
    calls: list = field(default_factory=list)
    accesses: list = field(default_factory=list)   # list[Access]


class LockModel:
    """Package lock inventory + per-function facts + caller contexts."""

    def __init__(self, graph: CallGraph):
        self.graph = graph
        #: qualified lock key -> "module.dotted:line" construction site
        #: (the lockwatch runtime crosswalk)
        self.lock_sites: dict[str, str] = {}
        #: (path, cls|None) -> {attr/name, ...} locks declared there
        self._declared: dict[tuple, set] = {}
        self.facts: dict[tuple, FuncFacts] = {}
        self._collect_locks()
        for fi in graph.functions.values():
            self.facts[fi.key] = self._walk(fi)
        self._contexts: dict[tuple, dict] | None = None

    # -- lock inventory -----------------------------------------------------
    def _collect_locks(self) -> None:
        for mod in self.graph.modules.values():
            for node in mod.call_assigns:
                if call_name(node.value) not in _LOCK_CTORS:
                    continue
                cls = mod.ctx.symbol_for(node)
                for t in node.targets:
                    d = dotted(t)
                    if d is None:
                        continue
                    if d.startswith("self.") and d.count(".") == 1:
                        # enclosing qual is "Class.method"; the class owns
                        # the lock
                        owner = cls.rsplit(".", 1)[0] if "." in cls else None
                        if owner is None:
                            continue
                        attr = d[len("self."):]
                        key = self._key(mod.path, owner, attr)
                        self._declared.setdefault(
                            (mod.path, owner), set()
                        ).add(attr)
                    elif "." not in d and cls == "<module>":
                        key = self._key(mod.path, None, d)
                        self._declared.setdefault((mod.path, None), set()).add(d)
                    elif "." not in d and (mod.path, cls) in self.graph.classes:
                        # class-BODY declaration (class Foo: _lock =
                        # Lock()): one lock shared by every instance --
                        # phase 1 registered these and so must we
                        key = self._key(mod.path, cls, d)
                        self._declared.setdefault(
                            (mod.path, cls), set()
                        ).add(d)
                    else:
                        continue
                    self.lock_sites.setdefault(
                        key, f"{mod.dotted}:{node.lineno}"
                    )

    @staticmethod
    def _key(path: str, cls: str | None, name: str) -> str:
        return f"{path}:{cls}.{name}" if cls else f"{path}:{name}"

    def lock_key(self, fi: FunctionInfo, expr: ast.AST) -> str | None:
        """Qualified identity of a lock-valued expression, or None when
        the expression is not a known lock."""
        d = dotted(expr)
        if d is None:
            return None
        if d.startswith("self.") and d.count(".") == 1 and fi.cls is not None:
            attr = d[len("self."):]
            if attr in self._declared.get((fi.path, fi.cls), ()):
                return self._key(fi.path, fi.cls, attr)
            return None
        if "." not in d:
            if d in self._declared.get((fi.path, None), ()):
                return self._key(fi.path, None, d)
            return None
        # typed receiver: w.cmp_lock / self._retry._cv
        root, rest = d.rsplit(".", 1)
        recv = self.graph.instance_type(fi, _parse_dotted(root))
        if recv is not None and rest in self._declared.get(
            (recv.path, recv.qual), ()
        ):
            return self._key(recv.path, recv.qual, rest)
        return None

    def class_locks(self, path: str, cls: str) -> set:
        return {
            self._key(path, cls, a)
            for a in self._declared.get((path, cls), ())
        }

    # -- local facts --------------------------------------------------------
    def _walk(self, fi: FunctionInfo) -> FuncFacts:
        facts = FuncFacts(fi)
        method_names = set()
        if fi.cls is not None:
            cinfo = self.graph.classes.get((fi.path, fi.cls))
            if cinfo is not None:
                method_names = set(cinfo.methods)
        nodes = self.graph.body_nodes(fi.node)
        # lock-free function (the overwhelming majority): every held set
        # is empty, so the facts fall straight out of the cached flat
        # body list -- no region recursion. The flat walk itself detects
        # With/acquire nodes and bails (returns None) so the common case
        # pays a single pass instead of prescan + walk.
        flat = self._walk_flat(fi, facts, method_names, nodes)
        if flat is not None:
            return flat
        facts = FuncFacts(fi)

        def visit(node: ast.AST, held: tuple) -> None:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                acquired = []
                for item in node.items:
                    lid = self.lock_key(fi, item.context_expr)
                    if lid is not None:
                        facts.acquisitions.append(
                            (lid, frozenset(held), node.lineno)
                        )
                        acquired.append(lid)
                    else:
                        # non-lock context managers still make calls
                        # (tracer.span(...)) the graph needs to see
                        visit(item.context_expr, held)
                inner = held + tuple(a for a in acquired if a not in held)
                for child in node.body:
                    visit(child, inner)
                return
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                return  # nested defs are their own call-graph nodes
            if isinstance(node, ast.Call):
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "acquire"
                ):
                    lid = self.lock_key(fi, node.func.value)
                    if lid is not None:
                        facts.acquisitions.append(
                            (lid, frozenset(held), node.lineno)
                        )
                reason = blocking_reason(node)
                if reason is not None:
                    facts.blocking.append(
                        (reason, frozenset(held), node.lineno, node)
                    )
                facts.calls.append((node, frozenset(held), node.lineno))
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _MUTATORS
                ):
                    recv = dotted(node.func.value) or ""
                    if recv.startswith("self.") and recv.count(".") == 1:
                        # `.add()`/`.update()` on an attr whose inferred
                        # type DEFINES that method is a method call, not
                        # a container mutation (self._retry.add(...))
                        rtype = self.graph.instance_type(fi, node.func.value)
                        if rtype is None or node.func.attr not in rtype.methods:
                            facts.accesses.append(Access(
                                recv[len("self."):], "write",
                                node.lineno, frozenset(held),
                            ))
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    base = t
                    while isinstance(base, ast.Subscript):
                        base = base.value
                    d = dotted(base)
                    if d and d.startswith("self.") and d.count(".") == 1:
                        facts.accesses.append(Access(
                            d[len("self."):], "write",
                            node.lineno, frozenset(held),
                        ))
            if isinstance(node, ast.Delete):
                for t in node.targets:
                    d = dotted(t)
                    if d and d.startswith("self.") and d.count(".") == 1:
                        facts.accesses.append(Access(
                            d[len("self."):], "write",
                            node.lineno, frozenset(held),
                        ))
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.ctx, ast.Load)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and node.attr not in method_names
            ):
                facts.accesses.append(Access(
                    node.attr, "read", node.lineno, frozenset(held)
                ))
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        body = fi.node.body if isinstance(fi.node.body, list) else [fi.node.body]
        for stmt in body:
            visit(stmt, ())
        return facts

    _EMPTY = frozenset()

    def _walk_flat(
        self, fi: FunctionInfo, facts: FuncFacts, method_names: set, nodes
    ) -> "FuncFacts | None":
        """The no-locks fast path: identical facts to the region walk,
        with every held set the empty frozenset. Returns None on the
        first With/acquire node -- the caller restarts with the region
        walk (partial facts are discarded with the FuncFacts)."""
        held = self._EMPTY
        for node in nodes:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                return None
            if isinstance(node, ast.Call):
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "acquire"):
                    return None
                reason = blocking_reason(node)
                if reason is not None:
                    facts.blocking.append((reason, held, node.lineno, node))
                facts.calls.append((node, held, node.lineno))
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _MUTATORS
                ):
                    recv = dotted(node.func.value) or ""
                    if recv.startswith("self.") and recv.count(".") == 1:
                        rtype = self.graph.instance_type(fi, node.func.value)
                        if rtype is None or node.func.attr not in rtype.methods:
                            facts.accesses.append(Access(
                                recv[len("self."):], "write",
                                node.lineno, held,
                            ))
            elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    base = t
                    while isinstance(base, ast.Subscript):
                        base = base.value
                    d = dotted(base)
                    if d and d.startswith("self.") and d.count(".") == 1:
                        facts.accesses.append(Access(
                            d[len("self."):], "write", node.lineno, held,
                        ))
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    d = dotted(t)
                    if d and d.startswith("self.") and d.count(".") == 1:
                        facts.accesses.append(Access(
                            d[len("self."):], "write", node.lineno, held,
                        ))
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.ctx, ast.Load)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and node.attr not in method_names
            ):
                facts.accesses.append(Access(
                    node.attr, "read", node.lineno, held
                ))
        return facts

    # -- interprocedural contexts -------------------------------------------
    def entry_contexts(self) -> dict:
        """fkey -> {frozenset(lockset): witness}, where witness is
        ``(caller fkey, call line, caller's own context lockset)`` --
        enough to rebuild the acquisition-to-blocking chain."""
        if self._contexts is not None:
            return self._contexts
        contexts: dict[tuple, dict] = {}
        work: list[tuple] = []

        def push(fkey, lockset, witness):
            if not lockset:
                return
            ctxs = contexts.setdefault(fkey, {})
            if lockset in ctxs or len(ctxs) >= _MAX_CONTEXTS:
                return
            ctxs[lockset] = witness
            work.append((fkey, lockset))

        for fkey, facts in self.facts.items():
            for call, held, line in facts.calls:
                if not held:
                    continue
                for target in self.graph.call_targets.get(
                    (facts.info.path, id(call)), ()
                ):
                    push(
                        target.key, frozenset(held),
                        (fkey, line, frozenset()),
                    )
        while work:
            fkey, lockset = work.pop()
            facts = self.facts.get(fkey)
            if facts is None:
                continue
            for call, held, line in facts.calls:
                for target in self.graph.call_targets.get(
                    (facts.info.path, id(call)), ()
                ):
                    push(
                        target.key, frozenset(lockset | held),
                        (fkey, line, lockset),
                    )
        self._contexts = contexts
        return contexts

    def context_chain(self, fkey: tuple, lockset: frozenset) -> list[str]:
        """Witness call chain (outermost caller first) for one inherited
        lockset, as ``path:qual:line`` hops."""
        chain = []
        contexts = self.entry_contexts()
        cur_key, cur_set = fkey, lockset
        seen = set()
        while (cur_key, cur_set) not in seen:
            seen.add((cur_key, cur_set))
            witness = contexts.get(cur_key, {}).get(cur_set)
            if witness is None:
                break
            caller, line, caller_set = witness
            path, qual = caller
            chain.append(f"{path}:{qual}:{line}")
            cur_key, cur_set = caller, frozenset(caller_set)
            if not cur_set:
                break
        chain.reverse()
        return chain

    @staticmethod
    def short_lock(key: str) -> str:
        """``pkg/mod.py:Cls._lock`` -> ``Cls._lock`` (for messages)."""
        return key.rsplit(":", 1)[-1]


def _parse_dotted(text: str) -> ast.AST:
    return ast.parse(text, mode="eval").body
