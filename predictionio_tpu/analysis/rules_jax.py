"""J-series rules: the jax drift/tracing invariants this repo learned the
hard way. Each rule's docstring names the incident it encodes; the catalog
with reproduction context lives in ``docs/static_analysis.md``.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from predictionio_tpu.analysis.astutil import (
    call_name,
    const_strings,
    dotted,
    func_defs,
    keyword,
    walk_calls,
)
from predictionio_tpu.analysis.engine import Finding, ModuleContext

#: the one module allowed to touch the drifting jax surface directly
SHIM_PATH_SUFFIX = "utils/jax_compat.py"

JIT_NAMES = {"jax.jit", "jit", "jax.pjit", "pjit"}
PARTIAL_NAMES = {"functools.partial", "partial"}

_OPT_STATE_RE = re.compile(r"opt_state|optimizer|adam_state", re.IGNORECASE)

#: names whose presence marks a module as doing sharded placement (the
#: precondition under which legacy-jax donation of optimizer state
#: miscompiles -- an unsharded trainer donating moments is fine)
_SHARDING_MARKERS = {
    "NamedSharding", "put_global", "shard_map", "with_sharding_constraint",
    "PartitionSpec",
}


def _is_shim(ctx: ModuleContext) -> bool:
    return ctx.path.endswith(SHIM_PATH_SUFFIX)


def _jit_index(ctx: ModuleContext) -> "_JitIndex":
    """One _JitIndex per module, shared by J002/J003/J004. Cached on the
    context object itself (the symbols map builds lazily and must stay
    pure node->qualname)."""
    cached = getattr(ctx, "_jit_index_cache", None)
    if cached is None:
        cached = _JitIndex(ctx)
        ctx._jit_index_cache = cached
    return cached


class _JitIndex:
    """Functions that run under trace: ``@jax.jit``-style decorations,
    ``jax.jit(fn, ...)`` call sites (including one level of factory
    resolution: ``jax.jit(make_step(...))`` -> the nested def ``make_step``
    returns), and Pallas kernel bodies (first arg of ``pallas_call``)."""

    def __init__(self, ctx: ModuleContext):
        self.defs = func_defs(ctx.tree)
        #: id(FunctionDef) -> set of static (untraced) parameter names
        self.jitted: dict[int, tuple[ast.FunctionDef, set[str]]] = {}
        self.kernels: dict[int, ast.FunctionDef] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                jit_call = self._jit_decorator(node)
                if jit_call is not None:
                    self._mark(node, jit_call)
            elif isinstance(node, ast.Call):
                name = call_name(node)
                if name in JIT_NAMES and node.args:
                    for fn in self._resolve_fn(node.args[0]):
                        self._mark(fn, node)
                elif name.endswith("pallas_call") and node.args:
                    target = node.args[0]
                    if isinstance(target, ast.Name):
                        for fn in self.defs.get(target.id, []):
                            self.kernels[id(fn)] = fn

    def _jit_decorator(self, node: ast.FunctionDef) -> ast.Call | None:
        for dec in node.decorator_list:
            if (dotted(dec) or "") in JIT_NAMES:
                return ast.Call(func=dec, args=[], keywords=[])
            if isinstance(dec, ast.Call):
                name = call_name(dec)
                if name in JIT_NAMES:
                    return dec
                if name in PARTIAL_NAMES and dec.args and (
                    dotted(dec.args[0]) or ""
                ) in JIT_NAMES:
                    return dec
        return None

    def _resolve_fn(self, arg: ast.AST) -> list[ast.FunctionDef]:
        """``jax.jit(X)``: X a local def, or a call to a factory whose
        ``return <name>`` names a nested def (the make_train_step shape)."""
        if isinstance(arg, ast.Name):
            return self.defs.get(arg.id, [])
        if isinstance(arg, ast.Call) and isinstance(arg.func, ast.Name):
            out = []
            for factory in self.defs.get(arg.func.id, []):
                for ret in ast.walk(factory):
                    if isinstance(ret, ast.Return) and isinstance(ret.value, ast.Name):
                        for inner in self.defs.get(ret.value.id, []):
                            # the nested def, not a same-named global
                            if any(inner is n for n in ast.walk(factory)):
                                out.append(inner)
            return out
        return []

    def _mark(self, fn: ast.FunctionDef, jit_call: ast.Call) -> None:
        static: set[str] = set()
        params = _param_names(fn)
        kw = keyword(jit_call, "static_argnames")
        if kw is not None:
            static |= const_strings(kw.value)
        kw = keyword(jit_call, "static_argnums")
        if kw is not None:
            for c in ast.walk(kw.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, int):
                    if 0 <= c.value < len(params):
                        static.add(params[c.value])
        self.jitted[id(fn)] = (fn, static)


def _param_names(fn: ast.FunctionDef) -> list[str]:
    a = fn.args
    return [p.arg for p in a.posonlyargs + a.args]


def _tainted_names(fn: ast.FunctionDef, static: set[str]) -> set[str]:
    """Names bound to (potentially) traced values inside a jitted scope:
    the parameters, plus anything assigned from jnp/lax math on them."""
    tainted = {
        p.arg
        for p in fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs
        if p.arg not in static and p.arg != "self"
    }
    for _ in range(4):  # small fixpoint; chains in practice are short
        grew = False
        for node in ast.walk(fn):
            targets: list[ast.AST] = []
            if isinstance(node, ast.Assign):
                value, targets = node.value, node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                value, targets = node.value, [node.target]
            else:
                continue
            if value is None or not _expr_tainted(value, tainted):
                continue
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name) and n.id not in tainted:
                        tainted.add(n.id)
                        grew = True
        if not grew:
            break
    return tainted


def _expr_tainted(expr: ast.AST, tainted: set[str]) -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.Call):
            d = call_name(n)
            if d.startswith(("jnp.", "jax.numpy.", "jax.lax.")):
                return True
        if isinstance(n, ast.Name) and n.id in tainted:
            return True
    return False


#: calls whose result is static even when the argument is traced
_STATIC_CALLS = {"len", "isinstance", "hasattr", "callable", "getattr", "type"}
#: attributes that are static python values on tracers (branching on a
#: shape or dtype is legitimate trace-time specialization)
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding"}


def _test_tainted(test: ast.AST, tainted: set[str]) -> bool:
    """Taint check for branch tests, pruning subexpressions that are
    STATIC at trace time even on traced values: ``len(x)``, ``x.shape``,
    ``x is None`` identity checks, isinstance/hasattr."""
    if isinstance(test, ast.Call):
        name = call_name(test)
        if name in _STATIC_CALLS:
            return False
        if name.startswith(("jnp.", "jax.numpy.", "jax.lax.")):
            return True
    if isinstance(test, ast.Attribute) and test.attr in _STATIC_ATTRS:
        return False
    if isinstance(test, ast.Compare) and all(
        isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
    ):
        return False
    if isinstance(test, ast.Name):
        return test.id in tainted
    return any(_test_tainted(c, tainted) for c in ast.iter_child_nodes(test))


class RuleJ001:
    """Direct ``jax.experimental`` / ``jax.shard_map`` / ``pjit`` use outside
    the drift shim. Incident: jax 0.4.37 renamed/moved this entire surface
    (``check_vma`` vs ``check_rep``, ``jax.shard_map`` vs
    ``jax.experimental.shard_map``); every direct import is a copy of the
    drift policy that rots independently. Route through utils/jax_compat."""

    rule_id = "J001"
    severity = "error"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if _is_shim(ctx):
            return
        seen: set[int] = set()

        def finding(node: ast.AST, what: str) -> Finding | None:
            if node.lineno in seen:
                return None
            seen.add(node.lineno)
            return Finding(
                self.rule_id, self.severity, ctx.path, node.lineno,
                ctx.symbol_for(node),
                f"direct {what} outside utils/jax_compat (drift-shim policy)",
                "import the equivalent name from predictionio_tpu.utils.jax_compat",
            )

        for node in ast.walk(ctx.tree):
            f = None
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.startswith("jax.experimental"):
                        f = finding(node, f"import of {alias.name}")
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod.startswith("jax.experimental"):
                    f = finding(node, f"import from {mod}")
                elif mod == "jax" and any(
                    a.name in ("shard_map", "pjit") for a in node.names
                ):
                    f = finding(node, "import of jax.shard_map/pjit")
            elif isinstance(node, ast.Attribute):
                d = dotted(node) or ""
                if d.startswith("jax.experimental") or d in (
                    "jax.shard_map", "jax.pjit",
                ):
                    f = finding(node, f"use of {d}")
            if f is not None:
                yield f


class RuleJ002:
    """Donating optimizer state to a jit in a sharded-placement module
    without an ``IS_LEGACY_JAX`` gate. Incident (PR 4): on legacy jax,
    donating a tp-sharded adam-state pytree makes XLA pair donated buffers
    with wrong-shaped outputs ("Expected aliased input ... same size")."""

    rule_id = "J002"
    severity = "error"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if _is_shim(ctx):
            return
        index = _jit_index(ctx)
        module_is_sharded = self._module_sharded(ctx)
        for call in walk_calls(ctx.tree):
            if call_name(call) not in JIT_NAMES:
                continue
            yield from self._check_jit_call(ctx, index, call, module_is_sharded)
        # decorator form: @functools.partial(jax.jit, donate_argnums=...)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            dec = index._jit_decorator(node)
            if dec is None or (not dec.keywords and not dec.args):
                continue
            yield from self._check_donation(
                ctx, dec, _param_names(node), module_is_sharded, node.lineno
            )

    def _module_sharded(self, ctx: ModuleContext) -> bool:
        for n in ast.walk(ctx.tree):
            if isinstance(n, ast.Name) and n.id in _SHARDING_MARKERS:
                return True
            if isinstance(n, ast.Attribute) and n.attr in _SHARDING_MARKERS:
                return True
            if isinstance(n, ast.ImportFrom) and any(
                a.name in _SHARDING_MARKERS for a in n.names
            ):
                return True
        return False

    def _check_jit_call(self, ctx, index, call, module_is_sharded):
        if not call.args:
            return  # decorator-factory form; handled via _jit_decorator
        params: list[str] = []
        for fn in index._resolve_fn(call.args[0]):
            params = _param_names(fn)
            break
        sharded = module_is_sharded or any(
            kw.arg in ("in_shardings", "out_shardings") for kw in call.keywords
        )
        yield from self._check_donation(ctx, call, params, sharded, call.lineno)

    def _check_donation(self, ctx, call, params, sharded, line):
        if not sharded:
            return
        for kw_name in ("donate_argnums", "donate_argnames"):
            kw = keyword(call, kw_name)
            if kw is None:
                continue
            if self._gated(kw.value):
                continue
            donated = self._donated_names(kw, params)
            suspicious = [n for n in donated if _OPT_STATE_RE.search(n)]
            if not suspicious and params:
                continue  # names resolved and none look like optimizer state
            if not suspicious:
                # could not resolve the callee's params: fall back to "does
                # this module bind optimizer state at all"
                if not self._module_has_opt_state(ctx):
                    continue
                suspicious = ["<unresolved>"]
            yield Finding(
                self.rule_id, self.severity, ctx.path, line,
                ctx.symbol_for(call),
                f"{kw_name} donates optimizer state "
                f"({', '.join(suspicious)}) in a sharded module without an "
                "IS_LEGACY_JAX gate (legacy jax miscompiles sharded "
                "opt-state donation)",
                "donate params only on legacy jax: donate_argnums=(0,) if "
                "IS_LEGACY_JAX else (0, 1)",
            )

    def _gated(self, value: ast.AST) -> bool:
        if isinstance(value, ast.IfExp):
            return "IS_LEGACY_JAX" in {
                n.id for n in ast.walk(value.test) if isinstance(n, ast.Name)
            } | {
                a.attr for a in ast.walk(value.test) if isinstance(a, ast.Attribute)
            }
        return False

    def _donated_names(self, kw: ast.keyword, params: list[str]) -> list[str]:
        if kw.arg == "donate_argnames":
            return sorted(const_strings(kw.value))
        names = []
        for c in ast.walk(kw.value):
            if isinstance(c, ast.Constant) and isinstance(c.value, int):
                if 0 <= c.value < len(params):
                    names.append(params[c.value])
        return names

    def _module_has_opt_state(self, ctx: ModuleContext) -> bool:
        for n in ast.walk(ctx.tree):
            if isinstance(n, ast.Name) and _OPT_STATE_RE.search(n.id):
                return True
        return False


class RuleJ003:
    """Python ``if``/``while``/``assert`` on a ``jnp``-derived value
    inside a ``@jit`` scope or Pallas kernel (static tests -- ``x is
    None``, ``len()``, ``.shape`` -- are pruned); use
    lax.cond/select/while_loop instead.

    Incident: TracerBoolConversionError at trace time at best, silent
    specialization on a trace-time constant at worst -- the bug class
    every template trainer hit at least once before the gate existed."""

    rule_id = "J003"
    severity = "error"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        index = _jit_index(ctx)
        scopes = [(fn, static) for fn, static in index.jitted.values()]
        scopes += [(fn, set()) for fn in index.kernels.values()]
        reported: set[int] = set()
        for fn, static in scopes:
            tainted = _tainted_names(fn, static)
            for node in ast.walk(fn):
                if not isinstance(node, (ast.If, ast.While, ast.Assert)):
                    continue
                if node.lineno in reported:
                    continue
                if _test_tainted(node.test, tainted):
                    reported.add(node.lineno)
                    kind = type(node).__name__.lower()
                    yield Finding(
                        self.rule_id, self.severity, ctx.path, node.lineno,
                        ctx.symbol_for(node),
                        f"python `{kind}` on a traced value inside jitted "
                        f"scope {fn.name!r}",
                        "use jax.lax.cond / jnp.where / lax.while_loop, or "
                        "hoist the branch out of the jitted function",
                    )


class RuleJ004:
    """Host-sync calls (``.item()``, ``float()``/``int()``/``bool()``,
    ``np.asarray``) on traced values inside jit: they either fail at
    trace time or silently force a device->host transfer per call on the
    serving hot path.

    Incident: the NCF serving path once paid ~860 ms/query on a
    remote-tunnel backend to per-call eager dispatches + host syncs."""

    rule_id = "J004"
    severity = "warning"

    _CASTS = {"float", "int", "bool"}
    _NP_SINKS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        index = _jit_index(ctx)
        scopes = [(fn, static) for fn, static in index.jitted.values()]
        scopes += [(fn, set()) for fn in index.kernels.values()]
        reported: set[int] = set()
        for fn, static in scopes:
            tainted = _tainted_names(fn, static)
            for call in walk_calls(fn):
                if call.lineno in reported:
                    continue
                what = self._host_sync(call, tainted)
                if what is None:
                    continue
                reported.add(call.lineno)
                yield Finding(
                    self.rule_id, self.severity, ctx.path, call.lineno,
                    ctx.symbol_for(call),
                    f"host-sync `{what}` on a traced value inside jitted "
                    f"scope {fn.name!r}",
                    "keep values on device inside jit; convert on the host "
                    "after the jitted call returns",
                )

    def _host_sync(self, call: ast.Call, tainted: set[str]) -> str | None:
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr == "item"
            and not call.args
            and _test_tainted(call.func.value, tainted)
        ):
            return ".item()"
        name = call_name(call)
        if name in self._CASTS and len(call.args) == 1 and _test_tainted(
            call.args[0], tainted
        ):
            return f"{name}()"
        if name in self._NP_SINKS and call.args and _test_tainted(
            call.args[0], tainted
        ):
            return f"{name}()"
        return None


class RuleJ005:
    """Concat-then-reshard to a ``P(..., "model", ...)`` spec. Incident
    (PR 4): jax 0.4.37 GSPMD MISCOMPILES concatenating per-bucket outputs
    and resharding the result to the model axis -- values land in wrong
    rows. Assemble with dynamic_update_slice into a pre-sharded buffer and
    reshard single arrays only."""

    rule_id = "J005"
    severity = "error"

    _CONCAT = ("jnp.concatenate", "jnp.concat", "jax.numpy.concatenate",
               "jnp.vstack", "jnp.hstack")
    _RESHARD = ("jax.device_put", "device_put", "jax.lax.with_sharding_constraint",
                "lax.with_sharding_constraint", "with_sharding_constraint",
                "reshard", "jax.device_put_sharded")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        concat_names = self._concat_names(ctx.tree)
        model_spec_names = self._model_spec_names(ctx.tree)
        for call in walk_calls(ctx.tree):
            if call_name(call) not in self._RESHARD:
                continue
            args = list(call.args) + [kw.value for kw in call.keywords]
            has_concat = any(self._is_concat_value(a, concat_names) for a in args)
            if not has_concat:
                continue
            if not any(
                self._mentions_model_spec(a, model_spec_names) for a in args
            ):
                continue
            yield Finding(
                self.rule_id, self.severity, ctx.path, call.lineno,
                ctx.symbol_for(call),
                "concatenated array resharded to a P(...'model'...) spec "
                "(jax 0.4.37 GSPMD miscompile shape: values land in wrong "
                "rows)",
                "dynamic_update_slice each piece into a buffer already "
                "sharded on 'model'; only reshard single arrays",
            )

    def _concat_names(self, tree: ast.AST) -> set[str]:
        out: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and self._has_concat(node.value):
                for t in node.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            out.add(n.id)
        return out

    def _has_concat(self, expr: ast.AST) -> bool:
        return any(
            call_name(c) in self._CONCAT for c in walk_calls(expr)
        )

    def _is_concat_value(self, expr: ast.AST, concat_names: set[str]) -> bool:
        if self._has_concat(expr):
            return True
        return isinstance(expr, ast.Name) and expr.id in concat_names

    def _model_spec_names(self, tree: ast.AST) -> set[str]:
        out: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and self._spec_in(node.value):
                for t in node.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            out.add(n.id)
        return out

    def _spec_in(self, expr: ast.AST) -> bool:
        for c in walk_calls(expr):
            name = call_name(c)
            if name.split(".")[-1] in ("P", "PartitionSpec", "NamedSharding"):
                if "model" in const_strings(c):
                    return True
        return False

    def _mentions_model_spec(self, expr: ast.AST, spec_names: set[str]) -> bool:
        if self._spec_in(expr):
            return True
        return any(
            isinstance(n, ast.Name) and n.id in spec_names
            for n in ast.walk(expr)
        )


class RuleJ006:
    """Loop-invariant host->device transfer inside a training loop.
    Incident (PR 10, device-resident epochs): ``fold_in_users`` re-shipped
    the FROZEN item-factor table to the device on every retrain cycle, and
    the first draft of the streamed ALS epoch loop would have re-shipped
    the opposite-side factor table / YtY Gram / ridge eye per block. A
    ``device_put``/``jnp.asarray``/``put_global`` whose argument the loop
    body never rebinds pays the host link (plus an allocation) once per
    iteration for bytes that never change -- hoist it above the loop (or
    cache the device copy, ``online.foldin._device_factors``). Per-batch
    transfers (the argument is sliced/rebound inside the loop) are the
    intended shape and stay silent, as do calls inside jitted scopes
    (tracers make them no-ops)."""

    rule_id = "J006"
    severity = "warning"

    _PUTS = {
        "jax.device_put", "device_put", "jnp.asarray", "jax.numpy.asarray",
        "put_global",
    }
    #: a loop is a TRAINING loop when its body calls something step-shaped;
    #: generic serving/IO loops stay out of scope. Deliberately NO
    #: `update`: `dict.update()`/`set.update()` in ordinary loops would
    #: misclassify them (optax-style `opt.update` loops call a step/fit
    #: function too, so coverage survives)
    _TRAIN_CALL_RE = re.compile(
        r"(^|[._])(step|iteration|train|fit|solve|fold)", re.IGNORECASE
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        index = _jit_index(ctx)
        traced = {id(fn) for fn, _ in index.jitted.values()}
        traced |= set(index.kernels.keys())
        # one pass: every loop under a traced def (jitted / kernel) runs
        # on tracers, where the 'transfer' is a no-op
        traced_loops: set[int] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if id(node) in traced:
                    for n in ast.walk(node):
                        if isinstance(n, (ast.For, ast.While, ast.AsyncFor)):
                            traced_loops.add(id(n))
        reported: set[int] = set()
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.For, ast.While, ast.AsyncFor)):
                continue
            if id(loop) in traced_loops:
                continue
            if not self._is_training_loop(loop):
                continue
            bound = self._bound_names(loop)
            for call in walk_calls(loop):
                if call.lineno in reported:
                    continue
                name = call_name(call)
                if name not in self._PUTS or not call.args:
                    continue
                root = self._root_name(call.args[0])
                if root is None or root in bound:
                    continue
                reported.add(call.lineno)
                yield Finding(
                    self.rule_id, self.severity, ctx.path, call.lineno,
                    ctx.symbol_for(call),
                    f"`{name}({root}...)` inside a training loop, but "
                    f"{root!r} is never rebound in the loop body: a "
                    "loop-invariant host->device transfer per iteration",
                    "hoist the transfer above the loop (put once, reuse "
                    "the device array across iterations)",
                )

    def _is_training_loop(self, loop) -> bool:
        for call in walk_calls(loop):
            name = call_name(call)
            if name in self._PUTS:
                continue
            if self._TRAIN_CALL_RE.search(name or ""):
                return True
        return False

    def _bound_names(self, loop) -> set[str]:
        """Names (re)bound anywhere inside the loop, including its own
        targets: transfers of these are per-iteration by construction."""
        bound: set[str] = set()

        def add_target(t: ast.AST) -> None:
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    bound.add(n.id)

        if isinstance(loop, (ast.For, ast.AsyncFor)):
            add_target(loop.target)
        for node in ast.walk(loop):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    add_target(t)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign, ast.NamedExpr)):
                add_target(node.target)
            elif isinstance(node, (ast.For, ast.AsyncFor)) and node is not loop:
                add_target(node.target)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if item.optional_vars is not None:
                        add_target(item.optional_vars)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                bound.add(node.name)
                for p in (node.args.posonlyargs + node.args.args
                          + node.args.kwonlyargs):
                    bound.add(p.arg)
        return bound

    #: wrappers to see through: device_put(np.asarray(x)) is still a
    #: transfer of x
    _UNWRAP = _PUTS | {"np.asarray", "numpy.asarray", "np.array",
                       "numpy.array"}

    def _root_name(self, expr: ast.AST) -> str | None:
        """The root variable of a bare Name / dotted attribute argument
        (seeing through asarray-style wrappers); subscripts and literals
        are per-iteration values and return None."""
        while (
            isinstance(expr, ast.Call)
            and call_name(expr) in self._UNWRAP
            and expr.args
        ):
            expr = expr.args[0]
        while isinstance(expr, ast.Attribute):
            expr = expr.value
        if isinstance(expr, ast.Name):
            return expr.id
        return None


RULES = (RuleJ001, RuleJ002, RuleJ003, RuleJ004, RuleJ005, RuleJ006)
