"""P-series rules: cross-process protocol ordering over the fabric.

Built on ``analysis/protocols.py``: the declared commit/publish/advance
point model classified over PR 13's package call graph, with process
roles seeded at ``__main__`` guards and stitched through ring, portfile,
and ``--notify`` edges.  Where the R series proves ordering inside one
process (fsync-before-cursor on one flowgraph), the P series proves it
across the IPC boundary: the ack a peer observes, the cursor another
process replays from, the generation guard a frame must bind.

P004 is deliberately a *module* rule (a routing ``%`` is file-local
evidence), so ``pio check --changed`` runs it per file inside the
pre-commit budget; the ordering rules (P001/P002/P003/P005) are
package-horizon like the rest of phase 2.

Every rule class docstring IS its incident-catalog entry: ``pio check
--explain RULE`` prints it, and the P table in
``docs/static_analysis.md`` is generated from it.
"""

from __future__ import annotations

import ast
from typing import Iterator

from predictionio_tpu.analysis.engine import Finding
from predictionio_tpu.analysis.packageindex import PackageRule
from predictionio_tpu.analysis.protocols import routing_mod_sites


def _hops(fi, *lines) -> tuple:
    return tuple(f"{fi.path}:{fi.qual}:{line}" for line in lines)


class RuleP001(PackageRule):
    """An acknowledgement -- a future ``set_result``, an HTTP 2xx, or a
    ring completion push -- reachable on some path while a WAL/journal
    append on that path is not yet covered by a commit point (an
    ``os.fsync``, an ``.fsync``, or the WAL's group-commit ``sync``).
    This is R003 generalized across the IPC boundary: the peer that
    observes the ack is in another process, so no amount of in-process
    ordering after the fact can retract it. Callees are credited
    transitively: a helper that appends AND syncs internally is a net
    commit; a helper that appends without syncing leaves the obligation
    open in its caller.

    Incident: the ingest pipeline's original shape acked an event at
    enqueue time, before the segment fsync -- a SIGKILL between the 201
    and the group commit silently dropped acked events, which the
    at-least-once replay contract (PAPER.md section 4) forbids; the fix
    moved ``future.set_result`` after ``wal.sync()`` and the partitioned
    WAL kept that ordering per shard. This rule pins both.
    """

    rule_id = "P001"
    severity = "error"

    def check_package(self, index) -> Iterator[Finding]:
        flow = index.protocols()
        from predictionio_tpu.analysis.protocols import ack_before_commit

        for fi in index.graph.functions.values():
            for wline, wdetail, aline, akind in ack_before_commit(
                flow, fi
            ):
                yield Finding(
                    rule_id=self.rule_id, severity=self.severity,
                    path=fi.path, line=aline, symbol=fi.qual,
                    message=(
                        f"{akind} ack at line {aline} is reachable while "
                        f"the WAL write {wdetail} at line {wline} has no "
                        f"covering commit (fsync/sync) on the path"
                    ),
                    hint=(
                        "move the ack after the covering wal.sync()/"
                        "os.fsync(), or route it through the durability "
                        "point that already exists"
                    ),
                    witness=_hops(fi, wline, aline),
                    related=((fi.path, wline,
                              f"uncommitted write: {wdetail}"),),
                )


class RuleP002(PackageRule):
    """A replay cursor or checkpoint advance reachable on a path BEFORE
    a publication point (registry publish, ``/models/swap`` notify) that
    the same path still performs: the publish->notify->advance order is
    inverted, so a crash between the advance and the publish loses the
    events the cursor already passed. Branches that terminate before
    publishing (early returns, error paths) are path-separated and never
    flag; callees that publish and advance internally in the correct
    order contribute nothing to their callers.

    Incident: exactly-once fold-in replay depends on the cursor being
    the LAST thing that moves -- publish the model, notify the serving
    fabric, then advance. The retrain loop's first draft advanced each
    partition cursor as soon as its batch merged, before the merged
    model was published; a crash after the advance and before the
    publish dropped the window from every follower. The fix ordered
    ``registry.publish`` -> ``_notify_swap`` -> ``cursor.advance``, and
    the partitioned follower kept the order per partition cursor.
    """

    rule_id = "P002"
    severity = "error"

    def check_package(self, index) -> Iterator[Finding]:
        flow = index.protocols()
        from predictionio_tpu.analysis.protocols import (
            advance_before_publish,
        )

        for fi in index.graph.functions.values():
            for aline, adetail, pline, pkind in advance_before_publish(
                flow, fi
            ):
                yield Finding(
                    rule_id=self.rule_id, severity=self.severity,
                    path=fi.path, line=aline, symbol=fi.qual,
                    message=(
                        f"cursor advance {adetail} at line {aline} is "
                        f"reachable before the {pkind} at line {pline} "
                        f"completes: a crash in between loses the "
                        f"consumed window"
                    ),
                    hint=(
                        "advance the cursor only after every publication "
                        "obligation on the path has completed "
                        "(publish -> notify -> advance)"
                    ),
                    witness=_hops(fi, aline, pline),
                    related=((fi.path, pline,
                              f"later publication point ({pkind})"),),
                )


class RuleP003(PackageRule):
    """A guard field (``generation``/``epoch``/``version``) read off a
    ring-popped frame in a function that never compares any guard value,
    running in a process role distinct from every frame producer's role:
    the consumer trusts a cross-process version without binding the
    swap-epoch guard in the acquisition that read it. Process roles are
    seeded at each module's ``__main__`` guard (each entry module is its
    own process) and propagated over call edges -- the cross-process
    extension of the C-series thread roles, stitched through the ring
    edge.

    Incident: the swap-epoch protocol exists because a scorer shard and
    its frontend restart independently -- a completion frame addressed
    to ring generation G must be dropped by a generation-G+1 consumer,
    not served. Reading ``frame["version"]`` without comparing it to the
    bound generation reintroduces the stale-read the per-shard hot swap
    was built to exclude: a respawned shard would serve scores from the
    dead epoch's factors.
    """

    rule_id = "P003"
    severity = "error"

    def check_package(self, index) -> Iterator[Finding]:
        flow = index.protocols()
        from predictionio_tpu.analysis.protocols import (
            unguarded_peer_reads,
        )

        for fi in index.graph.functions.values():
            for line, field, labels, pushers in unguarded_peer_reads(
                flow, fi
            ):
                role = labels[0] if labels else "proc:?"
                witness = ()
                roles = flow.proc.roles_of(fi.key)
                if roles:
                    witness = tuple(
                        flow.proc.witness_path(fi.key, sorted(
                            roles, key=lambda r: r.module
                        )[0])
                    )
                yield Finding(
                    rule_id=self.rule_id, severity=self.severity,
                    path=fi.path, line=line, symbol=fi.qual,
                    message=(
                        f"guard field {field!r} read from a ring-popped "
                        f"frame in {role} with no guard comparison in "
                        f"the function; frames are produced by "
                        f"{', '.join(pushers)} in another process"
                    ),
                    hint=(
                        "compare the frame's generation/epoch against "
                        "the guard bound in the same acquisition before "
                        "trusting any versioned field"
                    ),
                    witness=witness,
                )


class RuleP004:
    """A ``%`` partition/shard selection whose right operand names a
    shard, partition, or bucket count, outside the one blessed
    implementation in ``utils/stablehash.py``: routing-hash drift.
    Ingest placed every row with ``stable_bucket``; any second modulus
    is a second opinion about where data lives, and the two WILL
    disagree the day one of them changes. File-local by design so
    ``pio check --changed`` pays one file, not the package horizon.

    Incident: the small-catalog retrieval bug shipped because a spec
    ("pad to the tile boundary") and an implementation (a sentinel that
    aliased a real item id at exactly ``% tile`` boundaries) drifted
    apart with no single source of truth. Routing has the same shape
    with higher stakes: the serving shard map and the ingest partitioner
    each held a private ``crc32(...) % n`` until PR 19 blessed
    ``stable_bucket`` -- a re-derived modulus routes a user's events to
    one shard and their queries to another, which reads as silent empty
    recommendations, not a crash.
    """

    rule_id = "P004"
    severity = "warning"

    def check(self, ctx) -> Iterator[Finding]:
        for line, text in routing_mod_sites(ctx.tree, ctx.path):
            symbol = _enclosing_symbol(ctx.tree, line)
            yield Finding(
                rule_id=self.rule_id, severity=self.severity,
                path=ctx.path, line=line, symbol=symbol,
                message=(
                    f"partition/shard selection `{text}` bypasses "
                    f"utils/stablehash.stable_bucket: a second modulus "
                    f"is a second routing opinion"
                ),
                hint=(
                    "route the selection through stable_bucket(key, n) "
                    "so ingest and serving keep one hash forever"
                ),
            )


class RuleP005(PackageRule):
    """A handshake artifact (portfile, ``wal.parts`` layout marker,
    manifest, READY file) published by ``os.replace``/``os.rename``
    without a preceding fsync on the path, a layout-marker rename whose
    directory entry is never fsynced before the function exits, or a
    READY-style handshake file consumed without any CRC/checksum verify
    in the reader. A handshake file IS a cross-process message: the peer
    that reads it cannot tell a durable publication from one the page
    cache will forget at the next power cut.

    Incident: the checkpoint-cursor rename originally shipped without
    the fsync-before-rename, and recovery after SIGKILL replayed from a
    cursor the filesystem had silently rolled back -- the same shape
    recurs at every process boundary artifact: the scorer portfile the
    supervisor polls, the ``wal.parts`` marker that is the partition
    layout's single source of truth, the registry manifest the fabric
    swaps to. Rename-then-crash without the covering fsyncs leaves the
    OLD bytes (file fsync missed) or NO directory entry (dir fsync
    missed), and the peer process handshakes against a ghost.
    """

    rule_id = "P005"
    severity = "error"

    _MESSAGES = {
        "unsynced-rename": (
            "handshake rename {detail} at line {line} has no covering "
            "fsync on the path: the peer can read pre-rename bytes "
            "after a crash"
        ),
        "layout-no-dirfsync": (
            "layout-marker rename {detail} at line {line} never fsyncs "
            "the directory entry: the marker can vanish at a power cut "
            "and the peer resolves the wrong layout"
        ),
    }
    _HINTS = {
        "unsynced-rename": (
            "write to a tmp path, flush+os.fsync the fd, then "
            "os.replace onto the handshake name"
        ),
        "layout-no-dirfsync": (
            "after os.replace, fsync the containing directory so the "
            "new entry itself is durable"
        ),
    }

    def check_package(self, index) -> Iterator[Finding]:
        flow = index.protocols()
        from predictionio_tpu.analysis.protocols import (
            handshake_findings,
            unverified_ready_reads,
        )

        for fi in index.graph.functions.values():
            for kind, line, detail in handshake_findings(flow, fi):
                yield Finding(
                    rule_id=self.rule_id, severity=self.severity,
                    path=fi.path, line=line, symbol=fi.qual,
                    message=self._MESSAGES[kind].format(
                        detail=detail, line=line
                    ),
                    hint=self._HINTS[kind],
                    witness=_hops(fi, line),
                )
            for line, detail in unverified_ready_reads(flow, fi):
                yield Finding(
                    rule_id=self.rule_id, severity=self.severity,
                    path=fi.path, line=line, symbol=fi.qual,
                    message=(
                        f"READY handshake file consumed at line {line} "
                        f"({detail}) with no CRC/checksum verify in the "
                        f"reader"
                    ),
                    hint=(
                        "verify the artifact's CRC before acting on the "
                        "READY signal; a torn write must read as absent, "
                        "not as ready"
                    ),
                    witness=_hops(fi, line),
                )


def _enclosing_symbol(tree: ast.AST, line: int) -> str:
    """Innermost def/class qualname containing ``line`` (module rules
    have no call-graph FunctionInfo to ask)."""
    best = "<module>"
    best_span = None

    def walk(node, prefix):
        nonlocal best, best_span
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                qual = f"{prefix}.{child.name}" if prefix else child.name
                end = getattr(child, "end_lineno", child.lineno)
                if child.lineno <= line <= end:
                    span = end - child.lineno
                    if best_span is None or span <= best_span:
                        best, best_span = qual, span
                    walk(child, qual)
            else:
                walk(child, prefix)

    walk(tree, "")
    return best


RULES = (RuleP001, RuleP002, RuleP003, RuleP004, RuleP005)
