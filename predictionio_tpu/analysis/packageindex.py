"""The shared whole-package analysis state: one build, every rule reads.

``PackageIndex`` bundles the three phase-2 layers -- call graph
(``callgraph``), thread roles (``threadroles``), lockset model
(``locksets``) -- built ONCE per ``pio check`` run over every parsed
module and handed to each package-level rule. Rules must not rebuild any
layer themselves: the sweep's time budget (<10 s on the 2-core box,
bench #10) is paid for by sharing this index.

``PackageRule`` is the base for rules that need cross-module context;
its ``check(ctx)`` convenience wraps a single module in a one-file index
so rule fixtures (``tests/test_analysis.py``) keep the same entry point
as per-module rules.
"""

from __future__ import annotations

from dataclasses import dataclass

from predictionio_tpu.analysis.callgraph import CallGraph
from predictionio_tpu.analysis.locksets import LockModel
from predictionio_tpu.analysis.threadroles import RoleInference


@dataclass
class PackageIndex:
    contexts: list
    graph: CallGraph
    roles: RoleInference
    locks: LockModel
    #: lazily-built phase-3 layer (exception-edge resource dataflow);
    #: J/C-only runs never pay for it
    _resources: object = None
    #: lazily-built sharding-facts layer (meshflow); non-S runs never
    #: pay for it
    _meshflow: object = None
    #: lazily-built cross-process protocol layer; non-P runs never pay
    #: for it
    _protocols: object = None

    #: single-entry memo: (context identity tuple, pinned context list,
    #: index). ``parse_module`` returns the SAME ModuleContext object for
    #: an unchanged file, so an identical identity tuple proves the trees
    #: are identical and the previous build (plus its lazy layers) can be
    #: reused -- the check+report flows and the fixture suite build the
    #: same index back to back. The pinned list keeps the contexts alive
    #: so their ids cannot be recycled while the memo holds them.
    _build_memo = None

    @classmethod
    def build(cls, contexts: list) -> "PackageIndex":
        contexts = list(contexts)
        key = tuple(map(id, contexts))
        memo = cls._build_memo
        if memo is not None and memo[0] == key:
            return memo[2]
        graph = CallGraph(contexts)
        index = cls(
            contexts=contexts,
            graph=graph,
            roles=RoleInference(graph),
            locks=LockModel(graph),
        )
        cls._build_memo = (key, contexts, index)
        return index

    def resources(self):
        """The shared :class:`~predictionio_tpu.analysis.flowgraph.
        ResourceFlow`: per-function flowgraphs + obligation summaries,
        built ONCE per index and cached alongside it (every R rule
        reads the same build)."""
        if self._resources is None:
            from predictionio_tpu.analysis.flowgraph import ResourceFlow

            self._resources = ResourceFlow(self)
        return self._resources

    def meshflow(self):
        """The shared :class:`~predictionio_tpu.analysis.meshflow.
        MeshFlow`: mesh/spec/collective sharding facts + contexts, built
        ONCE per index and cached (every S rule and ``--mesh-report``
        read the same build)."""
        if self._meshflow is None:
            from predictionio_tpu.analysis.meshflow import MeshFlow

            self._meshflow = MeshFlow(self)
        return self._meshflow

    def protocols(self):
        """The shared :class:`~predictionio_tpu.analysis.protocols.
        ProtocolFlow`: declared commit/publish/advance points classified
        over the call graph + process roles, built ONCE per index and
        cached (every P rule and ``--protocol-report`` read the same
        build)."""
        if self._protocols is None:
            from predictionio_tpu.analysis.protocols import ProtocolFlow

            self._protocols = ProtocolFlow(self)
        return self._protocols


class PackageRule:
    """Base for rules whose ``check_package(index)`` needs the whole
    program; ``check(ctx)`` adapts a single module for fixtures."""

    def check(self, ctx):
        yield from self.check_package(PackageIndex.build([ctx]))
