"""Sharding-facts dataflow: the substrate the S-series rules interpret.

Every queued scale direction (the MPMD multi-engine slice scheduler,
multi-host streamed epochs, the sharded serving fabric) reshuffles mesh
construction, PartitionSpecs, and collectives across modules -- and this
repo's worst historical bugs live exactly there: the 0.4.37 GSPMD
concat->reshard miscompile, pallas_call being opaque to GSPMD outside
shard_map, and tp-sharded adam-state donation pairing the wrong buffers.
This module gives ``pio check`` eyes on that surface: an abstract
sharding-facts domain interpreted over PR 13's package call graph.

What it tracks, package-wide:

- **mesh construction sites**: ``Mesh(grid, ("data", "model"))`` literals
  (axis names read from the literal) and package mesh FACTORIES --
  functions like ``parallel/mesh.py``'s ``local_mesh`` whose every return
  is a mesh literal (or a call to an already-summarized factory), folded
  to a fixpoint so ``mesh = local_mesh(2, 2)`` binds axis names
  ``("data", "model")`` at the assignment;
- **PartitionSpec / NamedSharding literals**: ``P("model")`` /
  ``PartitionSpec("data", None)`` calls and the axis names they bind
  (the ``P = PartitionSpec`` alias resolves by last dotted component);
- **shard_map sites**: body (resolved through ``functools.partial``
  wrappers, local nested defs, and higher-order parameter bindings --
  the ``seq_parallel_shard_map(body, mesh, axis)`` forwarding shape),
  bound mesh, and in/out spec axis strings;
- **jit/pjit placement**: ``in_shardings``/``out_shardings``/
  ``donate_argnums``/``donate_argnames`` (callee parameter names resolved
  the way J002 does, through the ``jit(make_step(...))`` factory form);
- **collectives**: ``psum`` / ``psum_scatter`` / ``all_gather`` /
  ``axis_index`` / ... with their STRING-LITERAL axis names (variable
  axis names are honestly unknown and stay out of the domain).

Values (mesh axes, spec axes) propagate interprocedurally: when a call
passes a known mesh or spec into a resolved callee, the callee's
parameter binds the value WITH the hand-off hop recorded, so a
``P("model")`` minted in ``parallel/als.py`` and consumed three frames
down in ``ops/als_gram.py`` is joined against the mesh it actually lands
on, and the finding renders the mint->consume chain.

Execution contexts propagate the same way: each ``shard_map`` site seeds
its body with the site's axis environment (the resolved mesh's axis
names, or UNKNOWN -- an unknown environment binds everything, the
analysis errs quiet), and each jitted function seeds a "traced, no
enclosing shard_map" context; both flow down ordinary call edges with a
parent map kept per (function, seed) for witness-path reconstruction.
The join over paths is per-path, not a merge: a collective reached under
one environment that binds its axis and another that does not is a
finding on the second path, with that path as the witness.

``MeshFlow`` also renders ``pio check --mesh-report``: the complete
inventory of mesh / shard_map / PartitionSpec / NamedSharding / sharded-
jit construction sites (text + JSON) -- the worklist for extracting the
shared MPMD executor layer.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from predictionio_tpu.analysis.astutil import call_name, dotted, keyword

#: call-name LAST components that construct the things we track
_MESH_CTORS = {"Mesh"}
_SPEC_CTORS = {"P", "PartitionSpec"}
_NAMED_CTORS = {"NamedSharding"}
_JIT_LAST = {"jit", "pjit"}
#: collectives with an axis-name argument, mapped to the positional index
#: of that argument (keyword ``axis_name`` always wins)
_COLLECTIVES = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "psum_scatter": 1,
    "all_gather": 1, "all_to_all": 1, "ppermute": 1, "pbroadcast": 1,
    "axis_index": 0, "axis_size": 0, "pcast_varying": 1,
}
#: global-placement calls that are per-shard nonsense inside a shard_map
#: body (S005)
_GLOBAL_PLACEMENT = {
    "device_put", "device_put_sharded", "with_sharding_constraint",
    "put_global",
}

_MAX_FIXPOINT = 5
_MAX_TRAIL = 8


def _last(name: str) -> str:
    return name.rsplit(".", 1)[-1]


@dataclass(frozen=True)
class MeshVal:
    """A mesh value with statically-known axis names, plus its mint site
    and the hand-off trail it rode to wherever it is being read."""

    axes: tuple
    path: str
    qual: str
    line: int
    trail: tuple = ()

    @property
    def site(self) -> str:
        return f"{self.path}:{self.qual}:{self.line}"


@dataclass(frozen=True)
class SpecVal:
    """A PartitionSpec/NamedSharding value: the axis names it binds
    (``None`` entries dropped -- they name no axis), mint site, trail."""

    axes: tuple
    kind: str             # "PartitionSpec" | "NamedSharding"
    path: str
    qual: str
    line: int
    trail: tuple = ()

    @property
    def site(self) -> str:
        return f"{self.path}:{self.qual}:{self.line}"


@dataclass
class Site:
    """One inventory row of the mesh-report."""

    kind: str    # mesh | partition_spec | named_sharding | shard_map | sharded_jit
    path: str
    qual: str
    line: int
    detail: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.kind}] {self.qual}: {self.detail}"


@dataclass
class ShardMapSite:
    fi: object                  # FunctionInfo of the enclosing function
    line: int
    call: ast.Call
    bodies: list                # resolved body FunctionInfos
    mesh_vals: list             # MeshVal candidates for the mesh argument
    spec_axes: tuple            # axis-name strings appearing in in/out specs


@dataclass
class CollectiveSite:
    fi: object
    line: int
    op: str
    axes: tuple                 # string-literal axis names ((), if variable)


@dataclass
class DonatedCallable:
    """A jit-with-donation the enclosing scope can call by name."""

    name: str                   # the dotted callee name ("step", "self._step")
    jit_line: int
    positions: tuple            # donated positional indices into the CALL args
    gated: bool                 # IS_LEGACY_JAX-gated donation (the fix shape)


@dataclass
class Context:
    """One propagated execution context: how a function can be entered."""

    kind: str                   # "shard_map" | "jit"
    seed: str                   # "path:qual:line" of the site / jitted def
    axes: "tuple | None"        # bound axis names; None = unknown (binds all)
    mesh: "MeshVal | None" = None


class MeshFlow:
    """The shared sharding-facts layer: built once per PackageIndex, read
    by every S rule and by ``--mesh-report``."""

    def __init__(self, index):
        self.index = index
        self.graph = index.graph
        #: fkey -> axis tuple for mesh-factory functions
        self.factory_axes: dict = {}
        #: path -> {name: set[val]} module-level constants
        self.module_consts: dict = {}
        #: fkey -> {name: set[val]} local value environments
        self.fn_env: dict = {}
        #: (fkey, param) -> set[val] interprocedural bindings
        self.param_vals: dict = {}
        #: (path, clsqual, attr) -> set[val]
        self.attr_vals: dict = {}
        self.sites: list = []                    # inventory rows
        self.shardmap_sites: list = []
        #: fkey -> list[CollectiveSite]
        self.collectives: dict = {}
        #: fkey -> list[(line, call name)] global-placement calls
        self.placements: dict = {}
        #: fkey -> first pallas_call line in the function
        self.pallas_fns: dict = {}
        #: fkey -> list[DonatedCallable] callable by that function
        self.donations: dict = {}
        #: fkey -> {ctx_id: (Context, parent fkey | None, call line | None)}
        self.contexts: dict = {}
        #: path -> [MeshVal] mesh literals minted anywhere in the module
        self.minted_meshes: dict = {}
        #: (FunctionInfo, NamedSharding ast.Call) pairs, recorded during
        #: the ONE site scan so S002 never re-walks the package
        self.named_sharding_calls: list = []
        #: id(ctx) -> [(node, qual)]: the module-level walk runs once per
        #: module, not once per pass that needs it
        self._mod_nodes_cache: dict = {}
        #: fkeys of functions that run under jit (jit(f)/pjit(f) call
        #: sites resolved through the graph -- factory forms included --
        #: plus @jit-style decorators); found during the ONE site scan,
        #: never by rebuilding rules_jax's per-module _JitIndex
        self.jit_entries: set = set()
        self._build_factories()
        self._build_module_consts()
        self._build_envs()
        self._flow_params()
        self._scan_sites()
        self._propagate_contexts()

    # -- literal extraction ---------------------------------------------------
    def _axes_of_mesh_call(self, call: ast.Call) -> "tuple | None":
        """Axis names of a ``Mesh(devices, axis_names)`` literal."""
        arg = None
        kw = keyword(call, "axis_names")
        if kw is not None:
            arg = kw.value
        elif len(call.args) >= 2:
            arg = call.args[1]
        if arg is None:
            return None
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return (arg.value,)
        if isinstance(arg, (ast.Tuple, ast.List)):
            names = []
            for el in arg.elts:
                if not (isinstance(el, ast.Constant) and isinstance(el.value, str)):
                    return None
                names.append(el.value)
            return tuple(names)
        return None

    def _axes_of_spec_call(self, call: ast.Call) -> tuple:
        """Axis-name strings a P/PartitionSpec literal binds (``None``
        placeholders and nested tuples like ``P(("data","model"))``
        flatten; non-constant entries are skipped, not guessed)."""
        names = []
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            for node in ast.walk(arg):
                if isinstance(node, ast.Constant) and isinstance(node.value, str):
                    names.append(node.value)
        return tuple(names)

    def _literal_val(self, owner_path, owner_qual, expr) -> "set | None":
        """Mesh/spec vals a LITERAL expression denotes, else None."""
        if not isinstance(expr, ast.Call):
            return None
        last = _last(call_name(expr))
        if last in _MESH_CTORS:
            axes = self._axes_of_mesh_call(expr)
            if axes is not None:
                return {MeshVal(axes, owner_path, owner_qual, expr.lineno)}
            return set()
        if last in _SPEC_CTORS:
            return {SpecVal(
                self._axes_of_spec_call(expr), "PartitionSpec",
                owner_path, owner_qual, expr.lineno,
            )}
        if last in _NAMED_CTORS and expr.args:
            spec_axes: tuple = ()
            if len(expr.args) >= 2:
                inner = self._literal_val(owner_path, owner_qual, expr.args[1])
                for v in inner or ():
                    if isinstance(v, SpecVal):
                        spec_axes = v.axes
            return {SpecVal(
                spec_axes, "NamedSharding", owner_path, owner_qual,
                expr.lineno,
            )}
        return None

    # -- factories ------------------------------------------------------------
    def _build_factories(self) -> None:
        """Functions whose every ``return`` is a mesh literal (or a call
        to an already-summarized factory) summarize to that axis tuple --
        ``parallel/mesh.py``'s ``local_mesh`` is the canonical entry."""
        for _ in range(3):
            grew = False
            for fi in self.graph.functions.values():
                if fi.key in self.factory_axes:
                    continue
                axes = self._factory_summary(fi)
                if axes is not None:
                    self.factory_axes[fi.key] = axes
                    grew = True
            if not grew:
                break

    def _factory_summary(self, fi) -> "tuple | None":
        axes: "tuple | None" = None
        saw_return = False
        for node in self.graph.body_nodes(fi.node):
            if not isinstance(node, ast.Return) or node.value is None:
                continue
            saw_return = True
            got = self._return_mesh_axes(fi, node.value)
            if got is None:
                return None
            if axes is None:
                axes = got
            elif axes != got:
                return None
        return axes if saw_return else None

    def _return_mesh_axes(self, fi, expr) -> "tuple | None":
        if isinstance(expr, ast.Call):
            last = _last(call_name(expr))
            if last in _MESH_CTORS:
                return self._axes_of_mesh_call(expr)
            for target in self.graph.resolve_call(fi, expr):
                if target.key in self.factory_axes:
                    return self.factory_axes[target.key]
        return None

    # -- environments ---------------------------------------------------------
    def _module_level_nodes(self, ctx):
        """Module statements outside any def/lambda (class bodies kept:
        class-level spec constants are real mint sites). Returns
        ``(node, qual)`` pairs with the enclosing-class qualname computed
        inline -- never ``ctx.symbol_for``, whose lazy full-module symbol
        map is exactly the cost the pre-commit budget cannot pay."""
        cached = self._mod_nodes_cache.get(id(ctx))
        if cached is None:
            cached = list(self._walk_module_level(ctx))
            self._mod_nodes_cache[id(ctx)] = cached
        return cached

    def _walk_module_level(self, ctx):
        stack = [(n, "<module>") for n in ast.iter_child_nodes(ctx.tree)]
        while stack:
            node, qual = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(node, ast.ClassDef):
                inner = node.name if qual == "<module>" else f"{qual}.{node.name}"
                yield node, qual
                stack.extend(
                    (n, inner) for n in ast.iter_child_nodes(node)
                )
                continue
            yield node, qual
            stack.extend((n, qual) for n in ast.iter_child_nodes(node))

    def _build_module_consts(self) -> None:
        for ctx in self.index.contexts:
            consts: dict = {}
            for node, _qual in self._module_level_nodes(ctx):
                if not isinstance(node, ast.Assign):
                    continue
                vals = self._literal_val(ctx.path, "<module>", node.value)
                if not vals:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        consts.setdefault(t.id, set()).update(vals)
            if consts:
                self.module_consts[ctx.path] = consts

    def _build_envs(self) -> None:
        # ONE Assign pass per function builds both the value env and the
        # donation map (the pre-commit budget pays for every extra body
        # walk)
        for fi in self.graph.functions.values():
            env: dict = {}
            for node in self.graph.body_nodes(fi.node):
                if not isinstance(node, ast.Assign):
                    continue
                if isinstance(node.value, ast.Call):
                    self._collect_donation(fi, node)
                vals = self._value_of(fi, node.value, env)
                if not vals:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        env.setdefault(t.id, set()).update(vals)
                    else:
                        d = dotted(t)
                        if d and d.startswith("self.") and d.count(".") == 1 \
                                and fi.cls is not None:
                            self.attr_vals.setdefault(
                                (fi.path, fi.cls, d[5:]), set()
                            ).update(vals)
            if env:
                self.fn_env[fi.key] = env

    def _value_of(self, fi, expr, env=None) -> set:
        """Mesh/spec vals an expression may denote: literals, local env,
        module constants, interprocedural param bindings, ``self.attr``,
        and calls to summarized mesh factories."""
        lit = self._literal_val(fi.path, fi.qual, expr)
        if lit is not None:
            return lit
        if isinstance(expr, ast.Call):
            out: set = set()
            for target in self.graph.resolve_call(fi, expr):
                axes = self.factory_axes.get(target.key)
                if axes is not None:
                    out.add(MeshVal(axes, fi.path, fi.qual, expr.lineno))
            return out
        if isinstance(expr, ast.Name):
            if env is None:
                env = self.fn_env.get(fi.key, {})
            hit = env.get(expr.id)
            if hit:
                return set(hit)
            bound = self.param_vals.get((fi.key, expr.id))
            if bound:
                return set(bound)
            if expr.id in fi.params():
                # a parameter SHADOWS any same-named module constant --
                # its value is whatever the caller passes, and with no
                # interprocedural binding that is honestly unknown
                return set()
            consts = self.module_consts.get(fi.path, {})
            hit = consts.get(expr.id)
            if hit:
                return set(hit)
            return set()
        d = dotted(expr)
        if d and d.startswith("self.") and d.count(".") == 1 and fi.cls:
            return set(self.attr_vals.get((fi.path, fi.cls, d[5:]), ()))
        return set()

    # -- interprocedural value flow ------------------------------------------
    def _flow_params(self) -> None:
        """Push known mesh/spec values through resolved call arguments
        into callee parameters, recording the hand-off hop -- iterated to
        a fixpoint so a mesh minted two frames up still lands.

        Gated for the pre-commit budget: only functions that can PRODUCE
        a value (a non-empty local env, a module with mesh/spec
        constants, class attrs holding values, or values already bound to
        their params) evaluate Name arguments; everything else evaluates
        only Call arguments (an inline factory/ctor literal can appear
        anywhere). ~95% of the package never touches the domain and
        skips the per-argument work entirely."""
        by_mod: dict = {}
        by_cls: dict = {}
        for fi in self.graph.functions.values():
            by_mod.setdefault(fi.path, []).append(fi.key)
            if fi.cls is not None:
                by_cls.setdefault((fi.path, fi.cls), []).append(fi.key)
        interesting: set = set(self.fn_env)
        for path in self.module_consts:
            interesting.update(by_mod.get(path, ()))
        for (path, cls, _attr) in self.attr_vals:
            interesting.update(by_cls.get((path, cls), ()))
        for _ in range(_MAX_FIXPOINT):
            changed = False
            for fi in self.graph.functions.values():
                rich = fi.key in interesting
                for cs in self.graph.callees(fi.key):
                    if not cs.targets or not (
                        cs.call.args or cs.call.keywords
                    ):
                        continue
                    changed |= self._flow_call(fi, cs, rich, interesting)
            if not changed:
                break

    def _flow_call(self, fi, cs, rich: bool, interesting: set) -> bool:
        changed = False
        hop = f"{fi.path}:{fi.qual}:{cs.line}"
        for target in cs.targets:
            params = target.params()
            offset = 1 if params[:1] == ["self"] else 0
            pairs = []
            for i, arg in enumerate(cs.call.args):
                if i + offset < len(params):
                    pairs.append((params[i + offset], arg))
            for kw in cs.call.keywords:
                if kw.arg is not None and kw.arg in params:
                    pairs.append((kw.arg, kw.value))
            for pname, arg in pairs:
                if not rich and not isinstance(arg, ast.Call):
                    continue
                vals = self._value_of(fi, arg)
                if not vals:
                    continue
                cur = self.param_vals.setdefault((target.key, pname), set())
                for v in vals:
                    if len(v.trail) >= _MAX_TRAIL:
                        continue
                    forwarded = self._with_hop(v, hop)
                    if forwarded not in cur:
                        cur.add(forwarded)
                        changed = True
                        interesting.add(target.key)
        return changed

    @staticmethod
    def _with_hop(val, hop: str):
        if hop in val.trail or hop == val.site:
            return val
        if isinstance(val, MeshVal):
            return MeshVal(val.axes, val.path, val.qual, val.line,
                           val.trail + (hop,))
        return SpecVal(val.axes, val.kind, val.path, val.qual, val.line,
                       val.trail + (hop,))

    # -- site scan ------------------------------------------------------------
    def _scan_sites(self) -> None:
        for fi in self.graph.functions.values():
            for node in self.graph.body_nodes(fi.node):
                if isinstance(node, ast.Call):
                    self._classify_call(fi, fi.path, fi.qual, node)
            if self._has_jit_decorator(fi.node):
                self.jit_entries.add(fi.key)
        for ctx in self.index.contexts:
            for node, qual in self._module_level_nodes(ctx):
                if isinstance(node, ast.Call):
                    self._classify_call(None, ctx.path, qual, node)
        self.sites.sort(key=lambda s: (s.path, s.line, s.kind))
        self.shardmap_sites.sort(key=lambda s: (s.fi.path, s.line))

    def _classify_call(self, fi, path: str, qual: str, call: ast.Call) -> None:
        name = call_name(call)
        last = _last(name)
        if last in _MESH_CTORS:
            axes = self._axes_of_mesh_call(call)
            if axes is not None:
                self.minted_meshes.setdefault(path, []).append(
                    MeshVal(axes, path, qual, call.lineno)
                )
            self.sites.append(Site(
                "mesh", path, qual, call.lineno,
                f"axes={list(axes)}" if axes is not None else "axes=<dynamic>",
            ))
        elif last in _SPEC_CTORS:
            axes = self._axes_of_spec_call(call)
            self.sites.append(Site(
                "partition_spec", path, qual, call.lineno,
                f"binds={list(axes)}" if axes else "replicated",
            ))
        elif last in _NAMED_CTORS:
            axes: tuple = ()
            if len(call.args) >= 2:
                inner = self._literal_val(path, qual, call.args[1])
                for v in inner or ():
                    if isinstance(v, SpecVal):
                        axes = v.axes
            if fi is not None and call.args:
                self.named_sharding_calls.append((fi, call))
            self.sites.append(Site(
                "named_sharding", path, qual, call.lineno,
                f"spec binds={list(axes)}" if axes else "spec=<resolved at use>",
            ))
        elif self._is_shard_map_call(fi, call, last):
            if fi is not None:
                self._record_shard_map(fi, call)
        elif last in _JIT_LAST:
            if call.args:
                if fi is not None:
                    for target in self.graph.resolve_callable(
                        fi, call.args[0]
                    ):
                        self.jit_entries.add(target.key)
                elif isinstance(call.args[0], ast.Name):
                    mod = self.graph.by_path.get(path)
                    hit = mod.top.get(call.args[0].id) if mod else None
                    if hit is not None:
                        self.jit_entries.add(hit.key)
            shard_kws = [
                kw.arg for kw in call.keywords
                if kw.arg in ("in_shardings", "out_shardings",
                              "donate_argnums", "donate_argnames")
            ]
            if shard_kws:
                self.sites.append(Site(
                    "sharded_jit", path, qual, call.lineno,
                    f"{name}({', '.join(sorted(shard_kws))})",
                ))
        elif last in _COLLECTIVES and fi is not None:
            axes = self._collective_axes(call, last)
            self.collectives.setdefault(fi.key, []).append(
                CollectiveSite(fi, call.lineno, last, axes)
            )
        elif last in _GLOBAL_PLACEMENT and fi is not None:
            self.placements.setdefault(fi.key, []).append((call.lineno, name))
        elif last == "pallas_call" and fi is not None:
            self.pallas_fns.setdefault(fi.key, call.lineno)

    @staticmethod
    def _has_jit_decorator(node: ast.AST) -> bool:
        """``@jax.jit`` / ``@jit`` / ``@functools.partial(jax.jit, ...)``
        decorations, checked on the def node alone (no module walk)."""
        for dec in getattr(node, "decorator_list", ()):
            d = dotted(dec)
            if d is not None and _last(d) in _JIT_LAST:
                return True
            if isinstance(dec, ast.Call):
                name = call_name(dec)
                if _last(name) in _JIT_LAST:
                    return True
                if _last(name) == "partial" and dec.args and _last(
                    dotted(dec.args[0]) or ""
                ) in _JIT_LAST:
                    return True
        return False

    def _is_shard_map_call(self, fi, call: ast.Call, last: str) -> bool:
        """A shard_map-former: the jax API name itself, a call with a
        ``mesh`` keyword, or a package wrapper whose resolved signature
        takes a ``mesh`` parameter (``seq_parallel_shard_map``). Plain
        helpers that merely END with ``_shard_map`` (this analyzer's
        own ``_record_shard_map``) do not count. The drift shim's
        internal forwarding (``utils/jax_compat.py``) is excluded too:
        seeding contexts there would union every caller's body against
        every caller's mesh."""
        if not last.endswith("shard_map") or not call.args:
            return False
        if fi is not None and fi.path.endswith("utils/jax_compat.py"):
            return False
        if last == "shard_map" or keyword(call, "mesh") is not None:
            return True
        if fi is not None:
            for target in self.graph.resolve_callable(fi, call.func):
                if "mesh" in target.params():
                    return True
        return False

    def _collective_axes(self, call: ast.Call, op: str) -> tuple:
        arg = None
        kw = keyword(call, "axis_name")
        if kw is not None:
            arg = kw.value
        else:
            idx = _COLLECTIVES[op]
            if idx < len(call.args):
                arg = call.args[idx]
        if arg is None:
            return ()
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return (arg.value,)
        if isinstance(arg, (ast.Tuple, ast.List)):
            names = []
            for el in arg.elts:
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    names.append(el.value)
                else:
                    return ()
            return tuple(names)
        return ()

    def _record_shard_map(self, fi, call: ast.Call) -> None:
        body_expr = call.args[0] if call.args else None
        bodies: list = []
        if body_expr is not None:
            bodies = self.graph.resolve_callable(fi, body_expr)
            if not bodies and isinstance(body_expr, ast.Name) \
                    and body_expr.id in set(fi.params()):
                bodies = sorted(
                    self.graph.param_bindings.get(
                        (fi.key, body_expr.id), ()
                    ),
                    key=lambda f: f.key,
                )
        mesh_expr = None
        kw = keyword(call, "mesh")
        if kw is not None:
            mesh_expr = kw.value
        elif len(call.args) >= 2:
            mesh_expr = call.args[1]
        mesh_vals = [
            v for v in (
                self._value_of(fi, mesh_expr) if mesh_expr is not None else ()
            )
            if isinstance(v, MeshVal)
        ]
        spec_axes: list = []
        for kwname in ("in_specs", "out_specs"):
            kw = keyword(call, kwname)
            if kw is not None:
                for node in ast.walk(kw.value):
                    if isinstance(node, ast.Call) and \
                            _last(call_name(node)) in _SPEC_CTORS:
                        spec_axes.extend(self._axes_of_spec_call(node))
        # a FORWARDING site -- body and mesh both bare parameters of the
        # enclosing wrapper (the seq_parallel_shard_map shape) -- must
        # not seed contexts: param bindings union EVERY caller's body
        # against EVERY caller's mesh, convicting correct code under a
        # mesh it never runs with. The caller-side sites (detected via
        # the wrapper's `mesh` parameter) carry the per-caller pairing.
        params = set(fi.params())
        forwarding = (
            isinstance(body_expr, ast.Name) and body_expr.id in params
            and isinstance(mesh_expr, ast.Name) and mesh_expr.id in params
        )
        if not forwarding:
            self.shardmap_sites.append(ShardMapSite(
                fi, call.lineno, call, bodies, mesh_vals,
                tuple(dict.fromkeys(spec_axes)),
            ))
        mesh_detail = sorted({str(list(v.axes)) for v in mesh_vals})
        self.sites.append(Site(
            "shard_map", fi.path, fi.qual, call.lineno,
            "forwarding wrapper (callers carry the body/mesh pairing)"
            if forwarding else
            "body={} mesh axes={} specs name {}".format(
                ",".join(sorted(b.qual for b in bodies)) or "<unresolved>",
                "/".join(mesh_detail) if mesh_detail else "<unresolved>",
                sorted(set(spec_axes)) if spec_axes else "[]",
            ),
        ))

    # -- donation map (S004) --------------------------------------------------
    def _collect_donation(self, fi, node: ast.Assign) -> None:
        """``x = jit(body, donate_argnums=...)`` / ``self.attr = jit(...)``
        assignments visible to this function: call-site positions that
        hand their buffer over. donate_argnames resolves against the
        jitted callee's parameters (J002's resolution, via the graph)."""
        don = self._donation_of(fi, node.value)
        if don is None:
            return
        positions, gated = don
        for t in node.targets:
            d = dotted(t)
            if d is None:
                continue
            rec = DonatedCallable(d, node.value.lineno, positions, gated)
            self.donations.setdefault(fi.key, []).append(rec)
            # class-attr donations are callable from sibling methods too
            if d.startswith("self.") and fi.cls is not None:
                key = (fi.path, fi.cls, "__donated__")
                self.attr_vals.setdefault(key, set()).add(
                    (d, node.value.lineno, positions, gated)
                )

    def _donation_of(self, fi, call: ast.Call):
        """(donated positions, gated?) of a jit call, else None."""
        if _last(call_name(call)) not in _JIT_LAST:
            return None
        params: list = []
        if call.args:
            for target in self.graph.resolve_callable(fi, call.args[0]):
                params = target.params()
                break
        positions: list = []
        gated = False
        for kwname in ("donate_argnums", "donate_argnames"):
            kw = keyword(call, kwname)
            if kw is None:
                continue
            value = kw.value
            if isinstance(value, ast.IfExp) and self._legacy_gated(value.test):
                gated = True
                continue
            if kwname == "donate_argnums":
                for c in ast.walk(value):
                    if isinstance(c, ast.Constant) and isinstance(c.value, int):
                        positions.append(c.value)
            else:
                for c in ast.walk(value):
                    if isinstance(c, ast.Constant) and isinstance(c.value, str):
                        if c.value in params:
                            positions.append(params.index(c.value))
        if not positions and not gated:
            return None
        return tuple(sorted(set(positions))), gated

    @staticmethod
    def _legacy_gated(test: ast.AST) -> bool:
        for n in ast.walk(test):
            if isinstance(n, ast.Name) and n.id == "IS_LEGACY_JAX":
                return True
            if isinstance(n, ast.Attribute) and n.attr == "IS_LEGACY_JAX":
                return True
        return False

    def donated_callables(self, fi) -> list:
        """DonatedCallables callable from ``fi``: its own assignments plus
        ``self.attr`` donations recorded anywhere on its class."""
        out = list(self.donations.get(fi.key, ()))
        if fi.cls is not None:
            for rec in self.attr_vals.get(
                (fi.path, fi.cls, "__donated__"), ()
            ):
                if isinstance(rec, tuple):
                    name, line, positions, gated = rec
                    if not any(d.name == name for d in out):
                        out.append(DonatedCallable(name, line, positions, gated))
        return out

    # -- context propagation --------------------------------------------------
    def _propagate_contexts(self) -> None:
        seeds: list = []   # (Context, body fkey)
        for site in self.shardmap_sites:
            if site.mesh_vals:
                # one context per resolved mesh candidate: a body fed two
                # different meshes is checked against each (per-path join)
                for mv in site.mesh_vals:
                    ctx = Context(
                        "shard_map",
                        f"{site.fi.path}:{site.fi.qual}:{site.line}",
                        mv.axes, mesh=mv,
                    )
                    for body in site.bodies:
                        seeds.append((ctx, body.key))
                continue
            ctx = Context(
                "shard_map",
                f"{site.fi.path}:{site.fi.qual}:{site.line}", None,
            )
            for body in site.bodies:
                seeds.append((ctx, body.key))
        for fkey in sorted(self.jit_entries):
            fi = self.graph.functions.get(fkey)
            if fi is None:
                continue
            seeds.append((
                Context("jit", f"{fi.path}:{fi.qual}:{fi.node.lineno}", None),
                fi.key,
            ))
        work: list = []
        for ctx, fkey in seeds:
            if fkey not in self.graph.functions:
                continue
            store = self.contexts.setdefault(fkey, {})
            ckey = (ctx.seed, ctx.axes)   # one seed, two meshes = two paths
            if ckey not in store:
                store[ckey] = (ctx, None, None)
                work.append((fkey, ctx))
        while work:
            fkey, ctx = work.pop()
            ckey = (ctx.seed, ctx.axes)
            for cs in self.graph.callees(fkey):
                for target in cs.targets:
                    store = self.contexts.setdefault(target.key, {})
                    if ckey in store:
                        continue
                    store[ckey] = (ctx, fkey, cs.line)
                    work.append((target.key, ctx))

    def contexts_of(self, fkey, kind: "str | None" = None) -> list:
        out = []
        for ctx, _parent, _line in self.contexts.get(fkey, {}).values():
            if kind is None or ctx.kind == kind:
                out.append(ctx)
        return out

    def witness_path(self, fkey, ctx: Context) -> list:
        """Call chain from the context's seed down to ``fkey``:
        ``["path:qual:line", ...]`` hops, seed site first."""
        ckey = (ctx.seed, ctx.axes)
        chain: list = []
        cur = fkey
        seen: set = set()
        while cur is not None and cur not in seen:
            seen.add(cur)
            rec = self.contexts.get(cur, {}).get(ckey)
            if rec is None:
                break
            _ctx, parent, line = rec
            fi = self.graph.functions.get(cur)
            if parent is None:
                # the seed's entry function itself (the shard_map body /
                # the jitted def), line-less like threadroles' entry hop
                chain.append(f"{fi.path}:{fi.qual}" if fi else str(cur))
                break
            chain.append(f"{fi.path}:{fi.qual}:{line}" if fi else str(cur))
            cur = parent
        chain.reverse()
        return [ctx.seed] + chain

    def env_meshes(self, fkey) -> list:
        """Every MeshVal visible to a function (locals, params, module
        constants, class attrs) -- the S003 multi-axis-mesh evidence."""
        out: list = []
        fi = self.graph.functions.get(fkey)
        if fi is None:
            return out
        for vals in self.fn_env.get(fkey, {}).values():
            out.extend(v for v in vals if isinstance(v, MeshVal))
        for (key, _param), vals in self.param_vals.items():
            if key == fkey:
                out.extend(v for v in vals if isinstance(v, MeshVal))
        for vals in self.module_consts.get(fi.path, {}).values():
            out.extend(v for v in vals if isinstance(v, MeshVal))
        if fi.cls is not None:
            for (path, cls, attr), vals in self.attr_vals.items():
                if path == fi.path and cls == fi.cls and attr != "__donated__":
                    out.extend(v for v in vals if isinstance(v, MeshVal))
        return out

    def module_meshes(self, path: str) -> list:
        """Every statically-known MeshVal a module mints or binds: mesh
        literals anywhere in the file plus factory-derived values in any
        of its function environments (the coarse S003 evidence -- a
        module that builds a 2x2 mesh somewhere is doing multi-axis
        placement)."""
        out = list(self.minted_meshes.get(path, ()))
        for (p, _qual), env in self.fn_env.items():
            if p != path:
                continue
            for vals in env.values():
                out.extend(v for v in vals if isinstance(v, MeshVal))
        for vals in self.module_consts.get(path, {}).values():
            out.extend(v for v in vals if isinstance(v, MeshVal))
        return out

    def report_sites(self) -> list:
        """The ``--mesh-report`` inventory as uniform site dicts for the
        shared report writer (``engine.render_site_report_*``): every
        mesh / PartitionSpec / NamedSharding / shard_map / sharded-jit
        construction site -- the worklist for extracting the shared MPMD
        executor layer."""
        return [
            {
                "kind": s.kind, "path": s.path, "qual": s.qual,
                "line": s.line, "detail": s.detail,
            }
            for s in self.sites
        ]
