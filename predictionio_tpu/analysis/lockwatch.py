"""Runtime lock-order watcher: C001's reality check, C006's witness.

The static C001/C006 rules reason about lexical nesting and call-graph
paths; this module records what threads ACTUALLY do. Beyond order edges,
every acquisition records the lockset HELD at that moment (``held_at``),
and ``runtime_witness()`` renders what tier-1 observed at the lock sites
a static C006 race finding names -- or their absence -- so the repo-wide
gate can attach runtime evidence to a static report. ``install()`` replaces
``threading.Lock``/``threading.RLock`` with factories that hand
predictionio_tpu code (decided by the caller's module at construction
time -- one frame peek per ``Lock()``, no ``sys.settrace``) a thin wrapper.
Every acquisition while other watched locks are held records an order edge
``(held_site -> acquired_site)``; observing both ``A -> B`` and ``B -> A``
-- from any pair of threads, without needing the timing to actually
deadlock -- is an inversion.

Lock identity is the CONSTRUCTION SITE (``module:lineno``), not the
instance: two instances of the same class's ``self._lock`` share a site,
so per-instance locks validate the class-level ordering policy the static
rule checks. Inversions are recorded, never raised mid-acquire (failing
inside arbitrary lock paths would turn a diagnosis into a heisenbug);
the pytest hook in ``tests/conftest.py`` fails the test that produced one.

Enabled under pytest by default (``PIO_LOCKWATCH=0`` opts out); never
enabled in production servers -- the wrapper costs a dict hit per acquire.
"""

from __future__ import annotations

import sys
import threading
from dataclasses import dataclass, field


@dataclass
class Inversion:
    first: tuple[str, str]   # the edge seen earlier (site_a -> site_b)
    second: tuple[str, str]  # the contradicting edge
    thread: str
    detail: str = ""


@dataclass
class LockWatch:
    """Edge registry. One global instance backs ``install()``; tests can
    build private instances and wrap locks explicitly via ``wrap()``."""

    #: (site_a, site_b) -> thread name that first recorded the edge
    edges: dict = field(default_factory=dict)
    inversions: list = field(default_factory=list)
    #: site -> set of frozensets: every distinct lockset observed HELD at
    #: an acquisition of that site (the empty frozenset = acquired bare).
    #: This is the runtime half of C006: a static disjoint-lockset race
    #: finding can cite what locks tier-1 actually held at the sites in
    #: question -- or their absence.
    held_at: dict = field(default_factory=dict)
    _state: threading.local = field(default_factory=threading.local)
    _mutex: threading.Lock = field(default_factory=threading.Lock)

    def _held(self) -> list:
        held = getattr(self._state, "held", None)
        if held is None:
            held = self._state.held = []
        return held

    def note_acquired(self, lock: "_WatchedLock") -> None:
        held = self._held()
        for entry in held:
            if entry[0] is lock:
                entry[1] += 1  # reentrant re-acquire: no new edges
                return
        new_edges = []
        for entry in held:
            a, b = entry[0].site, lock.site
            if a != b:
                new_edges.append((a, b))
        held_sites = frozenset(e[0].site for e in held)
        held.append([lock, 1])
        # racy membership pre-check (GIL-safe): the steady state -- this
        # site already observed with this held-set, no new edges -- pays
        # no mutex at all, so watched locks stay near-transparent
        known = self.held_at.get(lock.site)
        need_record = known is None or held_sites not in known
        if not new_edges and not need_record:
            return
        with self._mutex:
            if need_record:
                self.held_at.setdefault(lock.site, set()).add(held_sites)
            if not new_edges:
                return
            for a, b in new_edges:
                self.edges.setdefault((a, b), threading.current_thread().name)
                if (b, a) in self.edges:
                    self.inversions.append(Inversion(
                        first=(b, a), second=(a, b),
                        thread=threading.current_thread().name,
                        detail=(
                            f"{a} -> {b} (thread "
                            f"{threading.current_thread().name}) contradicts "
                            f"{b} -> {a} (thread {self.edges[(b, a)]})"
                        ),
                    ))

    def note_released(self, lock: "_WatchedLock") -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is lock:
                held[i][1] -= 1
                if held[i][1] <= 0:
                    del held[i]
                return

    def wrap(self, real_lock, site: str) -> "_WatchedLock":
        return _WatchedLock(real_lock, site, self)

    def runtime_witness(self, sites: "list[str]") -> str:
        """What the run actually observed at the given lock construction
        sites (``module:lineno``): exact site first, tolerating a +/-2
        line drift between the static declaration line and the runtime
        construction frame (multi-line assignments) -- never the whole
        module, which would present other locks' acquisitions as
        evidence for this one. Used by the tier-1 gate to annotate C006
        findings with runtime evidence -- or its absence."""
        if not sites:
            return "no lock sites to witness"
        with self._mutex:
            snapshot = {k: set(v) for k, v in self.held_at.items()}
        parts = []
        for site in sites:
            module, _, line_s = site.rpartition(":")
            hits = {k: v for k, v in snapshot.items() if k == site}
            if not hits and line_s.isdigit():
                line = int(line_s)
                hits = {
                    k: v for k, v in snapshot.items()
                    if k.rsplit(":", 1)[0] == module
                    and k.rsplit(":", 1)[1].isdigit()
                    and abs(int(k.rsplit(":", 1)[1]) - line) <= 2
                }
            if not hits:
                parts.append(f"{site}: never acquired under lockwatch")
                continue
            for k, locksets in sorted(hits.items()):
                rendered = sorted(
                    "{" + ", ".join(sorted(ls)) + "}" if ls else "{}"
                    for ls in locksets
                )
                parts.append(f"{k}: acquired holding {', '.join(rendered)}")
        return "; ".join(parts)


class _WatchedLock:
    """Duck-types a lock: acquire/release/locked/context manager; anything
    else (Condition's ``_is_owned`` etc.) delegates to the real lock."""

    def __init__(self, real, site: str, watch: LockWatch):
        self._real = real
        self.site = site
        self._watch = watch

    def acquire(self, *args, **kwargs):
        got = self._real.acquire(*args, **kwargs)
        if got:
            self._watch.note_acquired(self)
        return got

    def release(self):
        self._real.release()
        self._watch.note_released(self)

    def locked(self):
        return self._real.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __getattr__(self, name):
        return getattr(self._real, name)


_GLOBAL = LockWatch()
_REAL_LOCK = None
_REAL_RLOCK = None


def global_watch() -> LockWatch:
    return _GLOBAL


def _watched_module() -> str | None:
    """The module of the frame constructing the lock; only
    predictionio_tpu's own locks are wrapped (stdlib queue/logging/etc.
    keep real locks untouched)."""
    try:
        mod = sys._getframe(2).f_globals.get("__name__", "")
    except ValueError:
        return None
    if mod.startswith("predictionio_tpu") and not mod.startswith(
        "predictionio_tpu.analysis.lockwatch"
    ):
        frame = sys._getframe(2)
        return f"{mod}:{frame.f_lineno}"
    return None


def install() -> None:
    """Patch ``threading.Lock``/``RLock`` so predictionio_tpu-constructed
    locks are watched. Idempotent; ``uninstall()`` restores."""
    global _REAL_LOCK, _REAL_RLOCK
    if _REAL_LOCK is not None:
        return
    _REAL_LOCK = threading.Lock
    _REAL_RLOCK = threading.RLock

    def make_lock():
        site = _watched_module()
        real = _REAL_LOCK()
        return _GLOBAL.wrap(real, site) if site else real

    def make_rlock():
        site = _watched_module()
        real = _REAL_RLOCK()
        return _GLOBAL.wrap(real, site) if site else real

    threading.Lock = make_lock
    threading.RLock = make_rlock


def uninstall() -> None:
    global _REAL_LOCK, _REAL_RLOCK
    if _REAL_LOCK is None:
        return
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    _REAL_LOCK = _REAL_RLOCK = None


def installed() -> bool:
    return _REAL_LOCK is not None
