"""Tiny AST helpers shared by the rule families."""

from __future__ import annotations

import ast
from typing import Iterator


def dotted(node: ast.AST) -> str | None:
    """``jax.experimental.shard_map`` for a Name/Attribute chain, else None."""
    # bare Name and one-level Attribute cover most call sites; this runs
    # hundreds of thousands of times per sweep, so skip the list+join
    # machinery for them
    if isinstance(node, ast.Name):
        return node.id
    if not isinstance(node, ast.Attribute):
        return None
    value = node.value
    if isinstance(value, ast.Name):
        return f"{value.id}.{node.attr}"
    parts: list[str] = [node.attr]
    node = value
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return ".".join(parts)
    return None


def walk_calls(node: ast.AST) -> Iterator[ast.Call]:
    for child in ast.walk(node):
        if isinstance(child, ast.Call):
            yield child


def names_in(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def call_name(call: ast.Call) -> str:
    return dotted(call.func) or ""


def const_strings(node: ast.AST) -> set[str]:
    return {
        c.value
        for c in ast.walk(node)
        if isinstance(c, ast.Constant) and isinstance(c.value, str)
    }


def keyword(call: ast.Call, name: str) -> ast.keyword | None:
    for kw in call.keywords:
        if kw.arg == name:
            return kw
    return None


def func_defs(tree: ast.AST) -> dict[str, list[ast.FunctionDef]]:
    """All function defs in the module, keyed by bare name (nested included)."""
    out: dict[str, list[ast.FunctionDef]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, []).append(node)
    return out
