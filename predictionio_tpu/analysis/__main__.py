"""``python -m predictionio_tpu.analysis [--self-check] [--explain RULE]
[--changed] [paths...]`` -- the same engine ``pio check`` fronts,
importable without the CLI."""

import sys

from predictionio_tpu.analysis.engine import run_cli

if __name__ == "__main__":
    sys.exit(run_cli())
