"""S-series rules: mesh / PartitionSpec / collective sharding semantics.

Built on ``analysis/meshflow.py``: the abstract sharding-facts domain
(mesh construction sites, spec literals, shard_map bindings, collectives,
donation maps) interpreted over PR 13's package call graph. Each finding
carries the mesh/spec CONSTRUCTION sites involved (``Finding.related``,
rendered as SARIF relatedLocations) plus a witness call path from the
binding site to the violation (``Finding.witness``, rendered as SARIF
codeFlows) -- a sharding bug report without the mesh it happened on is
not actionable.

Every rule class docstring IS its incident-catalog entry: ``pio check
--explain RULE`` prints it, and the S table in
``docs/static_analysis.md`` is generated from it.
"""

from __future__ import annotations

import ast
from typing import Iterator

from predictionio_tpu.analysis.astutil import dotted
from predictionio_tpu.analysis.engine import Finding
from predictionio_tpu.analysis.meshflow import MeshVal, SpecVal
from predictionio_tpu.analysis.packageindex import PackageIndex, PackageRule


def _related_of(*vals) -> tuple:
    """(path, line, label) mint-site triples for the report."""
    out = []
    for v in vals:
        if v is None:
            continue
        if isinstance(v, MeshVal):
            out.append((v.path, v.line, f"mesh constructed here (axes={list(v.axes)})"))
        elif isinstance(v, SpecVal):
            out.append((v.path, v.line,
                        f"{v.kind} constructed here (binds={list(v.axes)})"))
    return tuple(out)


def _trail_hops(val) -> list:
    return list(val.trail)


class RuleS001(PackageRule):
    """A collective (``psum``/``psum_scatter``/``all_gather``/
    ``axis_index``/...) over a string-literal axis name that no
    enclosing ``shard_map``/mesh binds on the witness path: either the
    function runs as (or below) a shard_map body whose resolved mesh
    lacks the axis, or it is reached from a jitted scope with no
    shard_map binding any axis at all. Unknown meshes and variable axis
    names stay silent -- the rule convicts only paths where the binding
    environment is statically known.

    Incident: the queued MPMD device-slice refactor (arxiv 2412.14374)
    ends the era of the global ``("data", "model")`` mesh singleton --
    per-engine slices mint their own meshes, and a
    ``psum_scatter(..., "model")`` helper that silently assumed the
    full mesh becomes an unbound-axis-name crash (or, under pmap-era
    fallbacks, a silent wrong-denominator mean) the first time a
    data-only slice calls it. ``parallel/als.py``'s
    ``_sharded_block_body`` is exactly such a helper three frames below
    its mesh construction."""

    rule_id = "S001"
    severity = "error"

    def check_package(self, index: PackageIndex) -> Iterator[Finding]:
        flow = index.meshflow()
        for fkey, sites in sorted(flow.collectives.items()):
            fi = flow.graph.functions.get(fkey)
            if fi is None:
                continue
            smap_ctxs = flow.contexts_of(fkey, "shard_map")
            jit_ctxs = flow.contexts_of(fkey, "jit")
            for site in sites:
                if not site.axes:
                    continue   # variable axis name: honestly unknown
                yield from self._check_site(flow, fi, site, smap_ctxs, jit_ctxs)

    def _check_site(self, flow, fi, site, smap_ctxs, jit_ctxs):
        for ctx in smap_ctxs:
            if ctx.axes is None:
                continue   # unknown mesh binds everything: err quiet
            missing = [a for a in site.axes if a not in ctx.axes]
            if not missing:
                continue
            hops = tuple(
                flow.witness_path(fi.key, ctx)
                + [f"{fi.path}:{fi.qual}:{site.line}"]
            )
            yield Finding(
                self.rule_id, self.severity, fi.path, site.line, fi.qual,
                f"collective `{site.op}` over axis "
                f"{'/'.join(repr(m) for m in missing)} which the enclosing "
                f"shard_map's mesh (axes={list(ctx.axes)}) does not bind "
                f"(witness path: {' -> '.join(hops)})",
                "run the collective over an axis of the mesh the shard_map "
                "actually binds, or thread the right mesh to this call",
                witness=hops,
                related=_related_of(ctx.mesh),
            )
        # per-path, never per-function: a shard_map route elsewhere must
        # not amnesty a separate unwrapped jit path to the same
        # collective (context propagation does not cross shard_map
        # boundaries, so a jit context here IS an unwrapped call chain)
        if jit_ctxs:
            ctx = jit_ctxs[0]
            hops = tuple(
                flow.witness_path(fi.key, ctx)
                + [f"{fi.path}:{fi.qual}:{site.line}"]
            )
            yield Finding(
                self.rule_id, self.severity, fi.path, site.line, fi.qual,
                f"collective `{site.op}` over axis "
                f"{'/'.join(repr(a) for a in site.axes)} with no enclosing "
                f"shard_map binding it on the witness path from the jitted "
                f"scope (witness path: {' -> '.join(hops)})",
                "wrap the collective-running body in shard_map over a mesh "
                "that binds the axis (the parallel/als.py routing)",
                witness=hops,
            )


class RuleS002(PackageRule):
    """A PartitionSpec placed on a mesh whose axis names do not include
    the spec's: ``NamedSharding(mesh, P("model"))`` -- or shard_map
    in/out specs naming an axis -- where the mesh that actually arrives
    (resolved interprocedurally through the call graph, so a spec minted
    in one module and consumed frames down in another is joined against
    the real mesh) lacks that axis name. Both construction sites land in
    the finding.

    Incident: the exact hazard of the MPMD slice refactor, where meshes
    stop being global singletons -- today every mesh is
    ``local_mesh()``'s ``("data", "model")`` and a stray ``P("model")``
    can't miss; the moment per-engine slices mint single-axis meshes, a
    spec routed onto the wrong mesh raises at best
    (``KeyError: 'model'``) and at worst silently replicates an array
    the caller believed was sharded -- the memory-blowup twin of the
    0.4.37 concat->reshard incident (J005)."""

    rule_id = "S002"
    severity = "error"

    def check_package(self, index: PackageIndex) -> Iterator[Finding]:
        flow = index.meshflow()
        seen: set = set()
        # NamedSharding call sites come from meshflow's ONE site scan --
        # re-walking every body here would double the package traversal
        # on the pre-commit path
        for fi, node in flow.named_sharding_calls:
            yield from self._check_named(flow, fi, node, seen)
        for site in flow.shardmap_sites:
            yield from self._check_shard_map(flow, site, seen)

    def _check_named(self, flow, fi, call, seen):
        mesh_vals = [
            v for v in flow._value_of(fi, call.args[0])
            if isinstance(v, MeshVal)
        ]
        spec_vals = []
        if len(call.args) >= 2:
            spec_vals = [
                v for v in flow._value_of(fi, call.args[1])
                if isinstance(v, SpecVal)
            ]
        for mv in mesh_vals:
            for sv in spec_vals:
                missing = [a for a in sv.axes if a not in mv.axes]
                if not missing:
                    continue
                key = (fi.path, call.lineno, mv.site, sv.site)
                if key in seen:
                    continue
                seen.add(key)
                hops = tuple(
                    [sv.site] + _trail_hops(sv)
                    + [f"{fi.path}:{fi.qual}:{call.lineno}"]
                )
                yield Finding(
                    self.rule_id, self.severity, fi.path, call.lineno,
                    fi.qual,
                    f"PartitionSpec binding {'/'.join(repr(m) for m in missing)} "
                    f"placed on a mesh whose axes are {list(mv.axes)} "
                    f"(spec minted at {sv.site}, mesh at {mv.site}; "
                    f"witness path: {' -> '.join(hops)})",
                    "build the spec from the mesh's own axis names, or route "
                    "the intended mesh to this placement",
                    witness=hops,
                    related=_related_of(mv, sv),
                )

    def _check_shard_map(self, flow, site, seen):
        if not site.mesh_vals or not site.spec_axes:
            return
        fi = site.fi
        for mv in site.mesh_vals:
            missing = [a for a in site.spec_axes if a not in mv.axes]
            if not missing:
                continue
            key = (fi.path, site.line, mv.site, tuple(missing))
            if key in seen:
                continue
            seen.add(key)
            hops = tuple(
                [mv.site] + _trail_hops(mv)
                + [f"{fi.path}:{fi.qual}:{site.line}"]
            )
            yield Finding(
                self.rule_id, self.severity, fi.path, site.line, fi.qual,
                f"shard_map specs name axis "
                f"{'/'.join(repr(m) for m in missing)} but the bound mesh's "
                f"axes are {list(mv.axes)} (mesh minted at {mv.site}; "
                f"witness path: {' -> '.join(hops)})",
                "make the in/out specs name only axes of the mesh handed to "
                "this shard_map",
                witness=hops,
                related=_related_of(mv),
            )


class RuleS003(PackageRule):
    """A ``pallas_call`` reachable inside a jitted scope under a
    multi-axis mesh with NO enclosing shard_map on the path: the kernel
    is opaque to GSPMD, so the partitioner replicates its operands and
    runs the whole kernel per device -- silently wrong results or an
    out-of-memory, never an error. Evidence of the multi-axis mesh (a
    resolved mesh construction with >= 2 axis names visible to the
    jitted entry, the kernel's function, or any frame on the witness
    path) is required; single-device jit of a kernel stays silent, and
    reaching the kernel through a shard_map body is the blessed route.

    Incident: the "pallas_call is opaque to GSPMD" class --
    ``ops/als_gram``'s fused kernel gave wrong sums the moment it was
    jitted under the 2x2 mesh without shard_map routing;
    ``parallel/als.py`` now wraps BOTH factor layouts in an explicit
    ``shard_map`` (``_sharded_block_body`` / the replicated-path
    ``smapped``), which is this rule's negative fixture."""

    rule_id = "S003"
    severity = "error"

    def check_package(self, index: PackageIndex) -> Iterator[Finding]:
        flow = index.meshflow()
        for fkey, line in sorted(flow.pallas_fns.items()):
            fi = flow.graph.functions.get(fkey)
            if fi is None:
                continue
            # per-context, not per-kernel: ops/als_gram's kernel is
            # reached BOTH through the blessed ALS shard_map route and
            # directly from the fold-in solver's jit -- a shard_map
            # path elsewhere must not amnesty an unwrapped jit path
            jit_ctxs = flow.contexts_of(fkey, "jit")
            for ctx in jit_ctxs:
                mesh = self._multi_axis_evidence(flow, fkey, ctx)
                if mesh is None:
                    continue
                hops = tuple(
                    flow.witness_path(fkey, ctx)
                    + [f"{fi.path}:{fi.qual}:{line}"]
                )
                yield Finding(
                    self.rule_id, self.severity, fi.path, line, fi.qual,
                    f"pallas_call reached from jitted scope {ctx.seed} "
                    f"with no enclosing shard_map while a multi-axis mesh "
                    f"(axes={list(mesh.axes)}, minted at {mesh.site}) is in "
                    f"scope: the kernel is opaque to GSPMD "
                    f"(witness path: {' -> '.join(hops)})",
                    "route the kernel through an explicit shard_map over the "
                    "mesh (the parallel/als.py _sharded_block_body shape)",
                    witness=hops,
                    related=_related_of(mesh),
                )
                break   # one finding per kernel site is enough

    def _multi_axis_evidence(self, flow, fkey, ctx):
        """A >=2-axis MeshVal visible on the seed->kernel path, or --
        the jit constructor usually lives OUTSIDE that chain -- minted
        anywhere in the jit seed's or the kernel's module."""
        keys = [fkey]
        for hop in flow.witness_path(fkey, ctx):
            parts = hop.rsplit(":", 2)
            if len(parts) == 3:
                keys.append((parts[0], parts[1]))
        for key in keys:
            for mv in flow.env_meshes(key):
                if len(mv.axes) >= 2:
                    return mv
        seed_path = ctx.seed.rsplit(":", 2)[0]
        for path in dict.fromkeys((seed_path, fkey[0])):
            for mv in flow.module_meshes(path):
                if len(mv.axes) >= 2:
                    return mv
        return None


class RuleS004(PackageRule):
    """Read-after-donate: a caller invokes a jitted program that donates
    an argument buffer (``donate_argnums``/``donate_argnames``), then
    reads the donated argument's name after the call returns (or loops
    back into the call without rebinding it) -- the buffer was handed to
    XLA and may already hold the output. Rebinding the name from the
    call's result (``params, opt = step(params, opt)``) is the intended
    shape and stays silent, as is the ``(0,) if IS_LEGACY_JAX else
    (0, 1)`` gated form (the J002 fix shape: the gate exists precisely
    to keep donation correct per jax version).

    Incident: the tp-sharded adam-state donation bug (PR 4/J002's
    sibling): on legacy jax the donated opt-state pytree paired wrong
    buffers inside XLA, and the debugging tail chased a caller that
    logged ``opt_state`` AFTER the donated step -- a read of a buffer
    that no longer belonged to it, returning plausible garbage that
    masked the real corruption for days."""

    rule_id = "S004"
    severity = "error"

    def check_package(self, index: PackageIndex) -> Iterator[Finding]:
        flow = index.meshflow()
        for fi in sorted(
            flow.graph.functions.values(), key=lambda f: f.key
        ):
            donated = {
                d.name: d for d in flow.donated_callables(fi) if not d.gated
            }
            if not donated:
                continue
            yield from self._check_function(flow, fi, donated)

    def _check_function(self, flow, fi, donated):
        body = flow.graph.body_nodes(fi.node)
        # name -> sorted line lists, loads and stores separately
        loads: dict = {}
        stores: dict = {}
        for node in body:
            d = None
            if isinstance(node, (ast.Name, ast.Attribute)):
                d = dotted(node)
            if d is None:
                continue
            ctx = getattr(node, "ctx", None)
            if isinstance(ctx, ast.Store):
                stores.setdefault(d, []).append(node.lineno)
            elif isinstance(ctx, ast.Load):
                loads.setdefault(d, []).append(node.lineno)
        loops = [
            (n.lineno, getattr(n, "end_lineno", n.lineno), n)
            for n in body
            if isinstance(n, (ast.For, ast.While, ast.AsyncFor))
        ]
        reported: set = set()
        for node in body:
            if not isinstance(node, ast.Call):
                continue
            callee = dotted(node.func)
            don = donated.get(callee or "")
            if don is None:
                continue
            for pos in don.positions:
                if pos >= len(node.args):
                    continue
                name = dotted(node.args[pos])
                if name is None:
                    continue
                yield from self._check_arg(
                    fi, don, node, name, loads, stores, loops, reported
                )

    def _check_arg(self, fi, don, call, name, loads, stores, loops, reported):
        line = call.lineno
        # the call's own argument lines are not "reads after": a
        # black-wrapped multi-line donated call puts the donated name on
        # a continuation line past call.lineno
        call_end = getattr(call, "end_lineno", line) or line
        # first rebinding at/after the donating call resets the hazard
        rebind = min(
            (ln for ln in stores.get(name, ()) if ln >= line),
            default=None,
        )
        horizon = rebind if rebind is not None else float("inf")
        late_reads = [
            ln for ln in loads.get(name, ())
            if call_end < ln < horizon
        ]
        enclosing = [
            (lo, hi) for lo, hi, _n in loops if lo <= line <= hi
        ]
        loop_hazard = None
        if enclosing and not any(
            lo <= ln <= hi
            for ln in stores.get(name, ())
            for lo, hi in enclosing
        ):
            loop_hazard = min(lo for lo, _hi in enclosing)
        if not late_reads and loop_hazard is None:
            return
        key = (fi.path, line, name)
        if key in reported:
            return
        reported.add(key)
        if late_reads:
            what = (
                f"{name!r} is read at line {late_reads[0]} after being "
                f"donated to the jitted call at line {line}"
            )
        else:
            what = (
                f"{name!r} is donated at line {line} inside the loop at "
                f"line {loop_hazard} and never rebound in the loop body: "
                f"the next iteration re-reads a donated buffer"
            )
        hops = (
            f"{fi.path}:{fi.qual}:{don.jit_line}",
            f"{fi.path}:{fi.qual}:{line}",
            f"{fi.path}:{fi.qual}:{late_reads[0] if late_reads else line}",
        )
        yield Finding(
            self.rule_id, self.severity, fi.path, line, fi.qual,
            f"read-after-donate: {what} (donation declared at line "
            f"{don.jit_line}; witness path: {' -> '.join(hops)})",
            "rebind the name from the call's result (params, opt = "
            "step(params, opt)), or stop donating a buffer the caller "
            "still needs",
            witness=hops,
            related=((fi.path, don.jit_line,
                      "donating jit constructed here"),),
        )


class RuleS005(PackageRule):
    """``device_put`` / ``with_sharding_constraint`` / ``put_global``
    inside a shard_map body (or any function on a call path below one):
    the body runs PER SHARD on per-shard values, and a global placement
    directive there either fails to trace or quietly re-places one
    shard's slice as if it were the global array. Placement belongs to
    the caller, before/after the shard_map boundary.

    Incident: the J005 era's debugging detour -- while chasing the
    0.4.37 concat->reshard miscompile, a ``with_sharding_constraint``
    was briefly pushed INSIDE ``_sharded_block_body`` to "pin" the
    bucket output, which traced on one jax version and crashed with an
    unbound-mesh error on the other; the durable fix
    (``dynamic_update_slice`` assembly in the CALLER, constraints only
    outside the shard_map) is the committed shape in
    ``parallel/als.py``."""

    rule_id = "S005"
    severity = "error"

    def check_package(self, index: PackageIndex) -> Iterator[Finding]:
        flow = index.meshflow()
        for fkey, sites in sorted(flow.placements.items()):
            fi = flow.graph.functions.get(fkey)
            if fi is None:
                continue
            ctxs = flow.contexts_of(fkey, "shard_map")
            if not ctxs:
                continue
            ctx = ctxs[0]
            for line, name in sites:
                hops = tuple(
                    flow.witness_path(fkey, ctx)
                    + [f"{fi.path}:{fi.qual}:{line}"]
                )
                yield Finding(
                    self.rule_id, self.severity, fi.path, line, fi.qual,
                    f"`{name}` inside a shard_map body: per-shard code "
                    f"applying global placement (bound at {ctx.seed}; "
                    f"witness path: {' -> '.join(hops)})",
                    "move the placement to the caller, outside the "
                    "shard_map boundary; inside the body every value is "
                    "already the local shard",
                    witness=hops,
                    related=_related_of(ctx.mesh),
                )


RULES = (RuleS001, RuleS002, RuleS003, RuleS004, RuleS005)
