"""R-series rules: exception-path resource-lifecycle invariants.

Built on ``analysis/flowgraph.py``: per-function flowgraphs with
explicit exception edges and a must-release obligation domain,
propagated interprocedurally through PR 13's package call graph so a
helper that releases on behalf of its caller (the ``_respond`` /
``_deliver`` shapes) is credited along the witness path. Each finding
reports the acquiring line and the witness hand-off path.

Every rule class docstring IS its incident-catalog entry: ``pio check
--explain RULE`` prints it, and the R table in
``docs/static_analysis.md`` is generated from it.
"""

from __future__ import annotations

from typing import Iterator

from predictionio_tpu.analysis.engine import Finding
from predictionio_tpu.analysis.flowgraph import ATTACH, FD, LOCK, PERMIT, SPAN
from predictionio_tpu.analysis.packageindex import PackageIndex, PackageRule


def _witness(fi, ob, leak) -> tuple:
    hops = [f"{fi.path}:{fi.qual}:{ob.line}"]
    hops.extend(leak.trail)
    hops.append(f"{fi.path}:{fi.qual}:{leak.line}")
    return tuple(hops)


def _witness_text(hops: tuple) -> str:
    return " -> ".join(hops)


def _grouped(index: PackageIndex) -> dict:
    """(function, obligation) -> {exit kind: Leak}; one finding per
    obligation, classified by the worst exit it survives to."""
    out: dict = {}
    for leak in index.resources().leaks:
        rec = out.setdefault((leak.fi.key, id(leak.ob)), {
            "fi": leak.fi, "ob": leak.ob, "exits": {},
        })
        rec["exits"].setdefault(leak.exit, leak)
    return out


class RuleR001(PackageRule):
    """A resource acquired but not released on some exception path out
    of the acquiring function: an admission permit
    (``Semaphore``/tracker ``.acquire()`` idioms), a raw
    ``Lock.acquire`` outside ``with``, or an
    ``open``/``mmap``/``socket`` descriptor that an exception edge
    carries past its ``close``. Releases by a helper the value (or the
    owning field) is handed to are credited through the package call
    graph -- the finding means NO path out of the function, direct or
    delegated, discharges the obligation on that exception edge.

    Incident: the PR-12 review pass caught the async watchdog holding
    admission permits for requests whose batch had wedged (a 503 path
    that never released), and THIS PR's first sweep convicted the ring
    consumer's retired-worker race -- a permit acquired, then
    ``ring.requests.pop()`` raising on a ring the supervisor had just
    closed, leaked the permit through the recovery ``continue`` and
    permanently shrank ``max_inflight``."""

    rule_id = "R001"
    severity = "error"

    def check_package(self, index: PackageIndex) -> Iterator[Finding]:
        for rec in sorted(
            _grouped(index).values(),
            key=lambda r: (r["fi"].key, r["ob"].line),
        ):
            ob, fi = rec["ob"], rec["fi"]
            if ob.kind not in (PERMIT, LOCK, FD):
                continue
            if "normal" in rec["exits"]:
                continue  # R004 owns the stronger never-released shape
            leak = rec["exits"]["exception"]
            hops = _witness(fi, ob, leak)
            yield Finding(
                self.rule_id, self.severity, fi.path, ob.line, fi.qual,
                f"{ob.kind} {ob.label!r} acquired at line {ob.line} is not "
                f"released on an exception path out of {fi.qual} "
                f"(leak edge at line {leak.line}; witness path: "
                f"{_witness_text(hops)})",
                "release in a finally/backstop handler, or hand the "
                "obligation to a helper that owns it on every path "
                "(the _deliver/_CompletionRetry shape)",
                witness=hops,
            )


class RuleR002(PackageRule):
    """A trace span started (``tracer.span``/``start_remote`` used as an
    explicit handle, not a ``with``) or attached
    (``Span.attach()``) with some path out of the function that
    neither finishes nor detaches it and never hands it to an owner.
    ``finally``-finished spans, handles forwarded to a finishing helper
    (``_finish_async_response``), handles stored into an owning
    entry/container, and the sampled-out-sentinel
    ``SAMPLED_OUT_ROOT.attach()/detach()`` discipline are all credited
    and stay silent.

    Incident: the non-UTF-8-body live-trace leak (PR 12 review): a
    request body that raised ``UnicodeDecodeError`` slipped past the
    ``json.JSONDecodeError`` handler, so the root span started on the
    ring consumer was never finished -- the trace stayed live forever
    and the request escaped its 500-envelope contract. The fix shape is
    the whole-submit-path catch-all backstop plus ``finally:
    guard.detach()``."""

    rule_id = "R002"
    severity = "error"

    def check_package(self, index: PackageIndex) -> Iterator[Finding]:
        for rec in sorted(
            _grouped(index).values(),
            key=lambda r: (r["fi"].key, r["ob"].line),
        ):
            ob, fi = rec["ob"], rec["fi"]
            if ob.kind not in (SPAN, ATTACH):
                continue
            leak = rec["exits"].get("exception") or rec["exits"]["normal"]
            hops = _witness(fi, ob, leak)
            what = (
                "attached to the thread context stack and never detached"
                if ob.kind == ATTACH else "started and neither finished nor "
                "handed to an owner"
            )
            yield Finding(
                self.rule_id, self.severity, fi.path, ob.line, fi.qual,
                f"span handle {ob.label!r} ({ob.kind}) is {what} on a "
                f"{leak.exit} path out of {fi.qual} (leak edge at line "
                f"{leak.line}; witness path: {_witness_text(hops)})",
                "finish/detach in a finally, add a catch-all backstop "
                "that finishes the root, or forward the handle to the "
                "shared _respond tail",
                witness=hops,
            )


class RuleR003(PackageRule):
    """A durability-protocol violation, checked as an ordering
    obligation at the commit site: a tmp file renamed into its commit
    location (``os.replace``/``os.rename``) on a path where the bytes
    written were never fsynced (file or directory), or a
    checkpoint/cursor write ordered BEFORE the fsync of the data it
    claims to cover. Helpers that fsync on the caller's behalf
    (``_fsync_dir``, a parameter the callee fsyncs) are credited
    through the call-graph summaries.

    Incident: the WAL/registry/snapshot tmp+fsync+rename contract
    (PRs 2/3/9) -- a rename WITHOUT the fsync publishes a name whose
    bytes can vanish in a crash, exactly the torn-manifest class the
    snapshot store's CRC checks exist to catch after the fact. THIS
    PR's sweep convicted the training-checkpoint meta sidecar
    (``workflow/checkpoint.py``), which renamed un-fsynced resume
    metadata into place."""

    rule_id = "R003"
    severity = "error"

    def check_package(self, index: PackageIndex) -> Iterator[Finding]:
        for rec in sorted(
            index.resources().durability,
            key=lambda r: (r.fi.key, r.line),
        ):
            yield Finding(
                self.rule_id, self.severity, rec.fi.path, rec.line,
                rec.fi.qual,
                f"durability-protocol violation ({rec.kind}): {rec.detail}",
                "fsync the written file (and the directory for new names) "
                "before the rename/checkpoint that commits it -- the "
                "data/snapshot discipline",
            )


class RuleR004(PackageRule):
    """An obligation that dies with no owner: a permit, raw lock, or
    descriptor acquired into a local (or bare ``acquire()`` on a
    field) that reaches the NORMAL exit of the function still open --
    never released, never returned, never stored, never handed to a
    releasing helper. Where R001 flags the exception edge that skips an
    existing release, R004 flags the shape where no release exists at
    all.

    Incident: the ``_CompletionRetry`` deadline-drop review finding
    (PR 12): a parked completion whose deadline expired was dropped --
    response gone, fine -- but the admission permit riding the entry
    was dropped WITH it, so every expired retry permanently shrank the
    scorer's admission window until the tier wedged closed."""

    rule_id = "R004"
    severity = "error"

    def check_package(self, index: PackageIndex) -> Iterator[Finding]:
        for rec in sorted(
            _grouped(index).values(),
            key=lambda r: (r["fi"].key, r["ob"].line),
        ):
            ob, fi = rec["ob"], rec["fi"]
            if ob.kind not in (PERMIT, LOCK, FD):
                continue
            if "normal" not in rec["exits"]:
                continue
            leak = rec["exits"]["normal"]
            hops = _witness(fi, ob, leak)
            yield Finding(
                self.rule_id, self.severity, fi.path, ob.line, fi.qual,
                f"{ob.kind} {ob.label!r} acquired at line {ob.line} "
                f"escapes {fi.qual} with no owner: the normal exit at "
                f"line {leak.line} drops it unreleased (witness path: "
                f"{_witness_text(hops)})",
                "release before every exit, store the obligation on an "
                "owner that releases it, or return it to the caller",
                witness=hops,
            )


RULES = (RuleR001, RuleR002, RuleR003, RuleR004)
