"""Exception-edge resource-lifecycle dataflow: the R-series substrate.

The serving and ingest tiers are held together by paired-protocol
invariants -- an admission permit released exactly once, a trace span
finished on every path, a tmp file fsynced before the rename that
commits it -- and four review passes in a row each caught an
exception-edge leak of one of them by hand (the non-UTF-8-body
live-trace leak, the watchdog permit hold, the ``_CompletionRetry``
deadline-drop permit, the retired-ring read race). This module makes
those protocols checkable mechanically:

- **Flowgraph**: each function is interpreted over a per-statement
  control-flow walk with EXPLICIT exception edges -- any call or
  ``raise`` may throw, ``try``/``except``/``finally``/``with`` are
  modeled structurally (``finally`` runs on return/break/continue/raise
  flows too), and loop bodies iterate to a fixpoint. Typed ``except``
  clauses both catch AND propagate (the non-UTF-8 incident was exactly
  a typed handler whose type did not match); only a bare /
  ``Exception`` / ``BaseException`` handler is a true backstop.
- **Obligations** (the must-release abstract domain): facts created by
  acquire-shaped calls -- semaphore/tracker ``.acquire()`` permit
  idioms, ``tracer.span``/``start_remote`` handles and
  ``Span.attach()``, raw ``Lock.acquire`` outside ``with``,
  ``open``/``mmap``/``socket`` file descriptors, and the
  tmp-write-pending-fsync facts of the durability protocol -- and
  discharged by their matching release (``release``/``finish``/
  ``detach``/``close``/``os.fsync``), by escaping to an owner (returned,
  stored on ``self``, packed into a container), or by being handed to a
  callee that releases on the caller's behalf.
- **Interprocedural credit**: per-function summaries (which parameters
  a function releases/fsyncs/invokes, which class-level permit/lock
  fields it may release, transitively) are computed to a fixpoint over
  PR 13's package call graph, so the async serving chain -- ring
  consumer -> ``submit_query_async`` -> flusher callback ->
  ``_complete_query`` -> ``_inflight.release()`` -- credits the
  acquiring function along the witness path instead of flagging it.

The join is may-analysis union: an obligation open on SOME path to an
exit is a leak on that exit. ``rules_resources`` turns the per-exit
leak records into R001 (exception-path permit/lock/fd leak), R002
(span neither finished nor detached), R003 (durability-protocol
violation, site-triggered at the commit rename / checkpoint write) and
R004 (obligation dies in a local with no owner).

Flowgraph state is cached per function alongside the
:class:`~predictionio_tpu.analysis.packageindex.PackageIndex` (one
``ResourceFlow`` per ``pio check`` run, built lazily so J/C-only runs
pay nothing).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field as dfield

from predictionio_tpu.analysis.astutil import call_name, dotted

# -- obligation kinds ---------------------------------------------------------
PERMIT = "permit"
LOCK = "lock"
SPAN = "span"
ATTACH = "attach"
FD = "fd"
DIRTY = "dirty"          # bytes written to a commit-protocol file, not yet fsynced

#: receiver-name tokens that mark a ``.acquire()`` as a permit idiom
_PERMIT_TOKENS = frozenset((
    "sem", "semaphore", "inflight", "permit", "permits", "tracker",
))
_LOCK_TOKENS = frozenset(("lock", "rlock", "mutex"))
#: method names that start a span handle (explicit-lifetime tracing)
_SPAN_STARTS = frozenset(("span", "start_remote", "start_span"))
#: receiver tokens for which a bare ``.attach()`` is a context-stack push
_ATTACH_TOKENS = frozenset(("span", "root", "guard", "handle"))
_FD_FUNCS = frozenset((
    "open", "os.open", "os.fdopen", "mmap.mmap", "socket.socket",
    "os.eventfd",
))
_WRITE_VERBS = frozenset(("write", "writelines", "truncate"))
#: checkpoint/cursor-write shapes for the R003 ordering obligation
_CKPT_TOKENS = frozenset(("checkpoint", "cursor"))
_SEM_CTORS = frozenset((
    "threading.Semaphore", "threading.BoundedSemaphore", "Semaphore",
    "BoundedSemaphore",
))
_CATCH_ALL_TYPES = frozenset(("Exception", "BaseException"))

#: release verb -> obligation kinds it discharges. ``detach`` does NOT
#: discharge a started span (a detached-but-unfinished span IS the
#: live-trace leak class R002 exists for), and ``finish`` does not pop
#: the context stack -- the pairing is exact by design.
_RELEASE_KINDS = {
    "release": (PERMIT, LOCK),
    "finish": (SPAN,),
    "detach": (ATTACH,),
    "close": (FD,),
}

_MAX_LOOP_ITERS = 4
_MAX_SUMMARY_ROUNDS = 8


def _tokens(d: str) -> set:
    return set(d.lower().replace(".", "_").split("_")) - {""}


def _is_tmpish(text: str) -> bool:
    return "tmp" in text.lower()


@dataclass(eq=False)
class Obligation:
    """One acquire fact. Interned per call site so loop fixpoints
    converge (re-executing the acquire is the same obligation)."""

    kind: str
    label: str               # human key: "self._inflight", "root", "f"
    line: int
    field: tuple | None = None     # (path, cls, attr) for class-field permits
    pathname: str | None = None    # DIRTY: name the written path was opened under


@dataclass
class Leak:
    fi: object               # FunctionInfo
    ob: Obligation
    exit: str                # "exception" | "normal"
    line: int                # line of the leaking exit edge
    trail: tuple             # non-discharging hand-off hops, for the witness


@dataclass
class Durability:
    fi: object
    line: int
    kind: str                # "rename" | "checkpoint"
    detail: str


@dataclass
class Summary:
    """What a function does to values handed to it (the release-on-
    behalf-of-caller credit) and to shared permit/lock fields."""

    releases: set = dfield(default_factory=set)   # param names discharged/owned
    fsyncs: set = dfield(default_factory=set)     # param names fsynced
    calls: set = dfield(default_factory=set)      # param names invoked as callables
    fields: set = dfield(default_factory=set)     # (path, cls, attr) may-released
    fsyncs_any: bool = False


# -- the whole-package layer --------------------------------------------------

class ResourceFlow:
    """Obligation analysis over every function of a
    :class:`PackageIndex`; built once per run, read by the R rules."""

    def __init__(self, index):
        self.index = index
        self.graph = index.graph
        self.locks = index.locks
        #: (path, clsqual) -> {attr}: semaphore-valued fields
        self._sem_fields: dict[tuple, set] = {}
        #: (path, clsqual, attr) -> ClassInfo: `self.attr = param` where the
        #: param carries a class annotation (extends callgraph.attr_types)
        self._attr_ext: dict[tuple, object] = {}
        self._collect_fields()
        self.summaries: dict[tuple, Summary] = {}
        self._build_summaries()
        self.leaks: list[Leak] = []
        self.durability: list[Durability] = []
        for fi in sorted(self.graph.functions.values(), key=lambda f: f.key):
            if self._relevant(fi):
                _Analysis(self, fi).run()

    # -- field inventory ----------------------------------------------------
    def _collect_fields(self) -> None:
        for cinfo in self.graph.classes.values():
            ann_types = {}
            for meth in cinfo.methods.values():
                args = getattr(meth.node, "args", None)
                if args is not None:
                    for p in args.posonlyargs + args.args + args.kwonlyargs:
                        hit = None
                        if p.annotation is not None:
                            ann = p.annotation
                            if isinstance(ann, ast.Constant) and isinstance(
                                ann.value, str
                            ):
                                try:
                                    ann = ast.parse(ann.value, mode="eval").body
                                except SyntaxError:
                                    ann = None
                            if ann is not None:
                                hit = self.graph._resolve_class_expr(meth, ann)
                        if hit is not None:
                            ann_types[p.arg] = hit
                for node in self.graph.body_nodes(meth.node):
                    if not isinstance(node, ast.Assign):
                        continue
                    for t in node.targets:
                        d = dotted(t)
                        if not (d and d.startswith("self.") and d.count(".") == 1):
                            continue
                        attr = d[len("self."):]
                        v = node.value
                        if isinstance(v, ast.Call) and call_name(v) in _SEM_CTORS:
                            self._sem_fields.setdefault(
                                cinfo.key, set()
                            ).add(attr)
                        elif isinstance(v, ast.Name) and v.id in ann_types:
                            self._attr_ext[(*cinfo.key, attr)] = ann_types[v.id]

    def _class_of_expr(self, fi, obj: str):
        """ClassInfo for a dotted receiver prefix (``self``, a typed
        local, ``self._bridge`` through the annotated-param extension)."""
        parts = obj.split(".")
        if parts[0] == "self":
            cinfo = (
                self.graph.classes.get((fi.path, fi.cls)) if fi.cls else None
            )
            parts = parts[1:]
        else:
            env = self.graph._local_env(fi).get(parts[0])
            cinfo = env[1] if env and env[0] == "type" else None
            parts = parts[1:]
        for attr in parts:
            if cinfo is None:
                return None
            types = cinfo.attr_types.get(attr)
            if types and len(types) == 1:
                cinfo = next(iter(types))
            else:
                cinfo = self._attr_ext.get((*cinfo.key, attr))
        return cinfo

    def field_of(self, fi, recv: str) -> tuple | None:
        """``self._inflight`` / ``self._bridge._inflight`` / ``w.cmp_lock``
        -> the class-qualified permit/lock field key, or None."""
        if "." not in recv:
            return None
        obj, attr = recv.rsplit(".", 1)
        cinfo = self._class_of_expr(fi, obj)
        if cinfo is None:
            return None
        if attr in self._sem_fields.get(cinfo.key, ()) or attr in (
            self.locks._declared.get(cinfo.key, ())
        ):
            return (*cinfo.key, attr)
        return None

    # -- summaries ----------------------------------------------------------
    def _build_summaries(self) -> None:
        for fi in self.graph.functions.values():
            self.summaries[fi.key] = self._local_summary(fi)
        for _ in range(_MAX_SUMMARY_ROUNDS):
            changed = False
            for fi in self.graph.functions.values():
                changed |= self._propagate_summary(fi)
            if not changed:
                break

    def _local_summary(self, fi) -> Summary:
        s = Summary()
        params = set(fi.params()) - {"self"}
        for node in self.graph.body_nodes(fi.node):
            if isinstance(node, ast.Call):
                fn = node.func
                name = call_name(node)
                if isinstance(fn, ast.Attribute):
                    recv = dotted(fn.value)
                    if fn.attr in _RELEASE_KINDS and recv:
                        if recv in params:
                            s.releases.add(recv)
                        if fn.attr == "release":
                            fld = self.field_of(fi, recv)
                            if fld is not None:
                                s.fields.add(fld)
                    if fn.attr == "fsync":
                        s.fsyncs_any = True
                if name == "os.close" and node.args:
                    d = dotted(node.args[0])
                    if d in params:
                        s.releases.add(d)
                if name == "os.fsync":
                    s.fsyncs_any = True
                    root = _fsync_target(node)
                    if root in params:
                        s.fsyncs.add(root)
                if isinstance(fn, ast.Name) and fn.id in params:
                    s.calls.add(fn.id)
                # params stored into a self-rooted container own the value
                if isinstance(fn, ast.Attribute) and fn.attr in (
                    "append", "add", "put", "put_nowait", "appendleft",
                ):
                    recv = dotted(fn.value) or ""
                    if recv.startswith("self."):
                        for p in _names_shallow(node.args):
                            if p in params:
                                s.releases.add(p)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    d = dotted(t)
                    base = t
                    while isinstance(base, ast.Subscript):
                        base = base.value
                    db = dotted(base)
                    if (d and d.startswith("self.")) or (
                        db and db.startswith("self.")
                    ):
                        for p in _names_shallow([node.value]):
                            if p in params:
                                s.releases.add(p)
        return s

    def _propagate_summary(self, fi) -> bool:
        s = self.summaries[fi.key]
        params = set(fi.params()) - {"self"}
        changed = False
        for site in self.graph.callees(fi.key):
            for target in site.targets:
                ts = self.summaries.get(target.key)
                if ts is None:
                    continue
                if ts.fields - s.fields:
                    s.fields |= ts.fields
                    changed = True
                if ts.fsyncs_any and not s.fsyncs_any:
                    s.fsyncs_any = True
                    changed = True
                if not params:
                    continue
                tparams = target.params()
                offset = 1 if tparams[:1] == ["self"] else 0
                pairs = []
                for i, arg in enumerate(site.call.args):
                    d = dotted(arg)
                    if d in params and i + offset < len(tparams):
                        pairs.append((d, tparams[i + offset]))
                for kw in site.call.keywords:
                    d = dotted(kw.value)
                    if d in params and kw.arg in tparams:
                        pairs.append((d, kw.arg))
                for mine, theirs in pairs:
                    if theirs in ts.releases and mine not in s.releases:
                        s.releases.add(mine)
                        changed = True
                    if theirs in ts.fsyncs and mine not in s.fsyncs:
                        s.fsyncs.add(mine)
                        changed = True
                    if theirs in ts.calls and mine not in s.calls:
                        s.calls.add(mine)
                        changed = True
        return changed

    # -- relevance prescan --------------------------------------------------
    def _relevant(self, fi) -> bool:
        """Does this function create any obligation or commit site? The
        sweep budget is paid here: most functions exit in one cheap
        pass and never run the dataflow."""
        for node in self.graph.body_nodes(fi.node):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name in ("os.replace", "os.rename"):
                return True
            if name in _FD_FUNCS:
                return True
            fn = node.func
            if isinstance(fn, ast.Attribute):
                if fn.attr == "acquire" and self._acquire_kind(fi, fn) is not None:
                    return True
                if fn.attr in _SPAN_STARTS:
                    return True
                if fn.attr == "attach" and not node.args:
                    recv = dotted(fn.value) or ""
                    if _tokens(recv) & _ATTACH_TOKENS:
                        return True
        return False

    def _acquire_kind(self, fi, fn: ast.Attribute) -> str | None:
        recv = dotted(fn.value)
        if not recv:
            return None
        fld = self.field_of(fi, recv)
        if fld is not None:
            cls_key = (fld[0], fld[1])
            if fld[2] in self._sem_fields.get(cls_key, ()):
                return PERMIT
            return LOCK
        toks = _tokens(recv)
        if toks & _PERMIT_TOKENS:
            return PERMIT
        if toks & _LOCK_TOKENS:
            return LOCK
        return None


def _fsync_target(call: ast.Call) -> str | None:
    """``os.fsync(fd)`` / ``os.fsync(f.fileno())`` -> the root name."""
    if not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Call) and isinstance(arg.func, ast.Attribute):
        if arg.func.attr == "fileno":
            return dotted(arg.func.value)
    return dotted(arg)


def _names_shallow(nodes) -> set:
    """Dotted references in expressions, including inside container
    displays and calls -- the escape check's reach. A chain contributes
    only its FULL dotted form: ``span.op`` escapes an attribute value,
    not the span handle itself."""
    out = set()

    def rec(n):
        d = dotted(n)
        if d is not None:
            out.add(d)
            return
        for c in ast.iter_child_nodes(n):
            rec(c)

    for node in nodes:
        rec(node)
    return out


# -- the per-function interpreter ---------------------------------------------

class _Ctx:
    """Where non-local control flow delivers its state: the innermost
    handler (``raise_to``) and the collectors ``finally`` interposes on."""

    __slots__ = ("raise_to", "return_to", "break_to", "continue_to")

    def __init__(self, raise_to, return_to, break_to=None, continue_to=None):
        self.raise_to = raise_to
        self.return_to = return_to
        self.break_to = break_to
        self.continue_to = continue_to

    def replaced(self, **kw) -> "_Ctx":
        out = _Ctx(self.raise_to, self.return_to, self.break_to, self.continue_to)
        for k, v in kw.items():
            setattr(out, k, v)
        return out


class _Analysis:
    """May-open obligation dataflow for ONE function. State = frozenset
    of ``(Obligation, alias names, hand-off trail)`` entries; join is
    union (an obligation open on some path stays open); ``None`` marks
    unreachable code. Which EXIT collector a state reaches (the
    function-level raise vs return sink) is what classifies a leak as
    exception-path vs normal -- no per-entry flag needed."""

    def __init__(self, flow: ResourceFlow, fi):
        self.flow = flow
        self.fi = fi
        self._obs: dict[int, Obligation] = {}      # id(call) -> interned
        self._handles: dict[str, tuple] = {}       # partial-release handles
        self._exc_exit: list = []                  # (state, line)
        self._ret_exit: list = []

    # -- driver -------------------------------------------------------------
    def run(self) -> None:
        body = self.fi.node.body
        if not isinstance(body, list):
            return  # lambda bodies hold no statements to leak across
        ctx = _Ctx(
            raise_to=lambda s, l: self._exc_exit.append((s, l)),
            return_to=lambda s, l: self._ret_exit.append((s, l)),
        )
        out = self._block(body, frozenset(), ctx)
        end = getattr(self.fi.node, "end_lineno", self.fi.node.lineno)
        flow = self.flow
        leaked: dict[int, dict] = {}

        def note(state, exit_kind, line):
            if state is None:
                return
            for ob, names, trail in state:
                if ob.kind == DIRTY:
                    continue
                rec = leaked.setdefault(id(ob), {"ob": ob, "exits": {}})
                prior = rec["exits"].get(exit_kind)
                # keep the exit whose hand-off trail says the most: the
                # witness should name the helper that failed to release
                if prior is None or len(trail) > len(prior[1]):
                    rec["exits"][exit_kind] = (line, trail)

        note(out, "normal", end)
        for state, line in self._ret_exit:
            note(state, "normal", line)
        for state, line in self._exc_exit:
            note(state, "exception", line)
        for rec in leaked.values():
            for exit_kind, (line, trail) in rec["exits"].items():
                flow.leaks.append(Leak(
                    fi=self.fi, ob=rec["ob"], exit=exit_kind,
                    line=line, trail=trail,
                ))

    # -- state helpers ------------------------------------------------------
    @staticmethod
    def _join(*states):
        live = [s for s in states if s is not None]
        if not live:
            return None
        out = live[0]
        for s in live[1:]:
            out = out | s
        return out

    def _gen(self, state, ob: Obligation, names) -> frozenset:
        return state | {(ob, frozenset(names), ())}

    @staticmethod
    def _discharge(state, pred) -> frozenset:
        return frozenset(e for e in state if not pred(e))

    # -- blocks and statements ----------------------------------------------
    def _block(self, stmts, state, ctx):
        for stmt in stmts:
            if state is None:
                break
            state = self._stmt(stmt, state, ctx)
        return state

    def _stmt(self, stmt, state, ctx):
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                state = self._eval(stmt.value, state, ctx)
                state = self._escape_via_return(stmt.value, state)
            ctx.return_to(state, stmt.lineno)
            return None
        if isinstance(stmt, ast.Raise):
            if stmt.exc is not None:
                state = self._eval(stmt.exc, state, ctx)
            ctx.raise_to(state, stmt.lineno)
            return None
        if isinstance(stmt, ast.Break):
            if ctx.break_to is not None:
                ctx.break_to.append(state)
            return None
        if isinstance(stmt, ast.Continue):
            if ctx.continue_to is not None:
                ctx.continue_to.append(state)
            return None
        if isinstance(stmt, ast.If):
            return self._if(stmt, state, ctx)
        if isinstance(stmt, (ast.While,)):
            return self._loop(stmt, state, ctx, test=stmt.test)
        if isinstance(stmt, ast.For):
            state = self._eval(stmt.iter, state, ctx)
            return self._loop(stmt, state, ctx, test=None)
        if isinstance(stmt, ast.Try):
            return self._try(stmt, state, ctx)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._with(stmt, state, ctx)
        if isinstance(stmt, ast.Assign):
            return self._assign(stmt, state, ctx)
        if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            if stmt.value is not None:
                state = self._eval(stmt.value, state, ctx)
            return state
        if isinstance(stmt, ast.Expr):
            return self._expr(stmt, state, ctx)
        if isinstance(
            stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            return state  # nested defs are their own flowgraphs
        if isinstance(stmt, ast.Delete):
            return state
        # generic statement: evaluate any embedded calls
        return self._eval(stmt, state, ctx)

    # -- control flow -------------------------------------------------------
    def _if(self, stmt, state, ctx):
        then_in = else_in = None
        test = stmt.test
        acq = self._classify_call(test) if isinstance(test, ast.Call) else None
        neg = (
            isinstance(test, ast.UnaryOp)
            and isinstance(test.op, ast.Not)
            and isinstance(test.operand, ast.Call)
        )
        neg_acq = self._classify_call(test.operand) if neg else None
        if acq is not None:
            # `if x.acquire(timeout=...):` -- held only in the then branch
            state = self._eval(test, state, ctx, skip=test)
            then_in = self._gen(state, acq[0], acq[1])
            else_in = state
        elif neg_acq is not None:
            # `if not x.acquire(timeout=...):` -- held only PAST the if
            state = self._eval(test, state, ctx, skip=test.operand)
            then_in = state
            else_in = self._gen(state, neg_acq[0], neg_acq[1])
        else:
            state = self._eval(test, state, ctx)
            then_in = else_in = state
            sentinel = _sentinel_test(test)
            if sentinel is not None:
                # `X.trace_id is None`: in that branch X is the shared
                # sampled-out sentinel (SAMPLED_OUT_ROOT / NULL_SPAN),
                # which owes no finish -- the tracer's documented
                # suppression contract
                name, none_branch = sentinel
                cleared = self._discharge(
                    state,
                    lambda e: e[0].kind in (SPAN, ATTACH) and name in e[1],
                )
                if none_branch == "then":
                    then_in = cleared
                else:
                    else_in = cleared
        t_out = self._block(stmt.body, then_in, ctx)
        e_out = self._block(stmt.orelse, else_in, ctx)
        return self._join(t_out, e_out)

    def _loop(self, stmt, state, ctx, test):
        infinite = (
            test is not None
            and isinstance(test, ast.Constant)
            and test.value is True
        )
        breaks: list = []
        conts: list = []
        loop_ctx = ctx.replaced(break_to=breaks, continue_to=conts)
        head = state
        for _ in range(_MAX_LOOP_ITERS):
            cur = head
            if test is not None:
                cur = self._eval(test, cur, ctx)
            body_out = self._block(stmt.body, cur, loop_ctx)
            nxt = self._join(head, body_out, *conts)
            conts.clear()
            if nxt == head:
                break
            head = nxt
        out = self._join(*breaks, None if infinite else head)
        if stmt.orelse and out is not None:
            out = self._block(stmt.orelse, out, ctx)
        return out

    def _try(self, stmt, state, ctx):
        pending_exc: list = []      # exceptional flows owed to the OUTER ctx
        pending_ret: list = []
        pending_brk: list = []
        pending_cont: list = []
        has_final = bool(stmt.finalbody)
        body_exc: list = []

        inner_ctx = _Ctx(
            raise_to=lambda s, l: body_exc.append((s, l)),
            return_to=(
                (lambda s, l: pending_ret.append((s, l)))
                if has_final else ctx.return_to
            ),
            break_to=(pending_brk if has_final else ctx.break_to),
            continue_to=(pending_cont if has_final else ctx.continue_to),
        )
        body_out = self._block(stmt.body, state, inner_ctx)
        exc_state = self._join(*(s for s, _ in body_exc))
        exc_line = body_exc[0][1] if body_exc else stmt.lineno

        # raises from HANDLER bodies (incl. bare re-raise) go outward
        handler_ctx = inner_ctx.replaced(
            raise_to=(
                (lambda s, l: pending_exc.append((s, l)))
                if has_final else ctx.raise_to
            ),
        )
        handler_outs = []
        if stmt.handlers and exc_state is not None:
            for h in stmt.handlers:
                handler_outs.append(
                    self._block(h.body, exc_state, handler_ctx)
                )
            if not any(_catches_all(h) for h in stmt.handlers):
                # a typed handler may NOT match (the non-UTF-8-body
                # incident): the raw exception also propagates
                pending_exc.append((exc_state, exc_line))
        elif exc_state is not None:
            pending_exc.append((exc_state, exc_line))

        if stmt.orelse and body_out is not None:
            body_out = self._block(stmt.orelse, body_out, inner_ctx)
        normal = self._join(body_out, *handler_outs)

        if has_final:
            if normal is not None:
                normal = self._block(stmt.finalbody, normal, ctx)
            for s, l in pending_exc:
                after = self._block(stmt.finalbody, s, ctx)
                if after is not None:
                    ctx.raise_to(after, l)
            for s, l in pending_ret:
                after = self._block(stmt.finalbody, s, ctx)
                if after is not None:
                    ctx.return_to(after, l)
            for collector, sink in (
                (pending_brk, ctx.break_to), (pending_cont, ctx.continue_to)
            ):
                for s in collector:
                    after = self._block(stmt.finalbody, s, ctx)
                    if after is not None and sink is not None:
                        sink.append(after)
        else:
            for s, l in pending_exc:
                ctx.raise_to(s, l)
        return normal

    def _with(self, stmt, state, ctx):
        for item in stmt.items:
            ce = item.context_expr
            as_name = (
                item.optional_vars.id
                if isinstance(item.optional_vars, ast.Name) else None
            )
            if isinstance(ce, ast.Call):
                spec = self._classify_call(ce)
                if spec is not None:
                    # managed acquire: the context-manager protocol
                    # guarantees the release -- no lifecycle obligation.
                    # An open of a commit-protocol tmp file still starts
                    # the DIRTY fact (closing is not fsyncing).
                    state = self._eval(ce, state, ctx, skip=ce)
                    ob, _names = spec
                    if ob.kind == FD and ob.pathname is not None and (
                        _is_tmpish(ob.pathname)
                    ):
                        dirty = self._intern(
                            ce, DIRTY, as_name or ob.label, ob.pathname
                        )
                        state = self._gen(state, dirty, {as_name or ob.label})
                    continue
            state = self._eval(ce, state, ctx)
        return self._block(stmt.body, state, ctx)

    # -- assignment and expression statements --------------------------------
    def _assign(self, stmt, state, ctx):
        value = stmt.value
        spec = self._classify_call(value) if isinstance(value, ast.Call) else None
        state = self._eval(value, state, ctx, skip=value if spec else None)
        target_names = {
            t.id for t in stmt.targets if isinstance(t, ast.Name)
        }
        attr_target = any(
            isinstance(_sub_base(t), ast.Attribute) for t in stmt.targets
        )
        # rebinding a name drops that alias from existing obligations
        if target_names:
            state = frozenset(
                (ob, names - target_names, trail)
                for ob, names, trail in state
            )
        if spec is not None:
            ob, default_names = spec
            names = target_names or default_names
            if ob.kind == FD and ob.pathname is not None and _is_tmpish(ob.pathname):
                dirty = self._intern(value, DIRTY, ob.label, ob.pathname)
                state = self._gen(state, dirty, set(names))
            if attr_target and not target_names:
                # `self._file = open(...)`: owned at birth -- the object
                # the attribute lives on carries the release obligation
                return state
            return self._gen(state, ob, names)
        # partial-release handle: cb = functools.partial(x.release)
        handle = self._partial_handle(value)
        if handle is not None and target_names:
            for n in target_names:
                self._handles[n] = handle
            return state
        value_names = _names_shallow([value])
        # alias copy: a = b
        if isinstance(value, ast.Name) and target_names:
            out = set()
            for ob, names, trail in state:
                if value.id in names:
                    out.add((ob, names | target_names, trail))
                else:
                    out.add((ob, names, trail))
            state = frozenset(out)
            return state
        # escape: obligation stored on self / packed into a container
        self_target = any(
            (dotted(t) or "").startswith("self.")
            or (dotted(_sub_base(t)) or "").startswith("self.")
            for t in stmt.targets
        )
        container = isinstance(value, (ast.Dict, ast.List, ast.Tuple, ast.Set))
        if (self_target or container) and value_names:
            state = self._discharge(
                state,
                lambda e: e[0].kind != DIRTY and (e[1] & value_names),
            )
        return state

    def _expr(self, stmt, state, ctx):
        value = stmt.value
        spec = self._classify_call(value) if isinstance(value, ast.Call) else None
        state = self._eval(value, state, ctx, skip=value if spec else None)
        if spec is not None:
            ob, names = spec
            if ob.kind == FD and ob.pathname is not None and _is_tmpish(ob.pathname):
                dirty = self._intern(value, DIRTY, ob.label, ob.pathname)
                state = self._gen(state, dirty, set(names))
            state = self._gen(state, ob, names)
        return state

    def _escape_via_return(self, value, state):
        names = _names_shallow([value])
        if isinstance(value, ast.Name) and value.id == "self":
            # returning self hands every self-rooted obligation to the
            # caller (the `return self.acquire()` / __enter__ shape)
            return self._discharge(
                state,
                lambda e: e[0].kind != DIRTY
                and any(n.startswith("self.") for n in e[1]),
            )
        if not names:
            return state
        return self._discharge(
            state, lambda e: e[0].kind != DIRTY and (e[1] & names)
        )

    # -- calls ---------------------------------------------------------------
    def _eval(self, node, state, ctx, skip=None):
        """Evaluate every call embedded in ``node``: apply discharge /
        acquire-independent effects and raise the exception edge."""
        for call in _calls_in(node):
            if call is skip:
                continue
            state = self._apply_call(call, state, ctx)
        return state

    def _apply_call(self, call, state, ctx):
        fn = call.func
        name = call_name(call)
        arg_names = set()
        for a in list(call.args) + [kw.value for kw in call.keywords]:
            d = dotted(a)
            if d is not None:
                arg_names.add(d)
            elif isinstance(a, (ast.Dict, ast.List, ast.Tuple, ast.Set)):
                arg_names |= _names_shallow([a])
            elif isinstance(a, ast.Call) and isinstance(a.func, ast.Attribute):
                if a.func.attr == "fileno":
                    d = dotted(a.func.value)
                    if d is not None:
                        arg_names.add(d)

        # 1. direct release verbs
        if isinstance(fn, ast.Attribute) and fn.attr in _RELEASE_KINDS:
            recv = dotted(fn.value)
            if recv:
                kinds = _RELEASE_KINDS[fn.attr]
                fld = self.flow.field_of(self.fi, recv)
                state = self._discharge(
                    state,
                    lambda e: e[0].kind in kinds and (
                        recv in e[1]
                        or (fld is not None and e[0].field == fld)
                    ),
                )
        if name == "os.close" and call.args:
            d = dotted(call.args[0])
            if d:
                state = self._discharge(
                    state, lambda e: e[0].kind == FD and d in e[1]
                )

        # 2. fsync discharges the durability obligations
        if name == "os.fsync" or (
            isinstance(fn, ast.Attribute) and fn.attr == "fsync"
        ):
            target = _fsync_target(call) if name == "os.fsync" else None
            state = self._discharge(
                state,
                lambda e: e[0].kind == DIRTY
                and (target is None or target in e[1]),
            )

        # 3. partial-release handle invocation
        if isinstance(fn, ast.Name) and fn.id in self._handles:
            verb, target_name = self._handles[fn.id]
            kinds = _RELEASE_KINDS.get(verb, ())
            state = self._discharge(
                state, lambda e: e[0].kind in kinds and target_name in e[1]
            )

        # 4. commit sites (R003)
        if name in ("os.replace", "os.rename"):
            state = self._commit_site(call, state)
        if isinstance(fn, ast.Attribute) and (
            _tokens(fn.attr) & _CKPT_TOKENS
        ):
            dirty = [e for e in state if e[0].kind == DIRTY]
            if dirty:
                ob = dirty[0][0]
                self.flow.durability.append(Durability(
                    fi=self.fi, line=call.lineno, kind="checkpoint",
                    detail=(
                        f"checkpoint/cursor write `{call_name(call)}` is "
                        f"ordered BEFORE the fsync covering the bytes "
                        f"written at line {ob.line}"
                    ),
                ))
                # report once per site, then consider it covered
                state = self._discharge(state, lambda e: e[0].kind == DIRTY)

        # 5. writes through a tracked fd dirty the commit protocol
        if isinstance(fn, ast.Attribute) and fn.attr in _WRITE_VERBS:
            recv = dotted(fn.value)
            if recv:
                for ob, names, _trail in state:
                    if ob.kind == FD and recv in names and ob.pathname:
                        dirty = self._intern(call, DIRTY, recv, ob.pathname)
                        state = self._gen(state, dirty, {recv})
                        break

        # 6. hand-offs: obligations passed as arguments
        targets = self.graph_targets(call)
        if arg_names:
            if targets:
                state = self._handoff(call, targets, arg_names, state)
            else:
                # unresolved callee: ownership is unknowable; err on the
                # quiet side (the value may be stashed or released)
                state = self._discharge(
                    state,
                    lambda e: e[0].kind not in (DIRTY,) and (e[1] & arg_names),
                )
        # 7. field-keyed permits released anywhere below the callee
        if targets:
            fields = set()
            for t in targets:
                ts = self.flow.summaries.get(t.key)
                if ts is not None:
                    fields |= ts.fields
            if fields:
                state = self._discharge(
                    state,
                    lambda e: e[0].field is not None and e[0].field in fields,
                )
            if any(
                self.flow.summaries.get(t.key, Summary()).fsyncs_any
                for t in targets
            ):
                state = self._discharge(state, lambda e: e[0].kind == DIRTY)

        # 8. the exception edge: any call may throw; hand-offs above are
        # assumed to stick (may-analysis errs quiet on discharging calls).
        # Logging is contractually non-raising (the logging module
        # swallows handler errors), so backstop handlers that log before
        # releasing stay clean.
        if not _is_nothrow(name):
            ctx.raise_to(state, call.lineno)
        return state

    def graph_targets(self, call) -> list:
        return self.flow.graph.call_targets.get(
            (self.fi.path, id(call)), []
        )

    def _handoff(self, call, targets, arg_names, state):
        """Credit a resolved callee that releases/owns the obligation on
        the caller's behalf; otherwise record the hop in the trail."""
        out = set()
        for entry in state:
            ob, names, trail = entry
            hit = names & arg_names
            if not hit or ob.kind == DIRTY:
                out.add(entry)
                continue
            discharged = False
            hop = None
            for t in targets:
                ts = self.flow.summaries.get(t.key)
                if ts is None:
                    continue
                tparams = t.params()
                offset = 1 if tparams[:1] == ["self"] else 0
                for i, a in enumerate(call.args):
                    d = dotted(a)
                    if d in hit and i + offset < len(tparams):
                        p = tparams[i + offset]
                        if p in ts.releases:
                            discharged = True
                        elif p in ts.calls and self._is_release_handle(a):
                            discharged = True
                for kw in call.keywords:
                    d = dotted(kw.value)
                    if d in hit and kw.arg in tparams:
                        if kw.arg in ts.releases:
                            discharged = True
                        elif kw.arg in ts.calls and self._is_release_handle(
                            kw.value
                        ):
                            discharged = True
                hop = f"{t.path}:{t.qual}:{call.lineno}"
            if discharged:
                continue
            if hop is not None and hop not in trail:
                trail = trail + (hop,)
            out.add((ob, names, trail))
        return frozenset(out)

    def _is_release_handle(self, expr) -> bool:
        """Is this argument itself a bound release (``x.release`` /
        ``functools.partial(x.release)``)? Then a callee that CALLS its
        parameter discharges the obligation."""
        if isinstance(expr, ast.Call):
            return self._partial_handle(expr) is not None
        d = dotted(expr)
        if d is None or "." not in d:
            return d in self._handles if d else False
        return d.rsplit(".", 1)[1] in _RELEASE_KINDS

    def _partial_handle(self, value) -> tuple | None:
        """``functools.partial(x.release)`` -> ("release", "x")."""
        if not isinstance(value, ast.Call):
            return None
        if call_name(value) not in ("partial", "functools.partial"):
            return None
        if not value.args:
            return None
        d = dotted(value.args[0])
        if d is None or "." not in d:
            return None
        obj, verb = d.rsplit(".", 1)
        if verb in _RELEASE_KINDS:
            return (verb, obj)
        return None

    def _commit_site(self, call, state):
        """``os.replace(src, dst)`` / ``os.rename``: the commit point of
        the tmp+fsync+rename protocol. Violated when the bytes renamed
        into place were written on this path with no fsync."""
        src = call.args[0] if call.args else None
        src_d = dotted(src) if src is not None else None
        src_text = src_d or ""
        if src is not None and src_d is None:
            src_text = " ".join(sorted(_names_shallow([src]))) or (
                src.value if isinstance(src, ast.Constant) and isinstance(
                    src.value, str
                ) else ""
            )
        dirty = [e for e in state if e[0].kind == DIRTY]
        matched = [
            e for e in dirty
            if src_d is not None and (
                src_d in e[1] or e[0].pathname == src_d
            )
        ]
        hits = matched or (dirty if _is_tmpish(src_text) else [])
        if hits:
            ob = hits[0][0]
            self.flow.durability.append(Durability(
                fi=self.fi, line=call.lineno, kind="rename",
                detail=(
                    f"tmp file written at line {ob.line} is renamed into "
                    f"its commit location with no fsync of the file on "
                    f"this path"
                ),
            ))
            return self._discharge(state, lambda e: e[0].kind == DIRTY)
        return state

    # -- acquire classification ----------------------------------------------
    def _intern(self, node, kind, label, pathname=None, field=None) -> Obligation:
        key = id(node) if kind != DIRTY else -id(node)
        ob = self._obs.get(key)
        if ob is None:
            ob = Obligation(
                kind=kind, label=label, line=node.lineno, field=field,
                pathname=pathname,
            )
            self._obs[key] = ob
        return ob

    def _classify_call(self, call) -> tuple | None:
        """An acquire-shaped call -> (Obligation, default alias names),
        or None."""
        if not isinstance(call, ast.Call):
            return None
        fn = call.func
        name = call_name(call)
        if isinstance(fn, ast.Attribute):
            recv = dotted(fn.value)
            if fn.attr == "acquire" and recv:
                kind = self.flow._acquire_kind(self.fi, fn)
                if kind is None:
                    return None
                ob = self._intern(
                    call, kind, recv,
                    field=self.flow.field_of(self.fi, recv),
                )
                return ob, {recv}
            if fn.attr in _SPAN_STARTS and recv:
                ob = self._intern(call, SPAN, f"{recv}.{fn.attr}")
                return ob, {f"<span:{call.lineno}>"}
            if fn.attr == "attach" and not call.args and recv:
                if _tokens(recv) & _ATTACH_TOKENS:
                    ob = self._intern(call, ATTACH, recv)
                    return ob, {recv}
        if name in _FD_FUNCS:
            pathname = None
            mode = None
            if call.args:
                a0 = call.args[0]
                pathname = dotted(a0)
                if pathname is None:
                    subnames = sorted(_names_shallow([a0]))
                    tmpish = [n for n in subnames if _is_tmpish(n)]
                    if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
                        pathname = a0.value
                    elif tmpish:
                        pathname = tmpish[0]
                if len(call.args) > 1 and isinstance(
                    call.args[1], ast.Constant
                ) and isinstance(call.args[1].value, str):
                    mode = call.args[1].value
            if name == "open" and mode is not None and (
                "r" in mode and "+" not in mode and "w" not in mode
                and "a" not in mode
            ):
                # read-only opens never owe the durability protocol; the
                # fd lifecycle obligation still applies
                pathname = None
            ob = self._intern(call, FD, name, pathname=pathname)
            return ob, {f"<fd:{call.lineno}>"}
        return None


def _sentinel_test(test) -> tuple | None:
    """``X.trace_id is None`` / ``is not None`` -> (X, branch in which X
    is the sampled-out sentinel): the explicit-handle tracing API's
    discriminator (a sentinel root records nothing and owes nothing)."""
    if not (
        isinstance(test, ast.Compare)
        and len(test.ops) == 1
        and isinstance(test.ops[0], (ast.Is, ast.IsNot))
        and isinstance(test.comparators[0], ast.Constant)
        and test.comparators[0].value is None
        and isinstance(test.left, ast.Attribute)
        and test.left.attr == "trace_id"
    ):
        return None
    name = dotted(test.left.value)
    if name is None:
        return None
    return name, ("then" if isinstance(test.ops[0], ast.Is) else "else")


#: call-name prefixes/names that never raise into caller control flow
_NOTHROW_PREFIXES = ("logger.", "logging.", "log.", "self.logger.", "self.log.")
_NOTHROW_NAMES = frozenset((
    "print", "warnings.warn", "traceback.print_exc",
    # constructing a release handle is not a throwing operation
    "partial", "functools.partial",
))


def _is_nothrow(name: str) -> bool:
    return name in _NOTHROW_NAMES or name.startswith(_NOTHROW_PREFIXES)


def _sub_base(node):
    while isinstance(node, ast.Subscript):
        node = node.value
    return node


def _catches_all(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    types = (
        handler.type.elts if isinstance(handler.type, ast.Tuple)
        else [handler.type]
    )
    for t in types:
        d = dotted(t)
        if d is not None and d.rsplit(".", 1)[-1] in _CATCH_ALL_TYPES:
            return True
    return False


def _calls_in(node):
    """Calls embedded in an expression/statement, in source order,
    without descending into nested function/lambda bodies (those are
    their own flowgraphs)."""
    out = []
    stack = [node]
    while stack:
        cur = stack.pop()
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if isinstance(cur, ast.Call):
            out.append(cur)
        stack.extend(ast.iter_child_nodes(cur))
    out.sort(key=lambda c: (c.lineno, c.col_offset))
    return out
