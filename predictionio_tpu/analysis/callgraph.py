"""Whole-package call graph: the phase-2 analysis substrate.

Phase 1's concurrency rules saw one module at a time with one level of
``self.`` call propagation -- enough for the WAL/snapshot incidents, blind
to the shapes PR 8-12 introduced, where the hazard spans files: the ring
consumer (``serving/procserver.py``) hands a lambda into
``QueryService.submit_query_async`` (``workflow/create_server.py``), which
registers a callback on a ``MicroBatcher`` future
(``workflow/microbatch.py``) that eventually calls the lambda back on the
flusher thread. A blocking call anywhere down that chain stalls every
batch, and no per-module walk can see it.

This module builds a module-qualified call graph over every parsed file:

- **functions**: every ``def``/``async def``/``lambda``, keyed by
  ``(path, qualname)`` (lambdas as ``<enclosing>.<lambda:LINE>``);
- **imports**: absolute and relative package imports, chased through one
  level of ``__init__`` re-exports;
- **types**: light flow-insensitive inference -- ``x = ClassName(...)``
  locals, ``self.attr = ClassName(...)`` instance attributes, and
  parameter annotations naming package classes -- so ``self._batcher
  .submit(...)`` resolves to ``MicroBatcher.submit``;
- **callable references**: ``self._run`` / ``module.func`` / bare names /
  ``functools.partial(fn, ...)`` wrappers / the ``jit(make_step(...))``
  factory form (a call whose callee ``return``s a nested def -- the shape
  ``rules_jax._JitIndex`` already parses);
- **higher-order bindings**: when a resolved call passes a callable
  reference as an argument, the callee's parameter (and any ``self.attr =
  param`` publication of it) resolves future ``param(...)`` calls to that
  reference.  Bindings are unioned globally (context-insensitive) and the
  edge build iterates to a fixpoint, which is exactly what stitches the
  async serving chain above into one path.

The graph is deliberately an over-approximation in places (a name that
several classes define methods for resolves to all of them) and an
under-approximation in others (dynamic dispatch through untyped values
drops the edge); each rule built on top chooses which side to err on.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from predictionio_tpu.analysis.astutil import call_name, dotted

#: the package every analyzed path is resolved under
PACKAGE = "predictionio_tpu"

#: wrappers seen through when resolving a callable reference
_PARTIAL_NAMES = {"partial", "functools.partial"}

#: max re-export / binding fixpoint iterations (chains are short in
#: practice; the cap guards cyclic imports)
_MAX_CHASE = 4
_MAX_FIXPOINT = 5


@dataclass(eq=False)
class FunctionInfo:
    """One def/lambda: the call-graph node."""

    path: str
    qual: str
    node: ast.AST
    cls: str | None          # enclosing class qualname iff a direct method
    module: "ModuleInfo" = None

    @property
    def key(self) -> tuple[str, str]:
        return (self.path, self.qual)

    @property
    def name(self) -> str:
        return self.qual.rsplit(".", 1)[-1]

    def params(self) -> list[str]:
        cached = self.__dict__.get("_params")
        if cached is None:
            a = getattr(self.node, "args", None)
            cached = [] if a is None else [
                p.arg for p in a.posonlyargs + a.args + a.kwonlyargs
            ]
            self.__dict__["_params"] = cached
        return cached


@dataclass(eq=False)
class ClassInfo:
    path: str
    qual: str
    node: ast.ClassDef
    module: "ModuleInfo" = None
    methods: dict = field(default_factory=dict)   # name -> FunctionInfo
    #: attr -> set[ClassInfo]: ``self.attr = ClassName(...)``
    attr_types: dict = field(default_factory=dict)
    #: attr -> set[FunctionInfo]: ``self.attr = <callable ref>``
    attr_callables: dict = field(default_factory=dict)
    #: (method FunctionInfo, param name, attr): ``self.attr = param`` --
    #: resolved against param bindings during the fixpoint
    attr_from_param: list = field(default_factory=list)

    @property
    def key(self) -> tuple[str, str]:
        return (self.path, self.qual)


@dataclass(eq=False)
class ModuleInfo:
    ctx: object                  # engine.ModuleContext
    dotted: str                  # "predictionio_tpu.serving.frontend"
    funcs: dict = field(default_factory=dict)     # qual -> FunctionInfo
    top: dict = field(default_factory=dict)       # module-level name -> FunctionInfo
    classes: dict = field(default_factory=dict)   # clsqual -> ClassInfo
    #: local name -> ("module", dotted) | ("symbol", dotted, name)
    imports: dict = field(default_factory=dict)
    #: statements under ``if __name__ == "__main__":`` (subprocess entry)
    main_body: list = field(default_factory=list)
    #: Import/ImportFrom nodes, collected during the ONE indexing visit
    #: (function-level lazy imports included) so no later pass re-walks
    #: the module tree
    import_nodes: list = field(default_factory=list)
    #: Assign-from-Call nodes (the lock/semaphore-constructor candidates)
    call_assigns: list = field(default_factory=list)

    @property
    def path(self) -> str:
        return self.ctx.path


def module_dotted(path: str) -> str:
    """``predictionio_tpu/serving/frontend.py`` -> its import name."""
    p = path[:-3] if path.endswith(".py") else path
    parts = p.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class CallSite:
    """One resolved call expression inside a function body."""

    __slots__ = ("line", "call", "targets")

    def __init__(self, line: int, call: ast.Call, targets: list):
        self.line = line
        self.call = call
        self.targets = targets   # list[FunctionInfo]


class CallGraph:
    """Package-wide function index + resolved call edges."""

    def __init__(self, contexts: list):
        self.modules: dict[str, ModuleInfo] = {}       # dotted -> ModuleInfo
        self.by_path: dict[str, ModuleInfo] = {}       # path -> ModuleInfo
        self.functions: dict[tuple, FunctionInfo] = {}  # key -> info
        self.classes: dict[tuple, ClassInfo] = {}
        #: fkey -> list[CallSite]
        self.callsites: dict[tuple, list] = {}
        #: (path, id(call ast node)) -> list[FunctionInfo] (locksets uses
        #: this to resolve calls during its own region walk)
        self.call_targets: dict[tuple, list] = {}
        #: (fkey, param) -> set[FunctionInfo]: higher-order bindings
        self.param_bindings: dict[tuple, set] = {}
        self._local_env_cache: dict[tuple, dict] = {}
        self._returned_defs_cache: dict[tuple, list] = {}
        self._params_cache: dict[tuple, frozenset] = {}
        #: id(fn node) -> flattened body-node list; every layer built on
        #: the graph (edges, roles, locksets fast path, the R-series
        #: flowgraphs) re-reads this instead of re-walking the AST --
        #: the body walk was the single hottest loop in the sweep
        self._body_cache: dict[int, list] = {}
        for ctx in contexts:
            self._index_module(ctx)
        self._index_imports()
        self._index_class_attrs()
        self._build_edges()

    # -- indexing -----------------------------------------------------------
    def _index_module(self, ctx) -> None:
        mod = ModuleInfo(ctx=ctx, dotted=module_dotted(ctx.path))
        self.modules[mod.dotted] = mod
        self.by_path[mod.path] = mod
        # nodes outside any function body (module level, decorators,
        # argument defaults) land here: traversed for indexing, read by
        # nobody -- the fill below is what makes body_nodes() free
        dead: list = []

        # iterative pre-order walk with an explicit stack; this touches
        # every node of every module, so generator machinery per node
        # (ast.iter_child_nodes) is what the inlined child iteration
        # below buys back. Stack entries carry the walk context:
        # (node, qual, parent_cls -- class the node is a DIRECT child
        # of, encl_cls -- innermost lexically-enclosing class, body --
        # innermost function's flattened node list)
        AST = ast.AST
        ATOM = _ATOM

        def push_children(stack, node, qual, parent_cls, encl_cls, body):
            sub = []
            append = sub.append
            for name in node._fields:
                f = getattr(node, name, None)
                if isinstance(f, AST):
                    if not isinstance(f, ATOM):
                        append((f, qual, parent_cls, encl_cls, body))
                elif type(f) is list:
                    for item in f:
                        if isinstance(item, AST) and not isinstance(item, ATOM):
                            append((item, qual, parent_cls, encl_cls, body))
            sub.reverse()
            stack.extend(sub)

        def enter_function(stack, child, fq, owner, stmts):
            """Descend into a def/lambda, filling its body-node cache:
            body statements (and their subtrees) go to the function's
            list, decorators/args are indexed but -- like ``_body_walk``
            -- belong to no body. Nested defs and lambdas inside a
            method close over its self, so they resolve self.* against
            the class (owner as encl_cls) even though only direct
            children are METHODS (parent_cls=None below)."""
            fbody: list = []
            self._body_cache[id(child)] = fbody
            body_ids = {id(s) for s in stmts}
            sub = []
            for name in child._fields:
                f = getattr(child, name, None)
                if isinstance(f, AST):
                    if not isinstance(f, ATOM):
                        sub.append((f, fq, None, owner,
                                    fbody if id(f) in body_ids else dead))
                elif type(f) is list:
                    for item in f:
                        if isinstance(item, AST) and not isinstance(item, ATOM):
                            sub.append((item, fq, None, owner,
                                        fbody if id(item) in body_ids
                                        else dead))
            sub.reverse()
            stack.extend(sub)

        stack: list = []
        push_children(stack, ctx.tree, "", None, None, dead)
        while stack:
            child, qual, parent_cls, encl_cls, body = stack.pop()
            t = child.__class__
            if t is ast.ClassDef:
                cq = f"{qual}.{child.name}" if qual else child.name
                cinfo = ClassInfo(mod.path, cq, child, module=mod)
                mod.classes[cq] = cinfo
                self.classes[cinfo.key] = cinfo
                body.append(child)
                push_children(stack, child, cq, cinfo, cinfo, body)
            elif t is ast.FunctionDef or t is ast.AsyncFunctionDef:
                fq = f"{qual}.{child.name}" if qual else child.name
                owner = parent_cls or encl_cls
                info = FunctionInfo(
                    mod.path, fq, child,
                    cls=owner.qual if owner else None,
                    module=mod,
                )
                mod.funcs[fq] = info
                self.functions[info.key] = info
                if parent_cls is not None:
                    parent_cls.methods[child.name] = info
                elif not qual:
                    mod.top[child.name] = info
                enter_function(stack, child, fq, owner, child.body)
            elif t is ast.Lambda:
                fq = f"{qual}.<lambda:{child.lineno}>" if qual else (
                    f"<lambda:{child.lineno}>"
                )
                owner = parent_cls or encl_cls
                info = FunctionInfo(
                    mod.path, fq, child,
                    cls=owner.qual if owner else None,
                    module=mod,
                )
                mod.funcs[fq] = info
                self.functions[info.key] = info
                enter_function(stack, child, fq, owner, [child.body])
            else:
                if t is ast.Import or t is ast.ImportFrom:
                    mod.import_nodes.append(child)
                elif t is ast.Assign:
                    if isinstance(child.value, ast.Call):
                        mod.call_assigns.append(child)
                elif (t is ast.If and qual == ""
                        and _is_main_guard(child.test)):
                    mod.main_body.extend(child.body)
                body.append(child)
                if t is ast.Name or t is ast.Constant:
                    continue  # leaves: nothing left to push
                push_children(
                    stack, child, qual, parent_cls, encl_cls, body
                )

    def _index_imports(self) -> None:
        for mod in self.modules.values():
            for node in mod.import_nodes:
                if isinstance(node, ast.Import):
                    for alias in node.names:
                        if alias.name.startswith(PACKAGE):
                            local = alias.asname or alias.name.split(".")[0]
                            target = (
                                alias.name if alias.asname else
                                alias.name.split(".")[0]
                            )
                            mod.imports[local] = ("module", target)
                elif isinstance(node, ast.ImportFrom):
                    base = self._from_base(mod, node)
                    if base is None:
                        continue
                    for alias in node.names:
                        local = alias.asname or alias.name
                        sub = f"{base}.{alias.name}"
                        if sub in self.modules or not self._has_module(base):
                            mod.imports[local] = ("module", sub)
                        else:
                            mod.imports[local] = ("symbol", base, alias.name)

    def _has_module(self, dotted_name: str) -> bool:
        return dotted_name in self.modules

    def _from_base(self, mod: ModuleInfo, node: ast.ImportFrom) -> str | None:
        if node.level == 0:
            m = node.module or ""
            return m if m.startswith(PACKAGE) else None
        # relative: our dotted name minus (level) trailing components
        # (package __init__ modules count as the package itself)
        parts = mod.dotted.split(".")
        if not mod.path.endswith("__init__.py"):
            parts = parts[:-1]
        parts = parts[: len(parts) - (node.level - 1)] if node.level > 1 else parts
        base = ".".join(parts)
        if node.module:
            base = f"{base}.{node.module}"
        return base if base.startswith(PACKAGE) else None

    def _index_class_attrs(self) -> None:
        for cinfo in self.classes.values():
            for meth in cinfo.methods.values():
                params = set(meth.params())
                for node in self.body_nodes(meth.node):
                    if not isinstance(node, ast.Assign):
                        continue
                    for t in node.targets:
                        d = dotted(t)
                        if not (d and d.startswith("self.") and d.count(".") == 1):
                            continue
                        attr = d[len("self."):]
                        value = node.value
                        if isinstance(value, ast.Call):
                            hit = self._resolve_class_expr(meth, value.func)
                            if hit is not None:
                                cinfo.attr_types.setdefault(attr, set()).add(hit)
                                continue
                        refs = self.resolve_callable(meth, value, _env={})
                        if refs:
                            cinfo.attr_callables.setdefault(
                                attr, set()
                            ).update(refs)
                        elif isinstance(value, ast.Name) and value.id in params:
                            cinfo.attr_from_param.append(
                                (meth, value.id, attr)
                            )

    # -- symbol resolution --------------------------------------------------
    def resolve_symbol(self, dotted_mod: str, name: str, _depth: int = 0):
        """A name exported by a module: ('func', info) | ('class', cinfo)
        | None. Chases one-level ``__init__`` re-exports."""
        mod = self.modules.get(dotted_mod)
        if mod is None or _depth > _MAX_CHASE:
            return None
        if name in mod.top:
            return ("func", mod.top[name])
        if name in mod.classes:
            return ("class", mod.classes[name])
        imp = mod.imports.get(name)
        if imp is not None:
            if imp[0] == "module":
                return ("module", imp[1])
            return self.resolve_symbol(imp[1], imp[2], _depth + 1)
        return None

    def _resolve_class_expr(self, fi: FunctionInfo, expr: ast.AST) -> ClassInfo | None:
        """``ClassName`` / ``mod.ClassName`` / imported name -> ClassInfo."""
        d = dotted(expr)
        if d is None:
            return None
        mod = fi.module
        if "." not in d:
            if d in mod.classes:
                return mod.classes[d]
            hit = self.resolve_symbol(mod.dotted, d)
            if hit and hit[0] == "class":
                return hit[1]
            return None
        root, rest = d.split(".", 1)
        imp = mod.imports.get(root)
        if imp and imp[0] == "module":
            hit = self.resolve_symbol(imp[1], rest)
            if hit and hit[0] == "class":
                return hit[1]
        return None

    def _local_env(self, fi: FunctionInfo) -> dict:
        """name -> ('type', ClassInfo) | ('callables', set[FunctionInfo]);
        from ``x = ClassName(...)`` / ``x = <callable ref>`` assignments
        and class-annotated parameters."""
        cached = self._local_env_cache.get(fi.key)
        if cached is not None:
            return cached
        env: dict = {}
        args = getattr(fi.node, "args", None)
        if args is not None:
            for p in args.posonlyargs + args.args + args.kwonlyargs:
                if p.annotation is not None:
                    ann = p.annotation
                    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                        # "ScorerBridge" string annotations
                        ann = _parse_annotation(ann.value)
                    if ann is not None:
                        hit = self._resolve_class_expr(fi, ann)
                        if hit is not None:
                            env[p.arg] = ("type", hit)
        for node in self.body_nodes(fi.node):
            if not isinstance(node, ast.Assign):
                continue
            names = [
                t.id for t in node.targets if isinstance(t, ast.Name)
            ]
            if not names:
                continue
            value = node.value
            if isinstance(value, ast.Call):
                hit = self._resolve_class_expr(fi, value.func)
                if hit is not None:
                    for n in names:
                        env[n] = ("type", hit)
                    continue
            refs = self.resolve_callable(fi, value, _env={})
            if refs:
                for n in names:
                    env[n] = ("callables", set(refs))
        self._local_env_cache[fi.key] = env
        return env

    def instance_type(self, fi: FunctionInfo, expr: ast.AST) -> ClassInfo | None:
        """Static type of a receiver expression, where inferable:
        ``self`` -> own class; typed local/param; ``self.attr`` with a
        recorded attr type."""
        if isinstance(expr, ast.Name):
            if expr.id == "self" and fi.cls is not None:
                return self.classes.get((fi.path, fi.cls))
            hit = self._local_env(fi).get(expr.id)
            if hit and hit[0] == "type":
                return hit[1]
            return None
        if isinstance(expr, ast.Attribute):
            base = self.instance_type(fi, expr.value)
            if base is not None:
                types = base.attr_types.get(expr.attr)
                if types and len(types) == 1:
                    return next(iter(types))
            return None
        return None

    # -- callable references ------------------------------------------------
    def resolve_callable(
        self, fi: FunctionInfo, expr: ast.AST, _env: dict | None = None
    ) -> list:
        """The function(s) a callable-valued expression denotes: the
        ``Thread(target=...)`` / ``add_done_callback(...)`` argument
        resolver. Returns [] when unresolvable."""
        if isinstance(expr, ast.Lambda):
            for info in fi.module.funcs.values():
                if info.node is expr:
                    return [info]
            return []
        if isinstance(expr, ast.Call):
            name = call_name(expr)
            if name in _PARTIAL_NAMES and expr.args:
                return self.resolve_callable(fi, expr.args[0], _env)
            # factory form: a call whose callee returns a nested def
            # (the jit(make_step(...)) shape)
            out = []
            for factory in self.resolve_callable(fi, expr.func, _env):
                out.extend(self._returned_defs(factory))
            return out
        d = dotted(expr)
        if d is None:
            return []
        env = self._local_env(fi) if _env is None else _env
        if "." not in d:
            hit = env.get(d)
            if hit:
                return list(hit[1]) if hit[0] == "callables" else []
            nested = fi.module.funcs.get(f"{fi.qual}.{d}")
            if nested is not None:
                return [nested]
            if d in fi.module.top:
                return [fi.module.top[d]]
            sym = self.resolve_symbol(fi.module.dotted, d)
            if sym and sym[0] == "func":
                return [sym[1]]
            return []
        root, rest = d.split(".", 1)
        if root == "self" and fi.cls is not None:
            cinfo = self.classes.get((fi.path, fi.cls))
            if cinfo is not None:
                if "." not in rest:
                    if rest in cinfo.methods:
                        return [cinfo.methods[rest]]
                    cands = cinfo.attr_callables.get(rest)
                    if cands:
                        return sorted(cands, key=lambda f: f.key)
                    return self._method_anywhere(fi.module, rest)
                attr, meth = rest.split(".", 1)
                if "." not in meth:
                    for t in cinfo.attr_types.get(attr, ()):  # typed attr
                        if meth in t.methods:
                            return [t.methods[meth]]
            return []
        hit = env.get(root)
        if hit and hit[0] == "type" and "." not in rest:
            m = hit[1].methods.get(rest)
            return [m] if m else []
        imp = fi.module.imports.get(root)
        if imp and imp[0] == "module":
            if "." not in rest:
                sym = self.resolve_symbol(imp[1], rest)
                if sym and sym[0] == "func":
                    return [sym[1]]
            else:
                first, meth = rest.split(".", 1)
                sym = self.resolve_symbol(imp[1], first)
                if sym and sym[0] == "class" and "." not in meth:
                    m = sym[1].methods.get(meth)
                    return [m] if m else []
        # imported class attribute: ClassName.method
        cinfo = None
        if root in fi.module.classes:
            cinfo = fi.module.classes[root]
        else:
            sym = self.resolve_symbol(fi.module.dotted, root)
            if sym and sym[0] == "class":
                cinfo = sym[1]
        if cinfo is not None and "." not in rest:
            m = cinfo.methods.get(rest)
            return [m] if m else []
        return []

    def _params_set(self, fi: FunctionInfo) -> frozenset:
        cached = self._params_cache.get(fi.key)
        if cached is None:
            cached = frozenset(fi.params())
            self._params_cache[fi.key] = cached
        return cached

    def _method_anywhere(self, mod: ModuleInfo, name: str) -> list:
        """``self.X`` with no same-class hit: any unique method named X in
        the module (the phase-1 _LockIndex heuristic, kept for fixtures
        written against it)."""
        hits = [
            c.methods[name] for c in mod.classes.values() if name in c.methods
        ]
        return hits if len(hits) == 1 else []

    def _returned_defs(self, factory: FunctionInfo) -> list:
        cached = self._returned_defs_cache.get(factory.key)
        if cached is not None:
            return cached
        out = []
        for ret in ast.walk(factory.node):
            if isinstance(ret, ast.Return) and isinstance(ret.value, ast.Name):
                nested = factory.module.funcs.get(
                    f"{factory.qual}.{ret.value.id}"
                )
                if nested is not None:
                    out.append(nested)
        self._returned_defs_cache[factory.key] = out
        return out

    # -- call resolution ----------------------------------------------------
    def resolve_call(self, fi: FunctionInfo, call: ast.Call) -> list:
        """The function(s) a call expression may enter."""
        func = call.func
        d = dotted(func)
        if d is None:
            # (lambda ...)(...) and subscripted callees: skip
            return []
        # param(...) through higher-order bindings
        if "." not in d and d in self._params_set(fi):
            return sorted(
                self.param_bindings.get((fi.key, d), ()),
                key=lambda f: f.key,
            )
        if d.startswith("self.") and d.count(".") == 1 and fi.cls is not None:
            cinfo = self.classes.get((fi.path, fi.cls))
            attr = d[len("self."):]
            if cinfo is not None and attr not in cinfo.methods:
                cands = cinfo.attr_callables.get(attr)
                if cands:
                    return sorted(cands, key=lambda f: f.key)
        targets = self.resolve_callable(fi, func)
        if targets:
            return targets
        # ClassName(...): the constructor is the callee
        cls = self._resolve_class_expr(fi, func)
        if cls is not None:
            init = cls.methods.get("__init__")
            return [init] if init is not None else []
        return []

    def _build_edges(self) -> None:
        # first pass: resolve every call once; the fixpoint then only
        # revisits DYNAMIC sites (param calls, attr-callable calls) whose
        # resolution can grow as higher-order bindings land -- the static
        # majority of sites never needs a second look
        dynamic: list[tuple] = []   # (fi, CallSite)
        for fi in list(self.functions.values()):
            params = self._params_set(fi)
            sites: list[CallSite] = []
            for node in self.body_nodes(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                targets = self.resolve_call(fi, node)
                site = CallSite(node.lineno, node, targets)
                sites.append(site)
                self.call_targets[(fi.path, id(node))] = targets
                self._bind_callable_args(fi, node, targets)
                d = dotted(node.func)
                if d is not None:
                    if "." not in d and d in params:
                        dynamic.append((fi, site))
                    elif d.startswith("self.") and d.count(".") == 1:
                        cinfo = (
                            self.classes.get((fi.path, fi.cls))
                            if fi.cls else None
                        )
                        # plain method calls resolve statically; only
                        # attr-callable slots can gain targets later
                        if cinfo is None or d[5:] not in cinfo.methods:
                            dynamic.append((fi, site))
            self.callsites[fi.key] = sites
        for _ in range(_MAX_FIXPOINT):
            changed = self._publish_param_attrs()
            for fi, site in dynamic:
                targets = self.resolve_call(fi, site.call)
                if [t.key for t in targets] != [
                    t.key for t in site.targets
                ]:
                    changed = True
                    site.targets = targets
                    self.call_targets[(fi.path, id(site.call))] = targets
                changed |= self._bind_callable_args(fi, site.call, targets)
            if not changed:
                break

    def _publish_param_attrs(self) -> bool:
        """Fold param bindings into ``self.attr = param`` publications."""
        changed = False
        for cinfo in self.classes.values():
            for meth, param, attr in cinfo.attr_from_param:
                bound = self.param_bindings.get((meth.key, param))
                if bound:
                    cur = cinfo.attr_callables.setdefault(attr, set())
                    if not bound <= cur:
                        cur.update(bound)
                        changed = True
        return changed

    def _bind_callable_args(
        self, fi: FunctionInfo, call: ast.Call, targets: list
    ) -> bool:
        """Record callable-reference arguments against the callee's
        parameters (the higher-order hand-off: ``submit_query_async(req,
        lambda r: ...)`` binds ``on_done`` to the lambda)."""
        changed = False
        for target in targets:
            params = target.params()
            offset = 1 if params[:1] == ["self"] else 0
            for i, arg in enumerate(call.args):
                refs = self._callable_arg(fi, arg)
                if refs and i + offset < len(params):
                    changed |= self._bind(
                        target, params[i + offset], refs
                    )
            for kw in call.keywords:
                if kw.arg is None:
                    continue
                refs = self._callable_arg(fi, kw.value)
                if refs and kw.arg in params:
                    changed |= self._bind(target, kw.arg, refs)
        return changed

    def _callable_arg(self, fi: FunctionInfo, expr: ast.AST) -> list:
        if isinstance(expr, (ast.Lambda, ast.Call)) or dotted(expr) is not None:
            refs = self.resolve_callable(fi, expr)
            # a Call argument that resolves as a *factory* form would be
            # a value, not a callable; only keep explicit partial()s
            if isinstance(expr, ast.Call) and call_name(expr) not in _PARTIAL_NAMES:
                return []
            return refs
        return []

    def _bind(self, target: FunctionInfo, param: str, refs: list) -> bool:
        cur = self.param_bindings.setdefault((target.key, param), set())
        fresh = set(refs) - cur
        if fresh:
            cur.update(fresh)
            return True
        return False

    # -- convenience --------------------------------------------------------
    def body_nodes(self, fn: ast.AST) -> list:
        """The function's body nodes, excluding nested defs/lambdas and
        their subtrees (those are their own call-graph nodes). Filled
        inline during indexing; the fallback (un-indexed nodes, e.g. a
        module tree) filters ``_body_walk`` to the same contract -- the
        raw walk also yields direct-child def statements themselves."""
        cached = self._body_cache.get(id(fn))
        if cached is None:
            cached = self._body_cache[id(fn)] = [
                n for n in _body_walk(fn)
                if not isinstance(
                    n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                )
            ]
        return cached

    def callees(self, fkey: tuple) -> list:
        return self.callsites.get(fkey, [])

    def function_at(self, path: str, qual: str) -> FunctionInfo | None:
        return self.functions.get((path, qual))


def _is_main_guard(test: ast.AST) -> bool:
    return (
        isinstance(test, ast.Compare)
        and isinstance(test.left, ast.Name)
        and test.left.id == "__name__"
        and any(
            isinstance(c, ast.Constant) and c.value == "__main__"
            for c in test.comparators
        )
    )


def _parse_annotation(text: str) -> ast.AST | None:
    try:
        return ast.parse(text, mode="eval").body
    except SyntaxError:
        return None


#: context/operator singletons (Load, Store, Add, Eq, ...): no children,
#: never inspected as standalone nodes (rules read them as ``node.ctx`` /
#: ``node.op`` attributes) -- ~a third of all AST nodes, so both the index
#: walk and every body_nodes() consumer skip them
_ATOM = (ast.expr_context, ast.boolop, ast.operator, ast.unaryop, ast.cmpop)


def _body_walk(fn: ast.AST):
    """Walk a function body without descending into nested defs/lambdas
    (those are their own call-graph nodes)."""
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    stack = list(body)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda) + _ATOM,
            ):
                continue
            stack.append(child)
