"""Rule engine: parse the package, run the J/C/R rule families, report.

The analyzer is deliberately dependency-free (``ast`` + the phase-2
whole-package core -- call graph, thread roles, lockset dataflow -- no
typeshed, no import-time execution of the analyzed code): it has to run
inside tier-1 on a 2-core box in single-digit seconds (files parse in
parallel, the package index builds once), and it encodes THIS repo's
invariants -- the jax version-drift shim policy, the
never-donate-sharded-optimizer-state rule, the no-blocking-I/O-under-
a-lock rule, the Eraser-style lockset race predicate -- not a general
Python lint. See ``docs/static_analysis.md`` for the rule catalog and
the incident each rule encodes (``--explain RULE`` prints any entry).

Baseline contract (``analysis/baseline.json``): accepted findings are keyed
by ``(rule, path, symbol)`` -- line-independent, so unrelated edits don't
churn the file -- and every entry carries a human justification. The
tier-1 gate asserts zero UNSUPPRESSED findings; entries that no longer
match any finding are "stale" and fail ``--self-check``, which is what
makes the baseline a ratchet instead of a dumping ground.
"""

from __future__ import annotations

import ast
import json
import os
import re
import subprocess
import textwrap
import time
from dataclasses import dataclass, field, asdict
from typing import Iterable, Iterator

#: severity ladder (sort order for reports)
SEVERITIES = ("error", "warning")


@dataclass(frozen=True)
class Finding:
    rule_id: str
    severity: str
    path: str          # repo-relative, posix separators
    line: int
    symbol: str        # enclosing "Class.method" / "func" / "<module>"
    message: str
    hint: str = ""
    #: structured witness call path ("path:qual:line" hops) -- rendered
    #: as SARIF codeFlows; interprocedural rules populate it
    witness: tuple = ()
    #: (path, line, label) construction sites backing the finding (the
    #: mesh/spec mint sites of the S rules) -- rendered as SARIF
    #: relatedLocations
    related: tuple = ()

    def key(self) -> tuple:
        return (self.rule_id, self.path, self.symbol)

    def render(self) -> str:
        loc = f"{self.path}:{self.line}"
        hint = f" [fix: {self.hint}]" if self.hint else ""
        return f"{loc}: {self.rule_id} {self.severity}: {self.message}{hint}"


@dataclass
class ModuleContext:
    """One parsed file, shared by every rule."""

    path: str                       # repo-relative
    tree: ast.AST
    source: str
    #: id(node) -> qualname; built LAZILY on first symbol_for() -- the
    #: package rules never ask, so a --changed run only pays the symbol
    #: walk for the files whose module rules actually run
    symbols: dict | None = None

    def symbol_for(self, node: ast.AST) -> str:
        """Qualname of the innermost enclosing def/class, '<module>' else."""
        if self.symbols is None:
            self.symbols = _index_symbols(self.tree)
        return self.symbols.get(id(node), "<module>")


def _index_symbols(tree: ast.AST) -> dict:
    """Map every AST node to its enclosing Class.func qualname."""
    out: dict = {}

    def visit(node: ast.AST, qual: str) -> None:
        for child in ast.iter_child_nodes(node):
            q = qual
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                q = f"{qual}.{child.name}" if qual else child.name
            out[id(child)] = q or "<module>"
            visit(child, q)

    visit(tree, "")
    return out


def package_root() -> str:
    """The ``predictionio_tpu`` package directory (computed from this file:
    the analyzer must not import the analyzed package)."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def repo_root() -> str:
    """Directory holding the ``predictionio_tpu`` package."""
    return os.path.dirname(package_root())


def iter_py_files(root: str) -> Iterator[str]:
    for dirpath, dirnames, filenames in os.walk(root):
        # the analyzer sweep must never descend into bytecode caches or
        # build output (repo-hygiene invariant, also enforced by .gitignore)
        dirnames[:] = [
            d for d in sorted(dirnames)
            if d not in ("__pycache__", "_build", ".git")
        ]
        for name in sorted(filenames):
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


#: (abspath, root) -> (mtime_ns, size, ModuleContext). Repeated
#: in-process checks (check + report in one invocation, self_check, the
#: fixture suite) re-parse an unchanged file for free; any on-disk edit
#: changes the stat signature and invalidates the entry.
_parse_cache: dict = {}


def parse_module(path: str, root: str | None = None) -> ModuleContext | None:
    root = root or repo_root()
    apath = os.path.abspath(path)
    try:
        st = os.stat(apath)
    except OSError:
        # a path that vanished between scoping and parsing (a deleted
        # file in the --changed diff, a mid-run unlink) is skipped like
        # a syntax error, never a crash
        return None
    key = (apath, root)
    hit = _parse_cache.get(key)
    if (hit is not None and hit[0] == st.st_mtime_ns
            and hit[1] == st.st_size):
        return hit[2]
    try:
        with open(apath, "r", encoding="utf-8") as f:
            source = f.read()
    except OSError:
        return None
    rel = os.path.relpath(apath, root).replace(os.sep, "/")
    try:
        tree = ast.parse(source, filename=rel)
    except SyntaxError:
        return None
    ctx = ModuleContext(path=rel, tree=tree, source=source)
    # the signature was taken before the read: if the file changed in
    # between, the stale entry misses on the next stat and re-parses
    _parse_cache[key] = (st.st_mtime_ns, st.st_size, ctx)
    return ctx


def parse_source(source: str, path: str = "fixture.py") -> ModuleContext:
    """Analyze an in-memory snippet (the rule-fixture test entry point)."""
    tree = ast.parse(source, filename=path)
    return ModuleContext(path=path, tree=tree, source=source)


def all_rules() -> list:
    from predictionio_tpu.analysis import (
        rules_concurrency,
        rules_jax,
        rules_protocol,
        rules_resources,
        rules_sharding,
    )

    return [
        cls() for cls in (
            rules_jax.RULES + rules_concurrency.RULES + rules_resources.RULES
            + rules_sharding.RULES + rules_protocol.RULES
        )
    ]


def select_rules(rule_ids: Iterable[str] | None = None) -> list:
    rules = all_rules()
    if not rule_ids:
        return rules
    wanted = {r.upper() for r in rule_ids}
    known = sorted(r.rule_id for r in rules)
    unknown = wanted - set(known)
    if unknown:
        # exit-2 with the catalog, never a silent zero-rule run
        raise ValueError(
            f"unknown rule id(s): {sorted(unknown)} (known: {known})"
        )
    return [r for r in rules if r.rule_id in wanted]


def parse_files(files: list[str], root: str | None = None) -> list[ModuleContext]:
    """Parse many files concurrently (reads overlap; the 2-core sweep
    budget in bench #10 is paid here). Unparseable files are skipped,
    matching ``parse_module``."""
    root = root or repo_root()
    # ast.parse is GIL-bound: on a single-core box the thread pool only
    # adds scheduling overhead, so parse serially there
    if len(files) < 8 or (os.cpu_count() or 2) < 2:
        ctxs = [parse_module(p, root) for p in files]
    else:
        from concurrent.futures import ThreadPoolExecutor

        workers = min(8, max(2, os.cpu_count() or 2))
        with ThreadPoolExecutor(max_workers=workers) as ex:
            ctxs = list(ex.map(lambda p: parse_module(p, root), files))
    return [c for c in ctxs if c is not None]


def check_paths(
    paths: Iterable[str] | None = None,
    rules: list | None = None,
    module_scope: "set[str] | None" = None,
    timings: "dict | None" = None,
) -> list[Finding]:
    """Run the rule set over files/directories; defaults to the package.

    Per-module rules run on each file independently; package rules
    (``check_package``) run ONCE over a shared :class:`PackageIndex`
    built from every parsed file -- scoping the paths scopes the
    interprocedural horizon with them.

    ``module_scope`` (repo-relative paths) restricts the PER-MODULE
    rules to those files while the package rules still see everything
    parsed: a module-rule finding depends only on its own file, so
    ``--changed`` skips the other ~99% of per-module work and stays
    inside the pre-commit latency budget. ``timings`` (optional dict) is
    filled with per-rule-family runtimes in seconds (bench #10).

    The whole run executes with the cyclic garbage collector paused
    (restored on exit): the analysis allocates millions of AST/state
    objects that stay reachable for the run's whole lifetime, and the
    generational collector re-scanning them was measured at ~20% of the
    sweep on the pre-commit path. One run's allocations are bounded by
    the package size, so pausing is safe."""
    import gc

    gc_was_enabled = gc.isenabled()
    if gc_was_enabled:
        gc.disable()
    try:
        return _check_paths(paths, rules, module_scope, timings)
    finally:
        if gc_was_enabled:
            gc.enable()


def _check_paths(paths, rules, module_scope, timings) -> list[Finding]:
    rules = rules if rules is not None else all_rules()
    root = repo_root()
    files: list[str] = []
    for p in paths or [package_root()]:
        if os.path.isdir(p):
            files.extend(iter_py_files(p))
        else:
            files.append(p)
    t0 = time.perf_counter()
    contexts = parse_files(files, root)
    if timings is not None:
        timings["parse"] = time.perf_counter() - t0
    module_rules = [r for r in rules if not hasattr(r, "check_package")]
    package_rules = [r for r in rules if hasattr(r, "check_package")]
    findings: list[Finding] = []

    def charge(rule_id: str, spent: float) -> None:
        if timings is not None:
            fam = rule_id[:1]
            timings.setdefault("families", {})
            timings["families"][fam] = (
                timings["families"].get(fam, 0.0) + spent
            )

    module_contexts = contexts if module_scope is None else [
        c for c in contexts if c.path in module_scope
    ]
    for rule in module_rules:
        t0 = time.perf_counter()
        for ctx in module_contexts:
            findings.extend(rule.check(ctx))
        charge(rule.rule_id, time.perf_counter() - t0)
    if package_rules:
        from predictionio_tpu.analysis.packageindex import PackageIndex

        t0 = time.perf_counter()
        index = PackageIndex.build(contexts)
        if timings is not None:
            timings["index"] = time.perf_counter() - t0
        for rule in package_rules:
            t0 = time.perf_counter()
            findings.extend(rule.check_package(index))
            charge(rule.rule_id, time.perf_counter() - t0)
    findings.sort(key=lambda f: (f.path, f.line, f.rule_id))
    return findings


def changed_files() -> list[str]:
    """Repo-relative ``.py`` files the working tree has touched vs HEAD
    (staged, unstaged, and untracked) -- the ``pio check --changed``
    pre-commit scope.

    Deletions and renames resolve to SURVIVING paths only:
    ``--diff-filter=d`` drops deleted entries at the git level (rename
    sources included -- with rename detection off a rename is a
    delete+add pair), and the existence filter below backstops any git
    that still lists a path with no file behind it. Scoping a vanished
    path would either crash the parse or silently report on nothing."""
    root = repo_root()
    out: set[str] = set()
    for cmd in (
        ["git", "diff", "--name-only", "--diff-filter=d", "HEAD", "--"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        proc = subprocess.run(
            cmd, cwd=root, capture_output=True, text=True, timeout=30
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"{' '.join(cmd)} failed: {proc.stderr.strip() or 'not a git repo?'}"
            )
        out.update(line.strip() for line in proc.stdout.splitlines())
    return sorted(
        f for f in out
        if f.endswith(".py") and os.path.exists(os.path.join(root, f))
    )


# -- baseline -----------------------------------------------------------------

def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "baseline.json")


def load_baseline(path: str | None = None) -> list[dict]:
    path = path or default_baseline_path()
    if not os.path.exists(path):
        return []
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    entries = doc.get("entries", [])
    for e in entries:
        for key in ("rule", "path", "symbol", "justification"):
            if key not in e:
                raise ValueError(f"baseline entry missing {key!r}: {e}")
    return entries


def apply_baseline(
    findings: list[Finding], entries: list[dict]
) -> tuple[list[Finding], list[Finding], list[dict]]:
    """Split findings into (unsuppressed, suppressed); also return entries
    that matched nothing (stale -- the ratchet says delete them)."""
    keys = {(e["rule"], e["path"], e["symbol"]): e for e in entries}
    matched: set[tuple] = set()
    unsuppressed, suppressed = [], []
    for f in findings:
        if f.key() in keys:
            matched.add(f.key())
            suppressed.append(f)
        else:
            unsuppressed.append(f)
    stale = [e for k, e in keys.items() if k not in matched]
    return unsuppressed, suppressed, stale


def write_baseline(
    findings: list[Finding],
    path: str | None = None,
    preserved: list[dict] | None = None,
) -> int:
    """Write a baseline covering every current finding, preserving existing
    justifications; new entries get a TODO that ``--self-check`` rejects
    until a human writes the real reason. ``preserved`` entries (the parts
    of the old baseline a ``--rules``/path-scoped run did NOT re-examine)
    are carried over verbatim instead of silently dropped."""
    path = path or default_baseline_path()
    old = {}
    if os.path.exists(path):
        old = {(e["rule"], e["path"], e["symbol"]): e for e in load_baseline(path)}
    keys = {f.key() for f in findings}
    keys |= {(e["rule"], e["path"], e["symbol"]) for e in (preserved or [])}
    entries = []
    for key in sorted(keys):
        rule, fpath, symbol = key
        prior = old.get(key)
        entries.append({
            "rule": rule,
            "path": fpath,
            "symbol": symbol,
            "justification": prior["justification"] if prior else
            "TODO: justify or fix",
        })
    doc = {"version": 1, "entries": entries}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=False)
        f.write("\n")
    return len(entries)


# -- reports ------------------------------------------------------------------

def render_text(
    unsuppressed: list[Finding], suppressed: list[Finding], stale: list[dict]
) -> str:
    lines = [f.render() for f in unsuppressed]
    if stale:
        lines.append("")
        lines.append("stale baseline entries (fixed findings -- delete them):")
        lines.extend(
            f"  {e['rule']} {e['path']} {e['symbol']}" for e in stale
        )
    lines.append("")
    lines.append(
        f"pio check: {len(unsuppressed)} finding(s), "
        f"{len(suppressed)} baseline-suppressed, {len(stale)} stale entr"
        f"{'y' if len(stale) == 1 else 'ies'}"
    )
    return "\n".join(lines).lstrip("\n")


def render_json(
    unsuppressed: list[Finding], suppressed: list[Finding], stale: list[dict]
) -> str:
    return json.dumps(
        {
            "findings": [asdict(f) for f in unsuppressed],
            "suppressed": [asdict(f) for f in suppressed],
            "stale_baseline": stale,
            "analysis_findings_total": len(unsuppressed),
        },
        indent=2,
    )


#: the schema SARIF output declares (CI annotators key off this)
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def _sarif_location(path: str, line: int, message: str | None = None) -> dict:
    loc = {
        "physicalLocation": {
            "artifactLocation": {"uri": path},
            "region": {"startLine": max(int(line), 1)},
        },
    }
    if message:
        loc["message"] = {"text": message}
    return loc


def _sarif_result(f: Finding, suppressed: bool) -> dict:
    result = {
        "ruleId": f.rule_id,
        "level": "error" if f.severity == "error" else "warning",
        "message": {"text": f.message + (f" [fix: {f.hint}]" if f.hint else "")},
        "locations": [_sarif_location(f.path, f.line)],
    }
    if f.witness:
        # the witness call path ("path:qual:line" hops) becomes a SARIF
        # codeFlow so diff annotators can render the hand-off chain
        flow_locs = []
        for hop in f.witness:
            parts = hop.split(":")
            hop_path, hop_line, label = f.path, f.line, hop
            if parts and parts[0].endswith(".py"):
                hop_path = parts[0]
            if parts and parts[-1].isdigit():
                hop_line = int(parts[-1])
            flow_locs.append({
                "location": _sarif_location(hop_path, hop_line, label),
            })
        result["codeFlows"] = [{"threadFlows": [{"locations": flow_locs}]}]
    if f.related:
        # construction sites backing the finding (the S rules' mesh/spec
        # mint sites) ride as relatedLocations so a CI annotator can link
        # "where the mesh/spec came from" next to the violation
        result["relatedLocations"] = [
            _sarif_location(rpath, rline, label)
            for rpath, rline, label in f.related
        ]
    if suppressed:
        result["suppressions"] = [{"kind": "external"}]
    return result


def render_sarif(
    unsuppressed: list[Finding], suppressed: list[Finding], rules: list,
    stale: "list[dict] | None" = None,
) -> str:
    """SARIF 2.1.0 (``--format sarif``): rule metadata comes from the
    same docstrings that generate the docs tables and ``--explain``
    output, witness paths ride as codeFlows, and baseline-suppressed
    findings are emitted with a ``suppressions`` marker so CI can
    annotate diffs without re-reporting accepted risks. Stale baseline
    entries fail the run (exit 1), so they MUST appear as results too --
    a CI annotator must never render a clean report for a red run."""
    descriptors = []
    for rule in sorted(rules, key=lambda r: r.rule_id):
        flags, incident = _split_doc(rule)
        descriptors.append({
            "id": rule.rule_id,
            "shortDescription": {"text": " ".join(flags.split())[:280] or rule.rule_id},
            "fullDescription": {"text": " ".join(f"{flags} {incident}".split())},
            "defaultConfiguration": {
                "level": "error" if rule.severity == "error" else "warning",
            },
        })
    doc = {
        "$schema": SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "pio-check",
                    "informationUri": (
                        "https://github.com/apache/predictionio"
                    ),
                    "rules": descriptors,
                },
            },
            "results": [
                *(_sarif_result(f, False) for f in unsuppressed),
                *(_sarif_result(f, True) for f in suppressed),
                *({
                    "ruleId": e["rule"],
                    "level": "error",
                    "message": {"text": (
                        f"stale baseline entry for {e['symbol']}: no "
                        f"finding matches it anymore -- the issue was "
                        f"fixed, delete the suppression (the ratchet)"
                    )},
                    "locations": [_sarif_location(e["path"], 1)],
                } for e in (stale or ())),
            ],
        }],
    }
    return json.dumps(doc, indent=2)


# -- inventory reports (shared by --mesh-report and --protocol-report) --------

def render_site_report_text(name: str, sites: list[dict]) -> str:
    """The shared inventory renderer: sites grouped by file plus a
    one-line kind summary. ``--mesh-report`` and ``--protocol-report``
    both route here, so the two reports cannot drift in format."""
    lines: list = []
    counts: dict = {}
    by_path: dict = {}
    for site in sites:
        counts[site["kind"]] = counts.get(site["kind"], 0) + 1
        by_path.setdefault(site["path"], []).append(site)
    for path in sorted(by_path):
        lines.append(f"{path}:")
        for site in by_path[path]:
            lines.append(
                f"  {site['line']}: [{site['kind']}] {site['qual']}: "
                f"{site['detail']}"
            )
    lines.append("")
    lines.append(
        f"{name}: "
        + ", ".join(f"{k}={v}" for k, v in sorted(counts.items()))
        + f" ({len(sites)} sites)"
    )
    return "\n".join(lines)


def render_site_report_json(name: str, sites: list[dict]) -> str:
    counts: dict = {}
    for site in sites:
        counts[site["kind"]] = counts.get(site["kind"], 0) + 1
    return json.dumps({
        "sites": sites,
        "counts": dict(sorted(counts.items())),
        "total": len(sites),
    }, indent=2)


def render_site_report_sarif(name: str, sites: list[dict]) -> str:
    """Inventory sites as note-level SARIF results (one ruleId per site
    kind) so CI annotators ingest the reports through the same pipeline
    as rule findings; round-trips against the json format (same site
    count, same locations)."""
    kinds = sorted({s["kind"] for s in sites})
    doc = {
        "$schema": SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "pio-check",
                    "informationUri": (
                        "https://github.com/apache/predictionio"
                    ),
                    "rules": [
                        {
                            "id": f"{name}/{kind}",
                            "shortDescription": {
                                "text": f"{name} inventory site: {kind}"
                            },
                            "defaultConfiguration": {"level": "note"},
                        }
                        for kind in kinds
                    ],
                },
            },
            "results": [
                {
                    "ruleId": f"{name}/{s['kind']}",
                    "level": "note",
                    "message": {
                        "text": f"{s['qual']}: {s['detail']}"
                    },
                    "locations": [
                        _sarif_location(s["path"], s["line"])
                    ],
                }
                for s in sites
            ],
        }],
    }
    return json.dumps(doc, indent=2)


def self_check(baseline_path: str | None = None) -> list[str]:
    """Cheap integrity pass: rules compile and are well-formed, every
    baseline entry still matches a real finding and carries a real
    justification. Returns a list of problems (empty = healthy)."""
    problems: list[str] = []
    rules = all_rules()
    seen_ids: set[str] = set()
    for rule in rules:
        if not rule.rule_id or rule.rule_id in seen_ids:
            problems.append(f"bad/duplicate rule id on {type(rule).__name__}")
        seen_ids.add(rule.rule_id)
        if rule.severity not in SEVERITIES:
            problems.append(f"{rule.rule_id}: bad severity {rule.severity!r}")
        if not getattr(rule, "check", None):
            problems.append(f"{rule.rule_id}: no check()")
        if not (type(rule).__doc__ or "").strip():
            problems.append(
                f"{rule.rule_id}: no docstring (it IS the --explain "
                f"entry and the docs table row)"
            )
    try:
        entries = load_baseline(baseline_path)
    except (ValueError, json.JSONDecodeError) as exc:
        return problems + [f"baseline unreadable: {exc}"]
    findings = check_paths(rules=rules)
    _, _, stale = apply_baseline(findings, entries)
    for e in stale:
        problems.append(
            f"stale baseline entry (no matching finding -- delete it): "
            f"{e['rule']} {e['path']} {e['symbol']}"
        )
    for e in entries:
        just = e.get("justification", "").strip()
        if not just or just.startswith("TODO"):
            problems.append(
                f"baseline entry lacks a justification: "
                f"{e['rule']} {e['path']} {e['symbol']}"
            )
    return problems


# -- the incident catalog (docstrings ARE the docs) ---------------------------

_INCIDENT_RE = re.compile(r"\bIncident\b")

#: markers the generated tables live between in docs/static_analysis.md
DOCS_TABLE_BEGIN = "<!-- BEGIN GENERATED RULE TABLE: {family} (pio check --update-docs) -->"
DOCS_TABLE_END = "<!-- END GENERATED RULE TABLE: {family} -->"

#: every docstring-generated rule family, in docs order
DOC_FAMILIES = ("J", "C", "R", "S", "P")


def _split_doc(rule) -> tuple[str, str]:
    """A rule docstring split into (what it flags, the incident it
    encodes) at the first 'Incident' sentence. The docstring is the
    single source: ``--explain`` prints it whole, the docs table renders
    this split -- CLI and docs cannot drift."""
    doc = textwrap.dedent(
        (type(rule).__doc__ or "").strip("\n")
    ).strip()
    # dedent misses the first line (no leading whitespace); normalize all
    doc = "\n".join(line.strip() for line in doc.splitlines())
    m = _INCIDENT_RE.search(doc)
    if m is None:
        return doc, ""
    return doc[: m.start()].rstrip(" .\n"), doc[m.start():]


def _table_cell(text: str) -> str:
    text = " ".join(text.split())
    text = re.sub(r"^Incident[^:]*:\s*", "", text)
    return text.replace("|", "\\|")


def explain(rule_id: str) -> str:
    """The incident-catalog entry for one rule (``--explain RULE``):
    the rule class docstring, verbatim."""
    rules = {r.rule_id: r for r in all_rules()}
    rule = rules.get(rule_id.upper())
    if rule is None:
        raise ValueError(
            f"unknown rule id {rule_id!r} (known: {sorted(rules)})"
        )
    flags, incident = _split_doc(rule)
    if not flags:
        raise ValueError(
            f"rule {rule.rule_id} has no docstring to explain (the "
            f"docstring IS the incident-catalog entry; --self-check "
            f"should have caught this)"
        )
    body = flags + ("\n\n" + incident if incident else "")
    return f"{rule.rule_id} ({rule.severity})\n\n{body}\n"


def render_rule_table(family: str) -> str:
    """The markdown rule table for one family ('J' or 'C'), generated
    from the rule docstrings. Embedded in docs/static_analysis.md
    between the DOCS_TABLE markers by ``--update-docs``; a tier-1 test
    asserts the committed docs match this output."""
    rows = [
        "| rule | severity | what it flags | the incident it encodes |",
        "|---|---|---|---|",
    ]
    for rule in sorted(all_rules(), key=lambda r: r.rule_id):
        if not rule.rule_id.startswith(family):
            continue
        flags, incident = _split_doc(rule)
        rows.append(
            f"| {rule.rule_id} | {rule.severity} | {_table_cell(flags)} "
            f"| {_table_cell(incident) or '—'} |"
        )
    return "\n".join(rows)


def default_docs_path() -> str:
    return os.path.join(repo_root(), "docs", "static_analysis.md")


def update_docs(path: str | None = None) -> list[str]:
    """Rewrite the generated rule-table blocks in the docs file; returns
    the families replaced. A family whose markers are missing raises --
    silently skipping one would leave its table stale while reporting
    success."""
    path = path or default_docs_path()
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    missing = [
        family for family in DOC_FAMILIES
        if DOCS_TABLE_BEGIN.format(family=family) not in text
        or DOCS_TABLE_END.format(family=family) not in text
    ]
    if missing:
        raise ValueError(
            f"docs rule-table markers missing for famil"
            f"{'y' if len(missing) == 1 else 'ies'} {', '.join(missing)} "
            f"in {path}"
        )
    replaced = []
    for family in DOC_FAMILIES:
        begin = DOCS_TABLE_BEGIN.format(family=family)
        end = DOCS_TABLE_END.format(family=family)
        head, rest = text.split(begin, 1)
        _, tail = rest.split(end, 1)
        text = f"{head}{begin}\n{render_rule_table(family)}\n{end}{tail}"
        replaced.append(family)
    with open(path, "w", encoding="utf-8") as f:
        f.write(text)
    return replaced


def add_check_arguments(parser) -> None:
    """The ``pio check`` flag surface, defined ONCE -- shared by the
    standalone CLI (``python -m predictionio_tpu.analysis``) and the
    ``pio check`` subcommand in ``tools/engine_commands.py``."""
    parser.add_argument(
        "paths", nargs="*",
        help="files/dirs to analyze (default: the predictionio_tpu package)",
    )
    parser.add_argument(
        "--explain", default=None, metavar="RULE",
        help="print RULE's incident-catalog entry (the rule docstring "
        "that also generates the docs table) and exit",
    )
    parser.add_argument(
        "--changed", action="store_true",
        help="scope the report to files git says changed vs HEAD "
        "(pre-commit use; the interprocedural analysis still sees the "
        "whole package, and out-of-scope baseline entries never go stale)",
    )
    parser.add_argument(
        "--update-docs", action="store_true",
        help="regenerate the rule tables in docs/static_analysis.md "
        "from the rule docstrings",
    )
    parser.add_argument(
        "--mesh-report", action="store_true",
        help="emit the inventory of mesh/shard_map/PartitionSpec/"
        "NamedSharding/sharded-jit construction sites (text, json, or "
        "sarif via --format) instead of running the rules -- the MPMD "
        "executor-extraction worklist",
    )
    parser.add_argument(
        "--protocol-report", action="store_true",
        help="emit the inventory of declared cross-process protocol "
        "points -- every commit (fsync/rename), publication (ring push, "
        "registry publish, notify, ack), and cursor-advance site with "
        "its protocol (text, json, or sarif via --format) instead of "
        "running the rules",
    )
    parser.add_argument(
        "--rules", default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--format", choices=("text", "json", "sarif"), default="text",
        help="sarif = SARIF 2.1.0 (rule metadata from the docstrings, "
        "witness paths as codeFlows) for CI diff annotation",
    )
    parser.add_argument(
        "--baseline", default=None,
        help="baseline JSON (default: predictionio_tpu/analysis/baseline.json;"
        " 'none' disables suppression)",
    )
    parser.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline to cover every current finding "
        "(existing justifications preserved; new entries get a TODO "
        "that --self-check rejects)",
    )
    parser.add_argument(
        "--self-check", action="store_true",
        help="verify rules compile and baseline entries still correspond "
        "to real findings",
    )


def _scope(paths: list[str]) -> tuple[set[str], list[str]] | None:
    """CLI paths normalized to repo-relative (files, dirs); None = full run."""
    if not paths:
        return None
    root = repo_root()

    def rel(p: str) -> str:
        return os.path.relpath(os.path.abspath(p), root).replace(os.sep, "/")

    files = {rel(p) for p in paths if not os.path.isdir(p)}
    dirs = [rel(p) for p in paths if os.path.isdir(p)]
    return files, dirs


def _entry_in_scope(entry: dict, ran: set[str], scope) -> bool:
    """Did this run re-examine the code a baseline entry points at? Only
    in-scope entries may be reported stale or rewritten; the rest of the
    baseline is carried through untouched."""
    if entry["rule"] not in ran:
        return False
    if scope is None:
        return True
    files, dirs = scope
    return entry["path"] in files or any(
        entry["path"] == d or entry["path"].startswith(d + "/") for d in dirs
    )


def run_with_args(args) -> int:
    """Execute a parsed ``pio check`` invocation."""
    if getattr(args, "explain", None):
        try:
            print(explain(args.explain), end="")
        except ValueError as exc:
            print(f"Error: {exc}")
            return 2
        return 0
    if getattr(args, "update_docs", False):
        try:
            replaced = update_docs()
        except (ValueError, OSError) as exc:
            print(f"Error: {exc}")
            return 2
        print(
            f"docs rule table(s) regenerated: {', '.join(replaced)}-series"
        )
        return 0
    wants_mesh = getattr(args, "mesh_report", False)
    wants_protocol = getattr(args, "protocol_report", False)
    if wants_mesh and wants_protocol:
        print("Error: --mesh-report and --protocol-report are exclusive")
        return 2
    if wants_mesh or wants_protocol:
        missing = [p for p in args.paths if not os.path.exists(p)]
        if missing:
            print(f"Error: no such file or directory: {', '.join(missing)}")
            return 2
        from predictionio_tpu.analysis.packageindex import PackageIndex

        root = repo_root()
        files: list[str] = []
        for p in args.paths or [package_root()]:
            if os.path.isdir(p):
                files.extend(iter_py_files(p))
            else:
                files.append(p)
        index = PackageIndex.build(parse_files(files, root))
        if wants_mesh:
            name, sites = "mesh-report", index.meshflow().report_sites()
        else:
            name, sites = (
                "protocol-report", index.protocols().report_sites()
            )
        if args.format == "json":
            print(render_site_report_json(name, sites))
        elif args.format == "sarif":
            print(render_site_report_sarif(name, sites))
        else:
            print(render_site_report_text(name, sites))
        return 0
    if args.self_check:
        problems = self_check(
            None if args.baseline in (None, "none") else args.baseline
        )
        if problems:
            for p in problems:
                print(f"self-check: {p}")
            return 1
        print("self-check OK: rules compile, baseline entries all live")
        return 0

    try:
        rules = select_rules(
            [r for r in (args.rules or "").split(",") if r.strip()] or None
        )
    except ValueError as exc:
        print(f"Error: {exc}")
        return 2
    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(f"Error: no such file or directory: {', '.join(missing)}")
        return 2
    if getattr(args, "changed", False):
        # the full package still parses (package rules need the whole
        # call graph); only the REPORT narrows to the changed files,
        # with the same path-scoped baseline semantics as explicit
        # paths: out-of-scope entries are never reported stale
        if args.paths:
            print("Error: --changed and explicit paths are mutually exclusive")
            return 2
        try:
            changed = changed_files()
        except (RuntimeError, OSError, subprocess.SubprocessError) as exc:
            print(f"Error: --changed needs git: {exc}")
            return 2
        root = repo_root()
        pkg_rel = os.path.relpath(package_root(), root).replace(os.sep, "/")
        extra = [
            os.path.join(root, f) for f in changed
            if not f.startswith(pkg_rel + "/")
        ]
        changed_set = set(changed)
        # module rules scoped to the changed files (their findings only
        # depend on the file itself); package rules keep the whole-
        # package horizon -- this is what holds the pre-commit run under
        # its 2 s budget
        findings = check_paths(
            [package_root()] + extra, rules, module_scope=changed_set
        )
        findings = [f for f in findings if f.path in changed_set]
        ran = {r.rule_id for r in rules}
        scope = (changed_set, [])
    else:
        findings = check_paths(args.paths or None, rules)
        ran = {r.rule_id for r in rules}
        scope = _scope(args.paths)
    if args.update_baseline:
        if args.baseline == "none":
            print("Error: --update-baseline with --baseline none makes no sense")
            return 2
        # a --rules/path-scoped run rewrites only what it re-examined; the
        # rest of the baseline (other rules, other paths -- and their
        # human-written justifications) is preserved verbatim
        preserved = [
            e for e in load_baseline(args.baseline)
            if not _entry_in_scope(e, ran, scope)
        ]
        n = write_baseline(findings, args.baseline, preserved=preserved)
        print(f"baseline rewritten: {n} entr{'y' if n == 1 else 'ies'}")
        return 0
    entries = [] if args.baseline == "none" else load_baseline(args.baseline)
    # out-of-scope entries (unrun rules / unanalyzed paths) must not be
    # reported stale: this run produced no evidence about them
    entries = [e for e in entries if _entry_in_scope(e, ran, scope)]
    unsuppressed, suppressed, stale = apply_baseline(findings, entries)
    if args.format == "json":
        print(render_json(unsuppressed, suppressed, stale))
    elif args.format == "sarif":
        print(render_sarif(unsuppressed, suppressed, rules, stale))
    else:
        print(render_text(unsuppressed, suppressed, stale))
    return 1 if (unsuppressed or stale) else 0


def run_cli(argv: list[str] | None = None) -> int:
    """Shared implementation of ``pio check`` and
    ``python -m predictionio_tpu.analysis``."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="pio check",
        description="JAX-aware static analysis + concurrency lint "
        "(rule catalog: docs/static_analysis.md)",
    )
    add_check_arguments(parser)
    return run_with_args(parser.parse_args(argv))
