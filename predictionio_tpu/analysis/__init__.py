"""``pio check``: JAX-aware static analysis + concurrency lint.

Rule families (catalog with incidents: ``docs/static_analysis.md``):

- **J-series** (``rules_jax``): the jax version-drift and tracing
  invariants -- drift-shim policy (J001), legacy donation miscompile
  (J002), control flow on tracers (J003), host sync inside jit (J004),
  the 0.4.37 concat+reshard GSPMD miscompile (J005).
- **C-series** (``rules_concurrency``): lock-order cycles (C001),
  blocking I/O under a lock (C002), cross-thread unlocked mutation (C003).

``analysis/baseline.json`` suppresses accepted findings (with mandatory
justifications); the tier-1 gate in ``tests/test_analysis.py`` asserts
zero unsuppressed findings over the package. ``analysis/lockwatch.py`` is
the runtime companion validating C001 against actual acquisition orders
under pytest.
"""

from predictionio_tpu.analysis.engine import (  # noqa: F401
    Finding,
    all_rules,
    apply_baseline,
    check_paths,
    load_baseline,
    parse_source,
    run_cli,
    self_check,
)
