"""``pio check``: JAX-aware static analysis + interprocedural
concurrency lint.

Rule families (catalog with incidents: ``docs/static_analysis.md``;
``pio check --explain RULE`` prints any entry):

- **J-series** (``rules_jax``): the jax version-drift and tracing
  invariants -- drift-shim policy (J001), legacy donation miscompile
  (J002), control flow on tracers (J003), host sync inside jit (J004),
  the 0.4.37 concat+reshard GSPMD miscompile (J005), loop-invariant
  h2d transfers (J006).
- **C-series** (``rules_concurrency``): built on the phase-2 whole-
  package core -- call graph (``callgraph``), thread-role inference
  (``threadroles``), lockset dataflow (``locksets``), shared via
  ``packageindex``. Lock-order cycles over call paths (C001), blocking
  I/O under caller-held locks (C002), fork-after-threads (C004),
  blocking calls reachable from flusher callbacks / event loops (C005),
  and the Eraser-style lockset race detector (C006, which replaced
  C003's allowlisted per-module walk).
- **R-series** (``rules_resources``): exception-path resource-lifecycle
  analysis on the phase-3 flowgraph layer (``flowgraph``): per-function
  CFGs with explicit exception edges and a must-release obligation
  domain, credited interprocedurally through the call graph. Permits/
  locks/fds leaked on exception paths (R001), spans neither finished
  nor detached (R002), tmp+fsync+rename / checkpoint-ordering
  durability violations (R003), obligations that die with no owner
  (R004).
- **S-series** (``rules_sharding``): sharding semantics on the phase-4
  meshflow layer (``meshflow``): mesh/PartitionSpec/NamedSharding
  construction sites, shard_map bindings, and collectives tracked as an
  abstract domain over the call graph. Collectives over unbound axis
  names (S001), specs placed on meshes lacking their axes (S002),
  pallas_call opaque to GSPMD outside shard_map under a multi-axis mesh
  (S003), read-after-donate (S004), global placement inside shard_map
  bodies (S005). ``pio check --mesh-report`` renders the same layer as
  the mesh/shard_map/spec site inventory.
- **P-series** (``rules_protocol``): cross-process protocol ordering on
  the phase-5 protocolflow layer (``protocols``): a declared table of
  each protocol's commit/publication/advance points, classified per call
  site and credited transitively over the call graph, plus per-module
  ``__main__`` process roles stitched through ring/portfile/notify
  edges. Ack reachable before its covering commit (P001), cursor
  advance before the consumer obligation completes (P002), unguarded
  cross-process version reads (P003), shard/partition moduli bypassing
  ``utils/stablehash`` (P004), handshake renames without covering fsync
  and READY files consumed without CRC verify (P005).
  ``pio check --protocol-report`` renders the same layer as the
  commit/publish/advance site inventory.

``analysis/baseline.json`` suppresses accepted findings (with mandatory
justifications; P entries additionally name the runtime test covering
the accepted risk); the tier-1 gate in ``tests/test_analysis.py``
asserts zero unsuppressed findings over the package. ``analysis/lockwatch.py``
and ``analysis/leakwatch.py`` are the runtime companions: lockwatch
validates C001 against actual acquisition orders under pytest and
records held locksets for C006's evidence; leakwatch watches span
lifecycles and package semaphore balances so an R-series leak a test
provokes fails that test with the site named.
"""

from predictionio_tpu.analysis.engine import (  # noqa: F401
    Finding,
    all_rules,
    apply_baseline,
    changed_files,
    check_paths,
    explain,
    load_baseline,
    parse_files,
    parse_source,
    render_rule_table,
    run_cli,
    self_check,
    update_docs,
)
