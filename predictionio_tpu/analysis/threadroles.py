"""Thread-role inference: WHICH threads can execute each function.

The phase-1 concurrency rules modeled two roles per class (the lexical
``Thread(target=self.X)`` entry vs public methods) and could not see a
role cross a module boundary. The serving/online tiers broke that model:
the micro-batcher's flusher resolves futures whose done-callbacks live
two modules away, the scorer bridge's ring consumer is an event loop on
its own thread, frontend workers are whole subprocesses entered through
``__main__``, and the retrain loop's follower thread calls into the
registry that request threads also touch.

This module seeds roles at every construction the package uses:

- ``thread``: ``threading.Thread(target=f)`` targets, plus
  ``ServiceThread`` HOOKS (its ``on_stop`` teardown callable -- the
  serve loop itself dispatches stdlib handlers no static resolver can
  see) -- each construction site is a DISTINCT role (two different
  threads are two different execution contexts);
- ``timer``: ``threading.Timer(interval, f)`` bodies;
- ``callback``: functions registered via ``Future.add_done_callback`` --
  the flusher role: they run on whatever thread RESOLVES the future
  (the micro-batcher's flusher on the serving path);
- ``eventloop``: bodies of ``select``/``selectors`` polling loops (the
  frontend worker's single-threaded serve loop, the bridge's ring
  consumer). NOTE: an event loop is a *scheduling* discipline, not a
  thread identity -- C005-style stall rules treat it as a role, while
  C006's race detection folds it into whichever thread runs it;
- ``main``: calls made under a module's ``if __name__ == "__main__":``
  guard -- the subprocess entry points (``python -m ...`` workers).

Roles then propagate over the whole-package call graph: every function
reachable from a role's entry point carries that role, with a witness
path (the call chain from the entry) kept for reporting.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from predictionio_tpu.analysis.astutil import call_name, keyword
from predictionio_tpu.analysis.callgraph import CallGraph

#: role kinds that denote a distinct concurrent execution context (used
#: by C006; ``eventloop`` is excluded -- see module docstring)
CONCURRENT_KINDS = ("thread", "timer", "callback", "main")


@dataclass(frozen=True)
class Role:
    kind: str     # thread | timer | callback | eventloop | main
    seed: str     # "path:line" of the construction / guard site

    @property
    def label(self) -> str:
        return f"{self.kind}@{self.seed}"


class RoleInference:
    """Seed + propagate roles; query per-function role sets and witness
    call paths."""

    def __init__(self, graph: CallGraph):
        self.graph = graph
        #: fkey -> set[Role]
        self.roles: dict[tuple, set] = {}
        #: (fkey, role) -> (parent fkey | None, call line | None)
        self._parent: dict[tuple, tuple] = {}
        self._seed_entries: list[tuple] = []  # (Role, fkey)
        self._seed()
        self._propagate()

    # -- seeds --------------------------------------------------------------
    def _seed(self) -> None:
        for fi in self.graph.functions.values():
            for node in self.graph.body_nodes(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                site = f"{fi.path}:{node.lineno}"
                if name == "ServiceThread" or name.endswith(".ServiceThread"):
                    # ServiceThread(server, on_stop=...): serve_forever
                    # dispatches stdlib handlers we cannot resolve, but
                    # its HOOKS (the on_stop teardown callable) run on
                    # whatever thread stops the service, concurrent with
                    # request handlers -- seed those
                    for kw in node.keywords:
                        self._add_seed(fi, "thread", site, kw.value)
                    for arg in node.args[1:]:
                        self._add_seed(fi, "thread", site, arg)
                elif name == "threading.Thread" or name.endswith(".Thread") or (
                    name == "Thread"
                ):
                    kw = keyword(node, "target")
                    if kw is not None:
                        self._add_seed(fi, "thread", site, kw.value)
                elif name == "threading.Timer" or name.endswith(".Timer") or (
                    name == "Timer"
                ):
                    target = None
                    kw = keyword(node, "function")
                    if kw is not None:
                        target = kw.value
                    elif len(node.args) >= 2:
                        target = node.args[1]
                    if target is not None:
                        self._add_seed(fi, "timer", site, target)
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "add_done_callback"
                    and node.args
                ):
                    self._add_seed(fi, "callback", site, node.args[0])
            if self._is_select_loop(fi):
                role = Role("eventloop", f"{fi.path}:{fi.node.lineno}")
                self._seed_entries.append((role, fi.key))
        for mod in self.graph.modules.values():
            if not mod.main_body:
                continue
            entry = _MainEntry(mod)
            for stmt in mod.main_body:
                for node in ast.walk(stmt):
                    if isinstance(node, ast.Call):
                        for target in self.graph.resolve_call(entry, node):
                            role = Role("main", f"{mod.path}:{node.lineno}")
                            self._seed_entries.append((role, target.key))

    def _add_seed(self, fi, kind: str, site: str, expr: ast.AST) -> None:
        for target in self.graph.resolve_callable(fi, expr):
            self._seed_entries.append((Role(kind, site), target.key))

    def _is_select_loop(self, fi) -> bool:
        """A while-loop body that polls ``*.select(...)``: the
        single-thread event-loop shape (frontend serve, ring consumer)."""
        for node in self.graph.body_nodes(fi.node):
            if not isinstance(node, ast.While):
                continue
            for inner in ast.walk(node):
                if (
                    isinstance(inner, ast.Call)
                    and isinstance(inner.func, ast.Attribute)
                    and inner.func.attr == "select"
                ):
                    return True
        return False

    # -- propagation --------------------------------------------------------
    def _propagate(self) -> None:
        work: list[tuple] = []
        for role, fkey in self._seed_entries:
            if fkey not in self.graph.functions:
                continue
            if role not in self.roles.setdefault(fkey, set()):
                self.roles[fkey].add(role)
                self._parent[(fkey, role)] = (None, None)
                work.append((fkey, role))
        while work:
            fkey, role = work.pop()
            for site in self.graph.callees(fkey):
                for target in site.targets:
                    tset = self.roles.setdefault(target.key, set())
                    if role in tset:
                        continue
                    tset.add(role)
                    self._parent[(target.key, role)] = (fkey, site.line)
                    work.append((target.key, role))

    # -- queries ------------------------------------------------------------
    def roles_of(self, fkey: tuple) -> set:
        return self.roles.get(fkey, set())

    def entries(self, kinds: tuple) -> list:
        """(Role, entry fkey) seeds whose kind is in ``kinds``."""
        return [
            (role, fkey) for role, fkey in self._seed_entries
            if role.kind in kinds and fkey in self.graph.functions
        ]

    def witness_path(self, fkey: tuple, role: Role) -> list[str]:
        """Call chain from the role's entry point to ``fkey``:
        ``["path:qual", "path:qual:line", ...]`` (entry first)."""
        chain: list[tuple] = []
        cur = fkey
        seen = set()
        while cur is not None and cur not in seen:
            seen.add(cur)
            parent = self._parent.get((cur, role))
            if parent is None:
                break
            chain.append((cur, parent[1]))
            cur = parent[0]
        chain.reverse()
        out = []
        for (path, qual), line in chain:
            out.append(f"{path}:{qual}" + (f":{line}" if line else ""))
        return out


class _MainEntry:
    """A pseudo-FunctionInfo for resolving calls made at a module's
    ``__main__`` guard (module scope: no self, no params)."""

    def __init__(self, mod):
        self.path = mod.path
        self.qual = "<module>"
        self.cls = None
        self.module = mod
        self.node = mod.ctx.tree
        self.key = (mod.path, "<module>")

    def params(self) -> list:
        return []
