"""Cross-process protocol facts: declared commit/publish/advance points.

PRs 18-19 turned the system into a multi-process fabric -- N scorer
shards behind a swap-epoch protocol, P WAL partitions each with its own
fsync stream and follower cursor, portfile handshakes, and one shared
``stablehash`` bucket function that ingest and serving must agree on
forever.  Every per-process family (J/C/R/S) stops at the process
boundary; the P series lifts the discipline to cross-process
happens-before.

The model is *declared*, not inferred: ``PROTOCOLS`` is a small table
naming, per protocol, its

- **commit points** -- the calls that make state durable (``os.fsync``,
  the WAL's group-commit ``sync``, a directory-entry fsync);
- **publication points** -- the calls that make state visible to a peer
  process (ring push, registry publish, the ``/models/swap`` notify, a
  handshake ``os.replace``, a future/HTTP 2xx ack);
- **advance points** -- the calls that move a replay cursor or
  checkpoint past consumed input.

``ProtocolFlow`` classifies every call site in the package against this
table (one pass over the shared call graph, cached on the
``PackageIndex`` like ``ResourceFlow``/``MeshFlow``), folds the tags
transitively over call edges, seeds *process roles* from each module's
``__main__`` guard (each entry module is a DISTINCT role -- the
cross-process analogue of PR 13's thread roles), and exposes the
path-sensitive ordering scans the P rules are built on.  The same site
inventory backs ``pio check --protocol-report``.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass


# -- the declared-protocol table ----------------------------------------------

@dataclass(frozen=True)
class Point:
    """One declared protocol point: a syntactic recognizer for the calls
    that commit, publish, advance, or consume protocol state."""

    role: str                 # "commit" | "publish" | "advance" | "consume"
    kind: str                 # stable site label ("fsync", "ring-push", ...)
    names: tuple = ()         # exact dotted call names ("os.fsync",)
    suffixes: tuple = ()      # dotted-name suffixes (".append",)
    name_all: tuple = ()      # every token must appear in the call name
    recv_any: tuple = ()      # receiver (dotted prefix) token allow-list
    target_any: tuple = ()    # substring match against resolved arg text
    arg_2xx: bool = False     # first positional arg must be a 2xx constant


@dataclass(frozen=True)
class Protocol:
    """One cross-process protocol: its ordering contract plus the table
    of declared points the analysis recognizes."""

    name: str
    contract: str
    points: tuple
    guard_tokens: tuple = ()   # swap-epoch: version-guard field tokens
    layout_tokens: tuple = ()  # handshake: targets needing a dir fsync
    verify_tokens: tuple = ()  # handshake: targets needing a CRC verify
    blessed: str = ""          # routing: the one blessed implementation


PROTOCOLS = (
    Protocol(
        name="wal-ack",
        contract=(
            "every ack (future result, HTTP 2xx, ring completion) is "
            "preceded by the fsync covering the writes it acknowledges"
        ),
        points=(
            Point("write", "wal-append", suffixes=(".append",),
                  recv_any=("wal", "journal")),
            Point("commit", "fsync", names=("os.fsync",),
                  suffixes=(".fsync",)),
            Point("commit", "group-commit", suffixes=(".sync",),
                  recv_any=("wal", "journal")),
            Point("publish", "future-ack", suffixes=(".set_result",)),
            Point("publish", "http-2xx", suffixes=(".send_response",),
                  arg_2xx=True),
            Point("publish", "ring-completion", suffixes=(".push",),
                  recv_any=("ring", "rings", "ctl", "requests",
                            "completions")),
        ),
    ),
    Protocol(
        name="replay-cursor",
        contract=(
            "publish -> notify -> cursor advance; a cursor or checkpoint "
            "never passes input whose consumer obligation is still open"
        ),
        points=(
            Point("publish", "registry-publish", suffixes=(".publish",)),
            Point("publish", "swap-notify", name_all=("notify", "swap")),
            Point("advance", "cursor-advance", suffixes=(".advance",)),
            Point("advance", "checkpoint", suffixes=(".checkpoint",)),
        ),
    ),
    Protocol(
        name="swap-epoch",
        contract=(
            "a frame or response field read from a peer process binds the "
            "generation/epoch guard in the same acquisition that read it"
        ),
        guard_tokens=("generation", "epoch", "version"),
        points=(
            Point("publish", "ring-push", suffixes=(".push",),
                  recv_any=("ring", "rings", "ctl", "requests",
                            "completions")),
            Point("consume", "ring-pop", suffixes=(".pop",),
                  recv_any=("ring", "rings", "ctl", "requests",
                            "completions")),
        ),
    ),
    Protocol(
        name="handshake",
        contract=(
            "handshake artifacts (portfile/marker/manifest) are fsynced "
            "before the rename that publishes them; layout markers also "
            "fsync the directory entry; READY files are CRC-verified "
            "before they are trusted"
        ),
        layout_tokens=("parts",),
        verify_tokens=("ready",),
        points=(
            Point("commit", "fsync", names=("os.fsync",),
                  suffixes=(".fsync",)),
            Point("commit", "dir-fsync", name_all=("fsync", "dir")),
            Point("publish", "handshake-rename",
                  names=("os.replace", "os.rename"),
                  target_any=("port", "parts", "marker", "manifest",
                              "ready")),
        ),
    ),
    Protocol(
        name="shard-routing",
        contract=(
            "every partition/shard selection routes through "
            "utils/stablehash.stable_bucket: ingest and serving must "
            "agree on the bucket function forever"
        ),
        blessed="predictionio_tpu/utils/stablehash.py",
        points=(),
    ),
)

def _build_trigger_tokens() -> frozenset:
    """One witness token per declared point: a call whose name tokens
    miss ALL of them cannot match any point, so ``_classify`` skips the
    protocol loop for the ~95% of calls that are not protocol points.
    The longest token of each recognizer is the rarest in practice."""
    trig = set()
    for proto in PROTOCOLS:
        for pt in proto.points:
            for n in pt.names:
                toks = _TOKEN_RE.findall(n.split(".")[-1].lower())
                if toks:
                    trig.add(max(toks, key=len))
            for s in pt.suffixes:
                toks = _TOKEN_RE.findall(s.split(".")[-1].lower())
                if toks:
                    trig.add(max(toks, key=len))
            if pt.name_all:
                trig.add(max((t.lower() for t in pt.name_all), key=len))
    return frozenset(trig)


#: the one blessed routing implementation (exempt from P004)
ROUTING_BLESSED_PATH = "utils/stablehash.py"
#: right-operand tokens that mark a ``%`` as a routing decision
ROUTING_TOKENS = frozenset(
    ("shard", "shards", "partition", "partitions", "bucket", "buckets")
)


@dataclass(frozen=True)
class Site:
    """One classified protocol point occurrence in the package."""

    protocol: str
    role: str
    kind: str
    path: str
    qual: str
    line: int
    detail: str
    target: str = ""   # resolved rename-target text (handshake sites)


_TOKEN_RE = re.compile(r"[a-z0-9]+")


def _tokens(text: str) -> frozenset:
    return frozenset(_TOKEN_RE.findall(text.lower()))


_TRIGGER_TOKENS = _build_trigger_tokens()


def _dotted(node: ast.AST) -> str:
    """``self.rings[i].requests.push`` -> ``self.rings.requests.push``
    (subscripts are transparent; unresolvable bases become ``?``)."""
    parts = []
    while True:
        if isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        else:
            break
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif parts:
        parts.append("?")
    else:
        return ""
    return ".".join(reversed(parts))


def _expr_text(expr: ast.AST, env: dict, consts: dict, depth: int = 0) -> str:
    """Best-effort text of an argument expression, following same-
    function Name assignments and module-level string constants -- the
    resolution that lets ``os.replace(tmp, path)`` see through
    ``path = os.path.join(self.directory, _PARTS_FILE)``."""
    if depth > 4:
        return ""
    if isinstance(expr, ast.Constant):
        return str(expr.value) if isinstance(expr.value, str) else ""
    if isinstance(expr, ast.Name):
        if expr.id in env:
            return env[expr.id]
        if expr.id in consts:
            return consts[expr.id]
        return expr.id
    if isinstance(expr, ast.Attribute):
        return _dotted(expr)
    if isinstance(expr, ast.JoinedStr):
        return "".join(
            _expr_text(v.value if isinstance(v, ast.FormattedValue) else v,
                       env, consts, depth + 1)
            for v in expr.values
        )
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        return (_expr_text(expr.left, env, consts, depth + 1)
                + _expr_text(expr.right, env, consts, depth + 1))
    if isinstance(expr, ast.Call):
        # os.path.join(a, b, ...) and str.format-ish calls: join the args
        return " ".join(
            _expr_text(a, env, consts, depth + 1) for a in expr.args
        )
    return ""


# -- process roles ------------------------------------------------------------

@dataclass(frozen=True)
class ProcRole:
    """One OS-process identity, seeded at a module's ``__main__`` guard.
    Distinct entry modules are distinct roles: the shard executable and
    the frontend executable never share an address space, so a value
    crossing between their call trees crossed a process boundary."""

    module: str   # dotted module of the entry point
    seed: str     # "path:line" of the guard

    @property
    def label(self) -> str:
        return f"proc:{self.module}"


class _ProcEntry:
    """A pseudo-FunctionInfo for resolving calls made at a module's
    ``__main__`` guard (module scope: no self, no params)."""

    def __init__(self, mod):
        self.path = mod.path
        self.qual = "<module>"
        self.cls = None
        self.module = mod
        self.node = mod.ctx.tree
        self.key = (mod.path, "<module>")

    def params(self) -> list:
        return []


class ProcessRoles:
    """Which OS processes can execute each function: ``__main__``-guard
    seeds propagated over call edges (the cross-process analogue of
    ``RoleInference``).  Functions reachable from two different entry
    modules run in two different processes -- that is the stitching
    P003 needs to call a ring/portfile/notify edge *cross*-process."""

    def __init__(self, graph):
        self.graph = graph
        self.role_map: dict[tuple, set] = {}
        self._parent: dict[tuple, tuple] = {}
        work = []
        for mod in graph.modules.values():
            if not mod.main_body:
                continue
            role = ProcRole(
                mod.dotted, f"{mod.path}:{mod.main_body[0].lineno}"
            )
            entry = _ProcEntry(mod)
            for stmt in mod.main_body:
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    for target in graph.resolve_call(entry, node):
                        bucket = self.role_map.setdefault(target.key, set())
                        if role not in bucket:
                            bucket.add(role)
                            self._parent[(target.key, role)] = (
                                None, node.lineno
                            )
                            work.append((target.key, role))
        while work:
            fkey, role = work.pop()
            for cs in graph.callees(fkey):
                for target in cs.targets:
                    bucket = self.role_map.setdefault(target.key, set())
                    if role not in bucket:
                        bucket.add(role)
                        self._parent[(target.key, role)] = (fkey, cs.line)
                        work.append((target.key, role))

    def roles_of(self, fkey: tuple) -> set:
        return self.role_map.get(fkey, set())

    def witness_path(self, fkey: tuple, role: ProcRole) -> list[str]:
        """Seed-to-function hop list ("path:qual:line") for SARIF
        codeFlows, mirroring ``RoleInference.witness_path``."""
        hops = []
        cur = fkey
        while cur is not None:
            parent, line = self._parent.get((cur, role), (None, 0))
            hops.append(f"{cur[0]}:{cur[1]}:{line}")
            cur = parent
        return list(reversed(hops))


# -- the facts layer ----------------------------------------------------------

_ROLE_ORDER = {"commit": 0, "write": 1, "consume": 2, "advance": 3,
               "publish": 4}


class ProtocolFlow:
    """Protocol point classification + transitive tags + process roles,
    built ONCE per ``PackageIndex`` (every P rule and
    ``--protocol-report`` read the same build)."""

    def __init__(self, index):
        self.index = index
        self.graph = index.graph
        self._consts: dict[str, dict] = {}
        for ctx in index.contexts:
            self._consts[ctx.path] = {
                t.id: s.value.value
                for s in ctx.tree.body if isinstance(s, ast.Assign)
                for t in s.targets
                if isinstance(t, ast.Name)
                and isinstance(s.value, ast.Constant)
                and isinstance(s.value.value, str)
            }
        #: (path, id(call node)) -> tuple[Site, ...]
        self.call_sites: dict[tuple, tuple] = {}
        #: fkey -> list[Site]
        self.fn_sites: dict[tuple, list] = {}
        self.sites: list[Site] = []
        #: fkey -> frozenset[(protocol, role)] -- transitive over callees
        self.trans: dict[tuple, set] = {}
        #: (fkey, (protocol, role)) -> representative Site for witnesses
        self.trans_repr: dict[tuple, Site] = {}
        #: fkeys containing a bare ``open(...)`` call -- the only
        #: candidates for the READY-read scan
        self.open_fns: set[tuple] = set()
        self._scan_sites()
        self._build_trans()
        self.proc = ProcessRoles(self.graph)
        #: modules whose process role pushes swap-epoch frames (the
        #: producer side of every ring edge)
        self.pusher_modules: set[str] = set()
        for fkey, sites in self.fn_sites.items():
            if not any(s.protocol == "swap-epoch" and s.role == "publish"
                       for s in sites):
                continue
            for role in self.proc.roles_of(fkey):
                self.pusher_modules.add(role.module)
            if fkey[1] == "<module>":
                mod = self.graph.by_path.get(fkey[0])
                if mod is not None:
                    self.pusher_modules.add(mod.dotted)

    # -- classification -----------------------------------------------------
    def _env(self, fi) -> dict:
        """Same-function Name -> resolved text (single pass; assignments
        normally precede the uses the rename matcher cares about)."""
        env: dict[str, str] = {}
        consts = self._consts.get(fi.path, {})
        for node in self.graph.body_nodes(fi.node):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt = node.targets[0]
            if isinstance(tgt, ast.Name):
                text = _expr_text(node.value, env, consts)
                if text:
                    env[tgt.id] = text
        return env

    def _scan_sites(self) -> None:
        for fi in self.graph.functions.values():
            env = None
            for node in self.graph.body_nodes(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                if (isinstance(node.func, ast.Name)
                        and node.func.id == "open" and node.args):
                    self.open_fns.add(fi.key)
                if env is None:
                    env = self._env(fi)
                sites = self._classify(fi.path, fi.qual, node, env)
                if sites:
                    self.call_sites[(fi.path, id(node))] = sites
                    self.fn_sites.setdefault(fi.key, []).extend(sites)
                    self.sites.extend(sites)
        for mod in self.graph.modules.values():
            for stmt in mod.main_body:
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    sites = self._classify(
                        mod.path, "<module>", node, {}
                    )
                    if sites:
                        self.call_sites[(mod.path, id(node))] = sites
                        self.fn_sites.setdefault(
                            (mod.path, "<module>"), []
                        ).extend(sites)
                        self.sites.extend(sites)
        self.sites.sort(key=lambda s: (s.path, s.line, s.protocol, s.role))

    def _classify(self, path, qual, call, env) -> tuple:
        name = _dotted(call.func)
        if not name:
            return ()
        toks = _tokens(name)
        if not (toks & _TRIGGER_TOKENS):
            return ()
        consts = self._consts.get(path, {})
        out = []
        seen = set()
        for proto in PROTOCOLS:
            for pt in proto.points:
                if (proto.name, pt.role) in seen:
                    continue
                target = self._match(pt, name, toks, call, env, consts)
                if target is None:
                    continue
                seen.add((proto.name, pt.role))
                out.append(Site(
                    protocol=proto.name, role=pt.role, kind=pt.kind,
                    path=path, qual=qual, line=call.lineno,
                    detail=f"{name}(...)", target=target,
                ))
        return tuple(out)

    def _match(self, pt, name, toks, call, env, consts):
        """None = no match; otherwise the resolved target text ("" when
        the point carries no target filter)."""
        hit = False
        if pt.names and name in pt.names:
            hit = True
        if not hit and pt.suffixes:
            for suf in pt.suffixes:
                if name.endswith(suf) and len(name) > len(suf):
                    recv = name[: -len(suf)]
                    if not pt.recv_any or (_tokens(recv)
                                           & set(pt.recv_any)):
                        hit = True
                        break
        if not hit and pt.name_all and set(pt.name_all) <= toks:
            hit = True
        if not hit:
            return None
        if pt.arg_2xx:
            if not call.args:
                return None
            arg = call.args[0]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, int)
                    and 200 <= arg.value < 300):
                return None
        if pt.target_any:
            text = " ".join(
                _expr_text(a, env, consts) for a in call.args
            ).lower()
            if not any(t in text for t in pt.target_any):
                return None
            return text
        return ""

    # -- transitive tags ----------------------------------------------------
    def _build_trans(self) -> None:
        tags: dict[tuple, set] = {}
        for fkey, sites in self.fn_sites.items():
            bucket = tags.setdefault(fkey, set())
            for s in sites:
                tag = (s.protocol, s.role)
                bucket.add(tag)
                self.trans_repr.setdefault((fkey, tag), s)
        changed = True
        while changed:
            changed = False
            for fkey in self.graph.callsites:
                bucket = tags.setdefault(fkey, set())
                for cs in self.graph.callsites[fkey]:
                    for target in cs.targets:
                        for tag in tags.get(target.key, ()):
                            if tag not in bucket:
                                bucket.add(tag)
                                rep = self.trans_repr.get(
                                    (target.key, tag)
                                )
                                if rep is not None:
                                    self.trans_repr.setdefault(
                                        (fkey, tag), rep
                                    )
                                changed = True
        self.trans = tags

    # -- the report ---------------------------------------------------------
    def report_sites(self) -> list[dict]:
        """Uniform site dicts for the shared inventory-report writer
        (``--protocol-report``): one row per classified point."""
        return [
            {
                "kind": f"{s.role}:{s.kind}",
                "protocol": s.protocol,
                "path": s.path,
                "qual": s.qual,
                "line": s.line,
                "detail": s.detail,
            }
            for s in self.sites
        ]


# -- the path-sensitive ordering scan -----------------------------------------

def _copy_state(state: dict) -> dict:
    return {k: set(v) for k, v in state.items()}


def _merge_state(dst: dict, src: dict) -> None:
    """May-union, except ``must*`` keys which intersect: a fact under a
    ``must`` key holds only if it holds on EVERY path reaching the
    join."""
    for k in set(dst) | set(src):
        a, b = dst.get(k, set()), src.get(k, set())
        dst[k] = (a & b) if k.startswith("must") else (a | b)


def scan_ordering(graph, fi, state: dict, visit, finish=None) -> None:
    """Walk ``fi``'s body path-sensitively in statement order.

    ``visit(state, call)`` fires for every call in execution order and
    mutates ``state`` (a dict of sets; ``must*`` keys intersect at
    joins, everything else unions).  If-branches fork copies; a branch
    that terminates (return/raise/break/continue) never merges back --
    that is what keeps the noop early-return in ``RetrainLoop.run_once``
    from polluting the fall-through path.  ``finish(state)`` fires once
    per function exit (every return/raise and the natural fall-off)."""

    def visit_calls(node, st):
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                visit(st, sub)

    def walk(stmts, st) -> bool:
        for s in stmts:
            t = type(s)
            if t in (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef):
                continue
            if t in (ast.Return, ast.Raise):
                visit_calls(s, st)
                if finish is not None:
                    finish(st)
                return False
            if t in (ast.Break, ast.Continue):
                return False
            if t is ast.If:
                visit_calls(s.test, st)
                then_st, else_st = _copy_state(st), _copy_state(st)
                then_live = walk(s.body, then_st)
                else_live = walk(s.orelse, else_st)
                if then_live and else_live:
                    st.clear()
                    st.update(then_st)
                    _merge_state(st, else_st)
                elif then_live:
                    st.clear()
                    st.update(then_st)
                elif else_live:
                    st.clear()
                    st.update(else_st)
                else:
                    return False
                continue
            if t in (ast.For, ast.AsyncFor):
                visit_calls(s.iter, st)
                body_st = _copy_state(st)
                if walk(s.body, body_st):
                    _merge_state(st, body_st)
                if s.orelse and not walk(s.orelse, st):
                    return False
                continue
            if t is ast.While:
                visit_calls(s.test, st)
                body_st = _copy_state(st)
                if walk(s.body, body_st):
                    _merge_state(st, body_st)
                if s.orelse and not walk(s.orelse, st):
                    return False
                continue
            if t in (ast.With, ast.AsyncWith):
                for item in s.items:
                    visit_calls(item.context_expr, st)
                if not walk(s.body, st):
                    return False
                continue
            if t is ast.Try:
                entry = _copy_state(st)
                live = walk(s.body, st)
                if live and s.orelse:
                    live = walk(s.orelse, st)
                branches = [st] if live else []
                for h in s.handlers:
                    # the handler can enter from anywhere between the
                    # try entry and the body end: union both
                    h_st = _copy_state(st)
                    _merge_state(h_st, entry)
                    if walk(h.body, h_st):
                        branches.append(h_st)
                if not branches:
                    if s.finalbody:
                        walk(s.finalbody, _copy_state(entry))
                    return False
                merged = branches[0]
                for b in branches[1:]:
                    _merge_state(merged, b)
                if merged is not st:
                    st.clear()
                    st.update(merged)
                if s.finalbody and not walk(s.finalbody, st):
                    return False
                continue
            visit_calls(s, st)
        return True

    body = fi.node.body
    if not isinstance(body, list):
        # a Lambda: single expression, single path
        visit_calls(body, state)
        if finish is not None:
            finish(state)
        return
    if walk(body, state) and finish is not None:
        finish(state)


# -- the rule-facing checks ---------------------------------------------------

def _call_events(flow, fi, call, protocol, paired: tuple) -> list:
    """Events a call contributes for one protocol: its direct sites plus
    derived tags from resolved callees.  A callee carrying BOTH roles of
    a ``paired`` contract (e.g. write+commit, or advance+publish) is
    internally ordered -- it is checked in its own scan and contributes
    only the net effect (the first role of the pair for commit-like
    pairs, nothing for advance/publish pairs)."""
    events = []
    for s in flow.call_sites.get((fi.path, id(call)), ()):
        if s.protocol == protocol:
            events.append((s.role, s))
    for target in flow.graph.call_targets.get((fi.path, id(call)), ()):
        if target.key == fi.key:
            continue
        tags = flow.trans.get(target.key) or ()
        roles = {r for (p, r) in tags if p == protocol}
        if not roles:
            continue
        net = _net_roles(roles, paired)
        for role in net:
            rep = flow.trans_repr.get((target.key, (protocol, role)))
            if rep is not None:
                events.append((role, rep))
    events.sort(key=lambda e: _ROLE_ORDER.get(e[0], 9))
    return events


def _net_roles(roles: set, paired: tuple) -> set:
    lo, hi = paired
    if lo in roles and hi in roles:
        # internally ordered: a commit-pair nets to the commit; an
        # ordering pair (advance/publish) nets to nothing
        return {lo} if lo == "commit" else set()
    return set(roles)


def ack_before_commit(flow, fi) -> list[tuple]:
    """P001 scan: (write line, write detail, ack line, ack kind) per
    path where an ack is reachable with an uncommitted WAL write."""
    # every write/ack visible to the scan (direct sites and callee nets
    # alike) is in the transitive tag set, so a function missing either
    # role cannot fire and skips the path-sensitive walk entirely
    tags = flow.trans.get(fi.key) or ()
    if ("wal-ack", "write") not in tags or ("wal-ack", "publish") not in tags:
        return []
    findings: list[tuple] = []
    seen: set[tuple] = set()

    def visit(state, call):
        for role, site in _call_events(
            flow, fi, call, "wal-ack", ("commit", "write")
        ):
            if role == "commit":
                state["pending"].clear()
            elif role == "write":
                state["pending"].add((site.line, site.detail))
            elif role == "publish":
                for wline, wdetail in sorted(state["pending"]):
                    key = (wline, call.lineno)
                    if key not in seen:
                        seen.add(key)
                        findings.append(
                            (wline, wdetail, call.lineno, site.kind)
                        )

    scan_ordering(flow.graph, fi, {"pending": set()}, visit)
    return findings


def advance_before_publish(flow, fi) -> list[tuple]:
    """P002 scan: (advance line, advance detail, publish line, publish
    kind) per path where a cursor advance precedes a publication."""
    tags = flow.trans.get(fi.key) or ()
    if (("replay-cursor", "advance") not in tags
            or ("replay-cursor", "publish") not in tags):
        return []
    findings: list[tuple] = []
    seen: set[tuple] = set()

    def visit(state, call):
        for role, site in _call_events(
            flow, fi, call, "replay-cursor", ("advance", "publish")
        ):
            if role == "advance":
                state["advanced"].add((site.line, site.detail))
            elif role == "publish":
                for aline, adetail in sorted(state["advanced"]):
                    key = (aline, call.lineno)
                    if key not in seen:
                        seen.add(key)
                        findings.append(
                            (aline, adetail, call.lineno, site.kind)
                        )

    scan_ordering(flow.graph, fi, {"advanced": set()}, visit)
    return findings


def handshake_findings(flow, fi) -> list[tuple]:
    """P005 scan: ("unsynced-rename" | "layout-no-dirfsync", line,
    detail) -- renames of handshake artifacts without a preceding fsync
    on the path, and layout-marker renames whose directory entry is
    never fsynced before the function exits."""
    # both finding shapes anchor on a rename performed HERE: a function
    # with no direct handshake publish site cannot fire
    if not any(s.protocol == "handshake" and s.role == "publish"
               for s in flow.fn_sites.get(fi.key, ())):
        return []
    findings: list[tuple] = []
    seen: set[tuple] = set()

    def emit(kind, line, detail):
        if (kind, line) not in seen:
            seen.add((kind, line))
            findings.append((kind, line, detail))

    def visit(state, call):
        for role, site in _call_events(
            flow, fi, call, "handshake", ("commit", "publish")
        ):
            if role != "commit":
                continue
            state["must_sync"].add("synced")
            if site.kind == "dir-fsync":
                state["pending_dir"].clear()
        for s in flow.call_sites.get((fi.path, id(call)), ()):
            if s.protocol != "handshake" or s.role != "publish":
                continue
            if "synced" not in state["must_sync"]:
                emit("unsynced-rename", s.line, s.detail)
            if any(t in s.target for t in ("parts",)):
                state["pending_dir"].add((s.line, s.detail))
            # the fsync is consumed: a second rename needs its own
            state["must_sync"].clear()

    def finish(state):
        for line, detail in sorted(state["pending_dir"]):
            emit("layout-no-dirfsync", line, detail)

    scan_ordering(
        flow.graph, fi,
        {"must_sync": set(), "pending_dir": set()},
        visit, finish,
    )
    return findings


_VERIFY_OK_TOKENS = frozenset(("crc", "crc32", "checksum", "digest", "sha",
                               "sha256", "md5", "verify"))


def unverified_ready_reads(flow, fi) -> list[tuple]:
    """P005 companion: (line, detail) for ``open()`` of a READY-style
    handshake file in a function that never mentions a CRC/checksum."""
    if fi.key not in flow.open_fns:
        return []
    graph = flow.graph
    consts = flow._consts.get(fi.path, {})
    env = flow._env(fi)
    reads = []
    fn_tokens: set = set()
    for node in graph.body_nodes(fi.node):
        if isinstance(node, (ast.Name, ast.Attribute)):
            fn_tokens |= _tokens(_dotted(node))
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            fn_tokens |= _tokens(node.value)
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id == "open" and node.args):
            text = _expr_text(node.args[0], env, consts).lower()
            if "ready" in text:
                reads.append((node.lineno, f"open({text[:40]!r})"))
    if not reads or (fn_tokens & _VERIFY_OK_TOKENS):
        return []
    return reads


def unguarded_peer_reads(flow, fi) -> list[tuple]:
    """P003 scan: (line, field, role labels, pusher modules) for guard-
    field reads off a ring-popped frame with no guard comparison in the
    function, in a process role distinct from every pusher's."""
    graph = flow.graph
    guard = set()
    for proto in PROTOCOLS:
        guard |= set(proto.guard_tokens)
    tainted: dict[str, int] = {}
    for node in graph.body_nodes(fi.node):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        sites = flow.call_sites.get((fi.path, id(node.value)), ())
        if not any(s.protocol == "swap-epoch" and s.role == "consume"
                   for s in sites):
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                tainted[tgt.id] = node.lineno
            elif isinstance(tgt, ast.Tuple):
                for elt in tgt.elts:
                    if isinstance(elt, ast.Name):
                        tainted[elt.id] = node.lineno
    if not tainted:
        return []
    reads: list[tuple] = []       # (line, field, bound-name-or-None)
    compare_tokens: set = set()
    compare_names: set = set()
    assigns: dict[int, str] = {}  # id(value node) -> bound local name
    for node in graph.body_nodes(fi.node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            assigns[id(node.value)] = node.targets[0].id
        if isinstance(node, ast.Compare):
            for side in [node.left, *node.comparators]:
                compare_tokens |= _tokens(_dotted(side))
                if isinstance(side, ast.Constant):
                    compare_tokens |= _tokens(str(side.value))
                if isinstance(side, ast.Name):
                    compare_names.add(side.id)
    for node in graph.body_nodes(fi.node):
        field = None
        if (isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Name)
                and node.value.id in tainted
                and isinstance(node.slice, ast.Constant)
                and isinstance(node.slice.value, str)):
            if _tokens(node.slice.value) & guard:
                field = node.slice.value
        elif (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id in tainted):
            if _tokens(node.attr) & guard:
                field = node.attr
        if field is not None:
            reads.append((node.lineno, field, assigns.get(id(node))))
    if not reads:
        return []
    if compare_tokens & guard:
        return []
    unbound = [r for r in reads if r[2] is None
               or r[2] not in compare_names]
    if not unbound:
        return []
    roles = flow.proc.roles_of(fi.key)
    if not roles:
        return []
    my_modules = {r.module for r in roles}
    foreign = flow.pusher_modules - my_modules
    if not foreign:
        return []
    labels = sorted(r.label for r in roles)
    return [(line, field, labels, sorted(foreign))
            for line, field, _ in unbound]


def routing_mod_sites(tree: ast.AST, path: str) -> list[tuple]:
    """P004 scan (file-local): (line, text) for every ``%`` whose right
    operand names a shard/partition/bucket count, outside the blessed
    ``utils/stablehash.py``."""
    if path.replace("\\", "/").endswith(ROUTING_BLESSED_PATH):
        return []
    out = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.BinOp)
                and isinstance(node.op, ast.Mod)):
            continue
        right = _dotted(node.right)
        if not right:
            continue
        last = right.rsplit(".", 1)[-1]
        if _tokens(last) & ROUTING_TOKENS:
            left = _dotted(node.left) or "<expr>"
            out.append((node.lineno, f"{left} % {right}"))
    return out
