"""Runtime resource-lifecycle watcher: the R-series' reality check.

The static R001/R002 rules reason about exception edges and call-graph
credit; this module records what tests ACTUALLY leak. ``install()``
wraps two protocols:

- **spans** (R002's runtime half): ``obs.trace.Span`` construction and
  ``finish`` are wrapped so every live-but-unfinished span is known.
  The shared sentinels (``NULL_SPAN``, ``SAMPLED_OUT_ROOT``) are
  separate classes and never tracked; ``finish`` is idempotent, so a
  double finish unregisters once.
- **permits** (R001/R004's runtime half): ``threading.Semaphore`` /
  ``BoundedSemaphore`` constructed from predictionio_tpu modules
  (decided by one caller-frame peek at construction, exactly
  lockwatch's policy -- stdlib-internal semaphores stay untouched)
  return a thin wrapper counting successful acquires vs releases per
  instance, keyed by construction site.

The pytest hooks in ``tests/conftest.py`` snapshot both ledgers around
every test and fail the test that ended with a NEW unfinished span or a
net permit debt -- after a short settle loop, because service teardown
legitimately finishes a straggler span a few milliseconds after the
test body returns. Inversions of this kind are recorded, never raised
mid-flight (failing inside arbitrary span/semaphore paths would turn a
diagnosis into a heisenbug).

Enabled under pytest by default (``PIO_LEAKWATCH=0`` opts out); never
enabled in production servers -- the wrappers cost a dict hit per
span/permit operation.
"""

from __future__ import annotations

import sys
import threading
import time
import weakref


class LeakWatch:
    """Live-obligation ledgers. One global instance backs ``install()``;
    tests can build private instances and wrap objects explicitly."""

    def __init__(self):
        self._mutex = threading.Lock()
        #: id(span) -> span (strong ref: a leaked span must not be
        #: garbage-collected out of the evidence)
        self._live_spans: dict = {}
        #: ledger key -> [weakref(sem), site, acquired, released]; keys
        #: are a monotonic serial, never id() -- CPython reuses ids
        #: after GC, and a reused key would let a new semaphore's debt
        #: net against a dead one's snapshot
        self._sems: dict = {}
        self._next_serial = 0

    # -- spans --------------------------------------------------------------
    def note_span_started(self, span) -> None:
        with self._mutex:
            self._live_spans[id(span)] = span

    def note_span_finished(self, span) -> None:
        with self._mutex:
            self._live_spans.pop(id(span), None)

    def pending_spans(self) -> list:
        """Live unfinished spans, oldest first."""
        with self._mutex:
            return list(self._live_spans.values())

    def span_snapshot(self) -> set:
        with self._mutex:
            return set(self._live_spans)

    def new_pending_spans(self, before: set) -> list:
        with self._mutex:
            return [
                s for k, s in self._live_spans.items() if k not in before
            ]

    # -- permits ------------------------------------------------------------
    def wrap_semaphore(self, sem, site: str) -> "_WatchedSemaphore":
        wrapped = _WatchedSemaphore(sem, site, self)
        with self._mutex:
            self._next_serial += 1
            wrapped._serial = self._next_serial
            self._sems[wrapped._serial] = [weakref.ref(wrapped), site, 0, 0]
        return wrapped

    def _note_acquired(self, wrapped, n: int = 1) -> None:
        with self._mutex:
            rec = self._sems.get(wrapped._serial)
            if rec is not None:
                rec[2] += n

    def _note_released(self, wrapped, n: int = 1) -> None:
        with self._mutex:
            rec = self._sems.get(wrapped._serial)
            if rec is not None:
                rec[3] += n

    def permit_debts(self) -> dict:
        """site -> net held permits (acquired - released) per LIVE
        watched semaphore; dead instances fall out of the ledger."""
        out: dict = {}
        with self._mutex:
            dead = []
            for key, (ref, site, acq, rel) in self._sems.items():
                if ref() is None:
                    dead.append(key)
                    continue
                out[f"{site}#{key}"] = acq - rel
            for key in dead:
                self._sems.pop(key, None)
        return out

    @staticmethod
    def new_debts(before: dict, after: dict) -> dict:
        """Semaphores whose net held count GREW over a test (new
        instances count from zero)."""
        return {
            key: held - before.get(key, 0)
            for key, held in after.items()
            if held - before.get(key, 0) > 0
        }


class _WatchedSemaphore:
    """Duck-types a semaphore; successful acquires and every release
    are charged to the ledger."""

    def __init__(self, real, site: str, watch: LeakWatch):
        self._real = real
        self.site = site
        self._watch = watch
        self._serial = 0  # assigned by wrap_semaphore

    def acquire(self, *args, **kwargs):
        got = self._real.acquire(*args, **kwargs)
        if got:
            self._watch._note_acquired(self)
        return got

    def release(self, n: int = 1):
        self._real.release(n)
        self._watch._note_released(self, n)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __getattr__(self, name):
        return getattr(self._real, name)


_GLOBAL = LeakWatch()
_REAL_SEMAPHORE = None
_REAL_BOUNDED = None
_REAL_SPAN_INIT = None
_REAL_SPAN_FINISH = None


def global_watch() -> LeakWatch:
    return _GLOBAL


def enabled_default() -> bool:
    """The conftest gate: on unless ``PIO_LEAKWATCH=0`` opts out."""
    import os

    return os.environ.get("PIO_LEAKWATCH", "1") != "0"


def _watched_site() -> str | None:
    """Construction site of the semaphore two frames up; only
    predictionio_tpu's own semaphores are wrapped."""
    try:
        frame = sys._getframe(2)
    except ValueError:
        return None
    mod = frame.f_globals.get("__name__", "")
    if mod.startswith("predictionio_tpu") and not mod.startswith(
        "predictionio_tpu.analysis.leakwatch"
    ):
        return f"{mod}:{frame.f_lineno}"
    return None


def install() -> None:
    """Wrap ``Span`` lifecycle and package-constructed semaphores.
    Idempotent; ``uninstall()`` restores."""
    global _REAL_SEMAPHORE, _REAL_BOUNDED, _REAL_SPAN_INIT, _REAL_SPAN_FINISH
    if _REAL_SEMAPHORE is not None:
        return
    from predictionio_tpu.obs import trace

    _REAL_SPAN_INIT = trace.Span.__init__
    _REAL_SPAN_FINISH = trace.Span.finish

    real_init = _REAL_SPAN_INIT
    real_finish = _REAL_SPAN_FINISH

    def span_init(self, *args, **kwargs):
        real_init(self, *args, **kwargs)
        _GLOBAL.note_span_started(self)

    def span_finish(self):
        real_finish(self)
        _GLOBAL.note_span_finished(self)

    trace.Span.__init__ = span_init
    trace.Span.finish = span_finish

    _REAL_SEMAPHORE = threading.Semaphore
    _REAL_BOUNDED = threading.BoundedSemaphore
    real_sem, real_bounded = _REAL_SEMAPHORE, _REAL_BOUNDED

    def make_semaphore(value: int = 1):
        site = _watched_site()
        real = real_sem(value)
        return _GLOBAL.wrap_semaphore(real, site) if site else real

    def make_bounded(value: int = 1):
        site = _watched_site()
        real = real_bounded(value)
        return _GLOBAL.wrap_semaphore(real, site) if site else real

    threading.Semaphore = make_semaphore
    threading.BoundedSemaphore = make_bounded


def uninstall() -> None:
    global _REAL_SEMAPHORE, _REAL_BOUNDED, _REAL_SPAN_INIT, _REAL_SPAN_FINISH
    if _REAL_SEMAPHORE is None:
        return
    from predictionio_tpu.obs import trace

    trace.Span.__init__ = _REAL_SPAN_INIT
    trace.Span.finish = _REAL_SPAN_FINISH
    threading.Semaphore = _REAL_SEMAPHORE
    threading.BoundedSemaphore = _REAL_BOUNDED
    _REAL_SEMAPHORE = _REAL_BOUNDED = None
    _REAL_SPAN_INIT = _REAL_SPAN_FINISH = None


def installed() -> bool:
    return _REAL_SEMAPHORE is not None


def settle(check, timeout_s: float = 1.0, interval_s: float = 0.02):
    """Re-evaluate ``check()`` (a callable returning the offending
    leaks) until it comes back empty or the timeout expires: service
    teardown may finish a straggler span / return a parked permit a few
    milliseconds after the test body ends. Returns the last result."""
    deadline = time.monotonic() + timeout_s
    result = check()
    while result and time.monotonic() < deadline:
        time.sleep(interval_s)
        result = check()
    return result
