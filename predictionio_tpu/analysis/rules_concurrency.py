"""C-series rules: the concurrency invariants, phase 2.

Phase 1 (PRs 5-12) walked one module at a time -- lexical ``with``
nesting plus one level of ``self.`` call propagation -- which matched the
WAL/snapshot incidents but not the shapes the serving/online tiers took,
where the hazard spans files and threads. Phase 2 rebuilds the family on
the whole-package core (``callgraph`` / ``threadroles`` / ``locksets``):

- C001/C002 join locksets over call paths (a blocking call N frames
  below the lock acquisition is the same stall as one frame below);
- C005 follows done-callback and event-loop roles through the call
  graph, including the higher-order hand-offs of the async serving path;
- C006 is the Eraser-style static lockset race detector that replaces
  C003: a field written under one thread role and read/written under
  another with disjoint locksets, package-wide, no module allowlist.

Every rule class docstring IS its incident-catalog entry: ``pio check
--explain RULE`` prints it, and the rule table in
``docs/static_analysis.md`` is generated from it (the paragraph starting
``Incident`` becomes the incident column).
"""

from __future__ import annotations

import ast
from typing import Iterator

from predictionio_tpu.analysis.astutil import call_name, dotted
from predictionio_tpu.analysis.engine import Finding, ModuleContext
from predictionio_tpu.analysis.locksets import blocking_reason
from predictionio_tpu.analysis.packageindex import PackageIndex, PackageRule
from predictionio_tpu.analysis.threadroles import CONCURRENT_KINDS

#: cap on the depth of role-carrying DFS walks (C005/C006); real chains
#: in this repo are <= 6 hops (ring consumer -> ... -> retry queue)
_MAX_DEPTH = 12


def _chain_text(hops: list[str]) -> str:
    return " -> ".join(hops)


class RuleC001(PackageRule):
    """Inconsistent lock-acquisition order: lock A held while acquiring
    B on one path, B held while acquiring A on another -- a cycle in the
    package lock graph, now joined over full call-graph reachability
    (the acquisition of B may sit any number of frames below the holder
    of A). A cycle is a deadlock waiting for the right interleaving.
    Validated at runtime by ``analysis/lockwatch.py``, which records
    actual acquisition-order edges (and the held lockset at every
    acquisition) under tier-1.

    Incident: the PR-2/PR-3 snapshot-GC and checkpoint-ordering races
    (snapshot GC vs builder, checkpoint vs flush)."""

    rule_id = "C001"
    severity = "error"

    def check_package(self, index: PackageIndex) -> Iterator[Finding]:
        locks = index.locks
        contexts = locks.entry_contexts()
        #: (held lock, acquired lock) -> (path, line) of first sighting
        edges: dict[tuple, tuple] = {}
        for fkey, facts in sorted(locks.facts.items()):
            inherited = [frozenset()] + sorted(
                contexts.get(fkey, ()), key=sorted
            )
            for lock, held, line in facts.acquisitions:
                for base in inherited:
                    for h in base | held:
                        if h != lock:
                            edges.setdefault(
                                (h, lock), (facts.info.path, line)
                            )
        reported: set[frozenset] = set()
        for (a, b), (path, line) in sorted(
            edges.items(), key=lambda kv: (kv[1], kv[0])
        ):
            if (b, a) not in edges or frozenset((a, b)) in reported:
                continue
            reported.add(frozenset((a, b)))
            rpath, rline = edges[(b, a)]
            sa, sb = index.locks.short_lock(a), index.locks.short_lock(b)
            yield Finding(
                self.rule_id, self.severity, path, line,
                "<module>",
                f"inconsistent lock order: {sa!r} -> {sb!r} "
                f"({path}:{line}) but also {sb!r} -> {sa!r} "
                f"({rpath}:{rline})",
                "pick one global acquisition order and restructure the "
                "second site to follow it",
            )


class RuleC002(PackageRule):
    """Blocking I/O (fsync, SQL execute/commit, socket calls, span
    export, ``queue.put/get`` without timeout, ``urlopen``,
    ``time.sleep``) while holding a lock -- including locks held by a
    CALLER any number of frames up the call graph; such findings report
    the witness call path from the acquisition to the blocking call.

    Incident: the WAL held its writer lock across the group-commit
    fsync, parking every concurrent ``append()`` behind disk latency
    (fixed in PR 5: dup the fd under the lock, fsync outside); the same
    shape recurred in the snapshot store and the span exporter."""

    rule_id = "C002"
    severity = "warning"

    def check_package(self, index: PackageIndex) -> Iterator[Finding]:
        locks = index.locks
        contexts = locks.entry_contexts()
        for fkey, facts in sorted(locks.facts.items()):
            inherited = sorted(contexts.get(fkey, ()), key=sorted)
            for reason, held, line, _call in facts.blocking:
                if held:
                    yield Finding(
                        self.rule_id, self.severity, facts.info.path, line,
                        facts.info.qual,
                        f"blocking call ({reason}) while holding "
                        f"{', '.join(sorted(locks.short_lock(h) for h in held))}",
                        "move the blocking call outside the critical "
                        "section (capture state under the lock, do I/O "
                        "after release)",
                    )
                elif inherited:
                    ls = inherited[0]
                    chain = locks.context_chain(fkey, ls) + [
                        f"{facts.info.path}:{facts.info.qual}:{line}"
                    ]
                    yield Finding(
                        self.rule_id, self.severity, facts.info.path, line,
                        facts.info.qual,
                        f"blocking call ({reason}) reached with "
                        f"{', '.join(sorted(locks.short_lock(h) for h in ls))} "
                        f"held by a caller (call path: {_chain_text(chain)})",
                        "move the blocking call outside the critical "
                        "section, or stop calling this helper under the "
                        "lock",
                    )


class RuleC004:
    """``fork()``-flavored child creation in a threads-and-locks
    package: ``os.fork()`` / ``os.forkpty()``; ``multiprocessing`` with
    the ``fork`` start method (explicit, or implied by a default-context
    ``Process(...)`` -- on Linux the default IS fork); and lock/registry/
    tracer/batcher-shaped state passed as ``Process`` args (inherited or
    duplicated across the process boundary, it silently diverges).

    Incident: the multi-process serving tier (PR 8). Every service
    module here starts threads and holds locks (batcher flusher, ingest
    writer, metrics registry, tracer), so a forked child inherits
    possibly-HELD locks with no owner thread -- the next acquire
    deadlocks forever -- and silently-duplicated registries/rings. The
    fix shape is ``serving/procserver.py``'s: ``subprocess.Popen`` of a
    fresh interpreter (or ``get_context("spawn")``), state handed across
    explicitly -- ring files by path, eventfds via ``pass_fds``."""

    rule_id = "C004"
    severity = "error"

    #: dotted-arg name TOKENS (split on "."/"_") that look like
    #: cross-fork-hazardous state; token equality, not substring -- a
    #: substring match flagged 'wall_clock' (lock) and 'timeout_seconds'
    #: (cond), and C004 is error-severity
    _STATE_HINTS = frozenset((
        "lock", "locks", "rlock", "mutex", "registry", "tracer",
        "batcher", "sem", "semaphore", "cond", "condition",
    ))
    _SAFE_CONTEXTS = ("spawn", "forkserver")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        # one walk collects everything; modules that never touch fork/
        # multiprocessing (almost all of them) exit before any per-call
        # analysis, keeping the full-package sweep inside its budget
        mp_aliases, process_names, calls, assigns = self._collect(ctx)
        if not (mp_aliases or process_names) and not any(
            call_name(c) in ("os.fork", "os.forkpty") for c in calls
        ):
            return
        spawn_ctx, fork_ctx = self._context_names(assigns)
        for call in calls:
            name = call_name(call)
            if name in ("os.fork", "os.forkpty"):
                yield Finding(
                    self.rule_id, self.severity, ctx.path, call.lineno,
                    ctx.symbol_for(call),
                    "os.fork() in a package whose modules start threads "
                    "and hold locks: the child inherits possibly-held "
                    "locks with no owner thread",
                    "exec a fresh interpreter (subprocess.Popen) or use a "
                    "multiprocessing spawn context",
                )
                continue
            if name.endswith((".set_start_method", ".get_context")) or name in (
                "set_start_method", "get_context"
            ):
                root = name.split(".")[0]
                if "." in name and root not in mp_aliases and not (
                    root in fork_ctx or root in spawn_ctx
                ):
                    continue
                if call.args and isinstance(call.args[0], ast.Constant) and (
                    call.args[0].value == "fork"
                ):
                    yield Finding(
                        self.rule_id, self.severity, ctx.path, call.lineno,
                        ctx.symbol_for(call),
                        "explicit multiprocessing 'fork' start method: "
                        "forked children inherit this package's locks and "
                        "registries mid-state",
                        'use get_context("spawn") (fresh interpreter) and '
                        "pass state explicitly",
                    )
                continue
            is_process = False
            if name.endswith(".Process"):
                root = name.rsplit(".", 1)[0]
                if root in spawn_ctx:
                    # the documented fix shape -- still check the args
                    yield from self._check_args(ctx, call)
                    continue
                is_process = root in mp_aliases or root in fork_ctx
            elif name in process_names:
                # covers `from multiprocessing import Process` AND its
                # aliased form (`... import Process as P; P(...)`)
                is_process = True
            if is_process:
                yield Finding(
                    self.rule_id, self.severity, ctx.path, call.lineno,
                    ctx.symbol_for(call),
                    "multiprocessing.Process under the platform-default "
                    "start method (fork on Linux): the child inherits "
                    "this package's locks and registries mid-state",
                    'use get_context("spawn").Process or subprocess.Popen',
                )
                yield from self._check_args(ctx, call)

    def _check_args(self, ctx: ModuleContext, call: ast.Call) -> Iterator[Finding]:
        """Lock/registry-shaped state handed to a child process: even a
        spawn context duplicates it (or fails to pickle it at runtime);
        either way the two copies silently diverge."""
        arg_nodes: list[ast.AST] = list(call.args)
        for kw in call.keywords:
            arg_nodes.append(kw.value)
        for node in arg_nodes:
            for sub in ast.walk(node):
                d = dotted(sub)
                if d is None:
                    continue
                tokens = d.lower().replace(".", "_").split("_")
                if any(t in self._STATE_HINTS for t in tokens):
                    yield Finding(
                        self.rule_id, self.severity, ctx.path, call.lineno,
                        ctx.symbol_for(call),
                        f"{d!r} handed to a child process: lock/registry "
                        "state inherited across the process boundary "
                        "diverges silently (or deadlocks if fork-inherited "
                        "while held)",
                        "share by path/fd (ring file, pass_fds) and rebuild "
                        "the object in the child",
                    )
                    break

    @staticmethod
    def _collect(ctx: ModuleContext) -> tuple:
        """One pass over the module: multiprocessing import aliases,
        names bound to its Process class, every Call node, and every
        Assign-from-Call (context-variable candidates)."""
        mp_aliases: set[str] = set()
        process_names: set[str] = set()
        calls: list[ast.Call] = []
        assigns: list[ast.Assign] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                calls.append(node)
            elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                assigns.append(node)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "multiprocessing":
                        mp_aliases.add(alias.asname or "multiprocessing")
            elif isinstance(node, ast.ImportFrom):
                if node.module == "multiprocessing":
                    for alias in node.names:
                        if alias.name == "Process":
                            process_names.add(alias.asname or "Process")
                        if alias.name in ("get_context", "set_start_method"):
                            mp_aliases.add("")  # bare calls resolve to mp
        return mp_aliases, process_names, calls, assigns

    def _context_names(
        self, assigns: "list[ast.Assign]"
    ) -> tuple[set[str], set[str]]:
        """Names assigned from ``get_context("spawn"|"forkserver")`` vs
        ``get_context("fork")`` / bare ``get_context()``."""
        spawn_ctx: set[str] = set()
        fork_ctx: set[str] = set()
        for node in assigns:
            name = call_name(node.value)
            if not (name == "get_context" or name.endswith(".get_context")):
                continue
            method = None
            if node.value.args and isinstance(node.value.args[0], ast.Constant):
                method = node.value.args[0].value
            target_names = {
                t.id for t in node.targets if isinstance(t, ast.Name)
            }
            if method in self._SAFE_CONTEXTS:
                spawn_ctx |= target_names
            else:
                fork_ctx |= target_names
        return spawn_ctx, fork_ctx


class RuleC005(PackageRule):
    """A blocking call (the C002 catalog, plus another future's
    ``.result()``) anywhere in the call graph below a function passed to
    ``Future.add_done_callback`` -- the flusher role -- or below a
    single-threaded ``select`` event loop (the frontend worker's serve
    loop, the ring consumer). Findings report the witness call path from
    the registration/loop down to the blocking call. ``.result()`` on
    the callback's OWN (already-resolved) future argument is exempt,
    tracked through argument forwarding at any depth; event-loop scans
    skip socket verbs (the loops' own sockets are non-blocking by
    construction).

    Incident: the async scorer fast path (PR 12): every
    ``/queries.json`` response is serialized and pushed to the
    completion ring from a done-callback running ON THE MICRO-BATCHER'S
    FLUSHER THREAD -- one blocking call there stalls every in-flight
    batch, not one request, and the call can hide several frames down
    (`consumer -> submit_query_async -> finish -> on_done -> deliver`).
    The fix shape is ``serving/procserver.py``'s ``_CompletionRetry``:
    one non-blocking push, overflow parked for a timer thread."""

    rule_id = "C005"
    severity = "error"

    def check_package(self, index: PackageIndex) -> Iterator[Finding]:
        reported: set[tuple] = set()
        for role, entry in index.roles.entries(("callback", "eventloop")):
            fi = index.graph.functions.get(entry)
            if fi is None:
                continue
            exempt = (
                frozenset(p for p in fi.params() if p != "self")
                if role.kind == "callback" else frozenset()
            )
            yield from self._scan(
                index, role, fi, exempt,
                [f"{fi.path}:{fi.qual}"], set(), reported,
            )

    def _scan(
        self, index, role, fi, exempt, chain, seen, reported, depth=0
    ) -> Iterator[Finding]:
        state = (fi.key, exempt, role)
        if state in seen or depth > _MAX_DEPTH:
            return
        seen.add(state)
        facts = index.locks.facts.get(fi.key)
        if facts is None:
            return
        for call, _held, line in facts.calls:
            reason = blocking_reason(call)
            if reason is None and isinstance(call.func, ast.Attribute):
                if call.func.attr == "result":
                    recv = dotted(call.func.value) or ""
                    if recv not in exempt:
                        reason = "Future.result()"
            if reason is not None and role.kind == "eventloop" and (
                reason.startswith("socket .")
            ):
                # the loop's own sockets are non-blocking by construction
                reason = None
            if reason is not None:
                key = (fi.path, line, reason)
                if key in reported:
                    continue
                reported.add(key)
                where = (
                    "a Future.add_done_callback callback: it runs on the "
                    "resolving thread (the micro-batcher's flusher on the "
                    "serving path) and stalls every batch behind it"
                    if role.kind == "callback" else
                    "a single-threaded event loop: it stalls every "
                    "connection and ring the loop services"
                )
                yield Finding(
                    self.rule_id, self.severity, fi.path, line,
                    fi.qual,
                    f"blocking call ({reason}) inside {where} "
                    f"[registered at {role.seed}; call path: "
                    f"{_chain_text(chain)}]",
                    "do the work non-blocking and park overflow on "
                    "another thread (the completion-retry-queue shape "
                    "in serving/procserver.py)",
                )
                continue
            for target in index.graph.call_targets.get(
                (fi.path, id(call)), ()
            ):
                fwd = self._forwarded(index, fi, call, target, exempt)
                yield from self._scan(
                    index, role, target, fwd,
                    chain + [f"{target.path}:{target.qual}:{line}"],
                    seen, reported, depth + 1,
                )

    @staticmethod
    def _forwarded(index, caller, call, target, exempt) -> frozenset:
        """Map the caller's exempt (resolved-future) names onto the
        callee's parameters through this call's arguments."""
        if not exempt:
            return frozenset()
        params = target.params()
        offset = 1 if params[:1] == ["self"] else 0
        out = set()
        for i, arg in enumerate(call.args):
            d = dotted(arg)
            if d in exempt and i + offset < len(params):
                out.add(params[i + offset])
        for kw in call.keywords:
            d = dotted(kw.value)
            if d in exempt and kw.arg in params:
                out.add(kw.arg)
        return frozenset(out)


class RuleC006(PackageRule):
    """Eraser-style static lockset race: a field written under one
    thread role and read/written under a different role with DISJOINT
    locksets, anywhere in the package. Roles are inferred
    interprocedurally (``threadroles``): ``Thread(target=...)`` entry
    points, ``threading.Timer`` bodies, done-callback (flusher)
    functions, subprocess ``__main__`` entries -- each a distinct
    concurrent context -- plus the merged "request" role of a class's
    public methods (counted only when some genuinely concurrent role
    also touches the class, so single-threaded tool classes stay
    silent). Locksets join over the witness call path; ``__init__`` and
    thread-constructing lifecycle methods are happens-before the spawn
    and excluded. Findings name both roles, their locksets, the witness
    path, and the lock construction sites so the tier-1 gate can cite
    lockwatch's runtime evidence.

    Incident: generalizes C003 (which guarded a hand-maintained module
    allowlist: ingest/WAL/snapshot/microbatch/metrics/serving/online)
    package-wide after the PR 8-12 tiers spread cross-thread state over
    modules the allowlist never named -- the ring consumer, the flusher
    callbacks, the retry timer, and the supervisor all mutate scorer
    state the request path reads."""

    rule_id = "C006"
    severity = "error"

    def check_package(self, index: PackageIndex) -> Iterator[Finding]:
        records = self._collect_accesses(index)
        confined = self._confined_classes(index)
        for (ckey, attr), recs in sorted(records.items()):
            if ckey in confined:
                continue
            yield from self._judge(index, ckey, attr, recs)

    # -- access collection --------------------------------------------------
    def _collect_accesses(self, index: PackageIndex) -> dict:
        """(class key, attr) -> list of (group, kind, lockset, line,
        path, func qual, role|None) access records, gathered by walking
        the call graph from every concurrent role entry and every public
        request method. Every ``main`` seed folds into ONE group: two
        ``__main__`` guards are two processes, never two threads of one
        process."""
        records: dict = {}
        lifecycle = self._lifecycle_methods(index)
        for role, entry in index.roles.entries(CONCURRENT_KINDS):
            group = "main" if role.kind == "main" else role.label
            self._dfs(
                index, entry, frozenset(), group, role,
                records, {}, lifecycle,
            )
        for cinfo in index.graph.classes.values():
            for name, meth in sorted(cinfo.methods.items()):
                if name.startswith("_") or meth.key in lifecycle:
                    continue
                self._dfs(
                    index, meth.key, frozenset(), "request", None,
                    records, {}, lifecycle,
                )
        return records

    def _dfs(
        self, index, fkey, pathheld, group, role, records, visited,
        lifecycle, depth=0, setup=False,
    ) -> None:
        seen = visited.setdefault(group, set())
        state = (fkey, pathheld, setup)
        if state in seen or depth > _MAX_DEPTH:
            return
        seen.add(state)
        facts = index.locks.facts.get(fkey)
        if facts is None:
            return
        fi = facts.info
        if fi.cls is not None and not setup and fi.name != "__init__" and (
            fkey not in lifecycle
        ):
            ckey = (fi.path, fi.cls)
            for acc in facts.accesses:
                records.setdefault((ckey, acc.attr), []).append((
                    group, acc.kind, frozenset(pathheld | acc.held),
                    acc.line, fi.path, fi.qual, role,
                ))
        for call, held, line in facts.calls:
            for target in index.graph.call_targets.get(
                (fi.path, id(call)), ()
            ):
                # everything reached THROUGH an __init__ (a constructor
                # called mid-traversal builds a fresh object) is
                # initialization, happens-before any sharing -- the
                # Eraser first-thread discount, one level deeper
                self._dfs(
                    index, target.key, frozenset(pathheld | held),
                    group, role, records, visited, lifecycle, depth + 1,
                    setup or target.name in ("__init__", "__enter__")
                    or target.key in lifecycle,
                )

    @staticmethod
    def _lifecycle_methods(index: PackageIndex) -> set:
        """Methods whose execution happens-before the threads they
        spawn: ``__init__``/``__enter__`` plus any method constructing a
        Thread/Timer. Their field writes are setup, not races (the
        Eraser initialization discount, statically)."""
        out: set = set()
        for cinfo in index.graph.classes.values():
            for name, meth in cinfo.methods.items():
                if name in ("__init__", "__enter__"):
                    out.add(meth.key)
                    continue
                for node in index.graph.body_nodes(meth.node):
                    if isinstance(node, ast.Call):
                        cn = call_name(node)
                        if cn.endswith(("Thread", "Timer")) and cn not in (
                            "", "current_thread",
                        ):
                            out.add(meth.key)
                            break
        return out

    # -- the race predicate -------------------------------------------------
    def _judge(self, index, ckey, attr, recs) -> Iterator[Finding]:
        path, cls = ckey
        if self._key_of(index, path, cls, attr) is not None:
            return  # the field IS a lock; guarding it with itself is fine
        strong = {
            r[0] for r in recs
            if r[6] is not None and r[6].kind in ("thread", "timer", "callback")
        }
        if not strong:
            # no genuinely concurrent role ever touches this class:
            # "main" and "request" alone are one thread in practice
            # (tool classes, module mains) -- the C003 precedent kept
            return
        groups: dict[str, list] = {}
        for rec in recs:
            groups.setdefault(rec[0], []).append(rec)
        if len(groups) < 2:
            return
        # the Eraser predicate: >= 2 roles touch the field, at least one
        # writes, and no lock is common to every access
        write_groups = {
            g for g, rs in groups.items() if any(r[1] == "write" for r in rs)
        }
        if not write_groups:
            return
        common = None
        for rs in groups.values():
            for r in rs:
                common = set(r[2]) if common is None else (common & r[2])
        if common:
            return
        # report the most race-shaped pair: a write and an access from a
        # DIFFERENT group with the smallest lockset overlap
        wrec, orec = None, None
        best = None
        for wg in sorted(write_groups):
            for w in groups[wg]:
                if w[1] != "write":
                    continue
                for og in sorted(groups):
                    if og == wg:
                        continue
                    for o in groups[og]:
                        overlap = len(w[2] & o[2])
                        if best is None or overlap < best:
                            best, wrec, orec = overlap, w, o
        if wrec is None:
            return
        locks_seen = sorted({lk for r in recs for lk in r[2]})
        sites = [
            index.locks.lock_sites.get(lk) for lk in locks_seen
        ]
        sites = [s for s in sites if s]
        witness = ""
        if wrec[6] is not None:
            hops = index.roles.witness_path((wrec[4], wrec[5]), wrec[6])
            if hops:
                witness = f"; role path: {_chain_text(hops)}"
        lock_note = (
            "lock sites for runtime witness (lockwatch): "
            + ", ".join(sites)
            if sites else "no lock is held at any access site "
            "(lockwatch has no runtime witness to offer)"
        )
        yield Finding(
            self.rule_id, self.severity, path, wrec[3],
            f"{cls}.{attr}",
            f"field {attr!r} of {cls} is written under role {wrec[0]} "
            f"(locks: {self._lockset_text(index, wrec[2])}) and "
            f"{orec[1]} under role {orec[0]} at {orec[4]}:{orec[3]} "
            f"(locks: {self._lockset_text(index, orec[2])}) with no "
            f"lock common to every access{witness}; {lock_note}",
            "guard every access with one shared lock, confine the field "
            "to a single thread, or publish it immutably before the "
            "thread starts",
        )

    @staticmethod
    def _confined_classes(index: PackageIndex) -> set:
        """Classes whose instances provably never escape one function:
        constructed only as locals, never published to ``self.attr`` /
        returned / passed on, and spawning no threads of their own --
        their fields are thread-confined by construction (the
        ``_ColumnSpill`` shape: a scratch object built, used, and closed
        inside one build call)."""
        published: set = set()
        constructed: set = set()
        graph = index.graph
        for cinfo in graph.classes.values():
            for types in cinfo.attr_types.values():
                published.update(t.key for t in types)
        for fi in graph.functions.values():
            env = graph._local_env(fi)
            local_types = {
                v[1].key: k for k, v in env.items() if v[0] == "type"
            }
            constructed.update(local_types)
            if not local_types:
                continue
            for node in index.graph.body_nodes(fi.node):
                # returning or passing the instance publishes it
                if isinstance(node, ast.Return) and node.value is not None:
                    t = graph.instance_type(fi, node.value)
                    if t is not None:
                        published.add(t.key)
                    elif isinstance(node.value, ast.Call):
                        c = graph._resolve_class_expr(fi, node.value.func)
                        if c is not None:
                            published.add(c.key)
                elif isinstance(node, ast.Call):
                    for arg in list(node.args) + [
                        kw.value for kw in node.keywords
                    ]:
                        t = graph.instance_type(fi, arg)
                        if t is not None and isinstance(arg, ast.Name):
                            published.add(t.key)
        for role, entry in index.roles.entries(("thread", "timer", "callback")):
            fi = graph.functions.get(entry)
            if fi is not None and fi.cls is not None:
                published.add((fi.path, fi.cls))
        return constructed - published

    @staticmethod
    def _key_of(index, path, cls, attr):
        key = f"{path}:{cls}.{attr}"
        return key if key in index.locks.lock_sites else None

    @staticmethod
    def _lockset_text(index, lockset) -> str:
        if not lockset:
            return "none"
        return ", ".join(
            sorted(index.locks.short_lock(lk) for lk in lockset)
        )


RULES = (RuleC001, RuleC002, RuleC004, RuleC005, RuleC006)
