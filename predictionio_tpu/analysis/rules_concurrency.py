"""C-series rules: lock ordering, blocking-I/O-under-lock, unlocked shared
mutation. Built on a light lock-region walk (lexical ``with <lock>:``
nesting plus one level of intra-module call propagation) -- not a full CFG,
but exactly the shapes the PR-2/PR-3 races took.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterator

from predictionio_tpu.analysis.astutil import call_name, dotted, keyword, walk_calls
from predictionio_tpu.analysis.engine import Finding, ModuleContext

#: C003's blast radius: the modules whose state is touched by both request
#: threads and background writer/flusher threads
C003_SCOPE = (
    "data/ingest.py",
    "data/wal.py",
    "data/snapshot.py",
    "workflow/microbatch.py",
    "utils/metrics.py",
    "serving/frontend.py",
    "serving/procserver.py",
    # PR 9: the continuous-learning subsystem -- the loop's state is read
    # by its follow thread and the query server's swap handlers
    "online/follower.py",
    "online/foldin.py",
    "online/registry.py",
    "online/loop.py",
)

_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "Lock", "RLock", "Condition",
}

#: attribute calls that mutate a container in place
_MUTATORS = {
    "append", "extend", "insert", "pop", "remove", "clear", "add",
    "discard", "update", "setdefault", "popitem",
}


def _lock_index(ctx: ModuleContext) -> "_LockIndex":
    """One _LockIndex per module, shared by the three C rules."""
    cached = ctx.symbols.get("__lock_index__")
    if cached is None:
        cached = _LockIndex(ctx)
        ctx.symbols["__lock_index__"] = cached
    return cached


def _lock_id(expr: ast.AST) -> str | None:
    """Normalize a lock reference: ``self._lock`` -> ``_lock``, a bare
    module-level ``_lock`` stays ``_lock``."""
    d = dotted(expr)
    if d is None:
        return None
    if d.startswith("self."):
        return d[len("self."):]
    return d


class _LockIndex:
    """Per-module lock inventory + per-function lock-region facts."""

    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        self.locks: set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                if call_name(node.value) in _LOCK_CTORS:
                    for t in node.targets:
                        lid = _lock_id(t)
                        if lid:
                            self.locks.add(lid)
        #: qualname -> _FuncFacts
        self.funcs: dict[str, "_FuncFacts"] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # symbols[] maps a def to its own qualname ("Class.method")
                qual = ctx.symbols.get(id(node), node.name)
                facts = _FuncFacts(qual, node)
                _walk_regions(node, self.locks, facts)
                self.funcs[qual] = facts

    def lookup(self, caller_qual: str, callee: str) -> "_FuncFacts | None":
        """Resolve ``self.foo()`` / ``foo()`` to a function in this module;
        prefers the caller's own class."""
        if callee.startswith("self."):
            name = callee[len("self."):]
            cls = caller_qual.rsplit(".", 1)[0] if "." in caller_qual else ""
            hit = self.funcs.get(f"{cls}.{name}")
            if hit is not None:
                return hit
            for qual, facts in self.funcs.items():
                if qual.endswith(f".{name}"):
                    return facts
            return None
        return self.funcs.get(callee)


@dataclass
class _FuncFacts:
    qual: str
    node: ast.AST
    #: (lock, frozenset(held), line) at each with-acquisition
    acquisitions: list = field(default_factory=list)
    #: (reason, frozenset(held), line) for blocking calls
    blocking: list = field(default_factory=list)
    #: (callee dotted name, frozenset(held), line) for calls made
    calls: list = field(default_factory=list)
    #: (attr, frozenset(held), line) for self-attribute mutations
    mutations: list = field(default_factory=list)


def _walk_regions(fn: ast.AST, locks: set[str], facts: _FuncFacts) -> None:
    def visit(node: ast.AST, held: tuple) -> None:
        if isinstance(node, ast.With):
            acquired = []
            for item in node.items:
                lid = _lock_id(item.context_expr)
                if lid is not None and lid in locks:
                    facts.acquisitions.append((lid, frozenset(held), node.lineno))
                    acquired.append(lid)
            inner = held + tuple(a for a in acquired if a not in held)
            for child in node.body:
                visit(child, inner)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
            return  # nested defs run on their own call stack
        if isinstance(node, ast.Call):
            name = call_name(node)
            # lock.acquire() outside a with-statement counts as an
            # acquisition event (region tracking stays with-based)
            if isinstance(node.func, ast.Attribute) and node.func.attr == "acquire":
                lid = _lock_id(node.func.value)
                if lid in locks:
                    facts.acquisitions.append((lid, frozenset(held), node.lineno))
            reason = _blocking_reason(node)
            if reason is not None:
                facts.blocking.append((reason, frozenset(held), node.lineno))
            if name and (name.startswith("self.") or name in ("",) or "." not in name):
                facts.calls.append((name, frozenset(held), node.lineno))
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATORS
            ):
                recv = dotted(node.func.value) or ""
                if recv.startswith("self.") and recv.count(".") == 1:
                    facts.mutations.append(
                        (recv[len("self."):], frozenset(held), node.lineno)
                    )
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                d = dotted(t)
                if d and d.startswith("self.") and d.count(".") == 1:
                    facts.mutations.append(
                        (d[len("self."):], frozenset(held), node.lineno)
                    )
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in ast.iter_child_nodes(fn):
        visit(stmt, ())


def _blocking_reason(call: ast.Call) -> str | None:
    name = call_name(call)
    if name == "os.fsync":
        return "os.fsync"
    if isinstance(call.func, ast.Attribute):
        attr = call.func.attr
        if attr == "fsync":
            return "fsync"
        # span/trace export under a lock serializes every instrumented hot
        # path behind the exporter's I/O -- the classic tracing-overhead
        # incident shape (obs/ policy: ring-buffer under the lock, any
        # export/flush outside it). `.export()`/`.force_flush()` are the
        # OTel exporter verbs; a bare `.flush()` only counts on receivers
        # that look like tracing objects, so file/stream flushes stay
        # un-flagged.
        if attr in ("export", "export_spans", "force_flush"):
            return f"span export .{attr}()"
        if attr == "flush":
            recv = (dotted(call.func.value) or "").lower()
            if any(
                s in recv for s in ("trace", "span", "exporter", "telemetry")
            ):
                return f"span export .{attr}()"
        if attr in ("execute", "executemany", "commit", "rollback"):
            return f"SQL .{attr}()"
        if attr in ("connect", "sendall", "recv", "accept", "makefile"):
            return f"socket .{attr}()"
        if attr in ("put", "get"):
            recv = (dotted(call.func.value) or "").lower()
            if "queue" in recv or recv in ("q", "self.q"):
                if keyword(call, "timeout") is not None:
                    return None
                block_kw = keyword(call, "block")
                if block_kw is not None and isinstance(
                    block_kw.value, ast.Constant
                ) and block_kw.value.value is False:
                    return None
                return f"blocking queue .{attr}() without timeout"
    if name == "time.sleep":
        return "time.sleep"
    if name in ("urllib.request.urlopen", "urlopen"):
        return "urlopen"
    return None


class RuleC001:
    """Inconsistent lock-acquisition order (cycle in the module's lock
    graph). Incident class: the PR-2/PR-3 snapshot-GC and checkpoint-
    ordering races; a cycle here is a deadlock waiting for the right
    interleaving. Validated at runtime by ``analysis/lockwatch.py``."""

    rule_id = "C001"
    severity = "error"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        index = _lock_index(ctx)
        if len(index.locks) < 2:
            return
        # edges: lock A held while acquiring lock B (direct + one level of
        # intra-module call propagation)
        edges: dict[tuple[str, str], int] = {}
        for facts in index.funcs.values():
            for lock, held, line in facts.acquisitions:
                for h in held:
                    if h != lock:
                        edges.setdefault((h, lock), line)
            for callee, held, line in facts.calls:
                if not held:
                    continue
                target = index.lookup(facts.qual, callee)
                if target is None:
                    continue
                for lock, _, _ in target.acquisitions:
                    for h in held:
                        if h != lock:
                            edges.setdefault((h, lock), line)
        reported: set[frozenset] = set()
        for (a, b), line in sorted(edges.items(), key=lambda kv: kv[1]):
            if (b, a) in edges and frozenset((a, b)) not in reported:
                reported.add(frozenset((a, b)))
                yield Finding(
                    self.rule_id, self.severity, ctx.path, line,
                    "<module>",
                    f"inconsistent lock order: {a!r} -> {b!r} (line {line}) "
                    f"but also {b!r} -> {a!r} (line {edges[(b, a)]})",
                    "pick one global acquisition order and restructure the "
                    "second site to follow it",
                )


class RuleC002:
    """Blocking I/O while holding a lock. Incident: the WAL held its writer
    lock across the group-commit fsync, serializing appenders behind disk
    latency; same shape as fsync-under-lock in the snapshot store."""

    rule_id = "C002"
    severity = "warning"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        index = _lock_index(ctx)
        if not index.locks:
            return
        for facts in index.funcs.values():
            for reason, held, line in facts.blocking:
                if not held:
                    continue
                yield Finding(
                    self.rule_id, self.severity, ctx.path, line,
                    facts.qual,
                    f"blocking call ({reason}) while holding "
                    f"{', '.join(sorted(held))}",
                    "move the blocking call outside the critical section "
                    "(capture state under the lock, do I/O after release)",
                )


class RuleC003:
    """A field mutated from two threads' entry points with no common lock.
    Scoped to the modules where request threads and background writers
    share state. Entry points: ``threading.Thread(target=self.X)`` methods
    (background) vs public methods (request threads)."""

    rule_id = "C003"
    severity = "error"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not any(ctx.path.endswith(s) for s in C003_SCOPE):
            return
        index = _lock_index(ctx)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(ctx, index, node)

    def _check_class(self, ctx, index, cls: ast.ClassDef):
        cls_qual = ctx.symbols.get(id(cls), cls.name)
        methods = {
            q.rsplit(".", 1)[1]: f
            for q, f in index.funcs.items()
            if q.startswith(f"{cls_qual}.") and q.count(".") == cls_qual.count(".") + 1
        }
        bg_roots = set()
        for call in walk_calls(cls):
            if call_name(call).endswith("Thread"):
                kw = keyword(call, "target")
                if kw is not None:
                    d = dotted(kw.value) or ""
                    if d.startswith("self."):
                        bg_roots.add(d[len("self."):])
        if not bg_roots:
            return
        fg_roots = {
            name for name in methods
            if not name.startswith("_") and name not in bg_roots
        }
        # attr -> root kind -> list of locksets observed at mutation sites
        observed: dict[str, dict[str, list]] = {}
        lines: dict[str, int] = {}
        for kind, roots in (("bg", bg_roots), ("fg", fg_roots)):
            for root in roots:
                for attr, held, line in self._reachable_mutations(
                    index, cls_qual, methods, root
                ):
                    if attr in index.locks:
                        continue
                    observed.setdefault(attr, {}).setdefault(kind, []).append(held)
                    lines.setdefault(attr, line)
        for attr, by_kind in sorted(observed.items()):
            if "bg" not in by_kind or "fg" not in by_kind:
                continue
            locksets = by_kind["bg"] + by_kind["fg"]
            common = set(locksets[0])
            for ls in locksets[1:]:
                common &= set(ls)
            if common:
                continue
            yield Finding(
                self.rule_id, self.severity, ctx.path, lines[attr],
                cls_qual,
                f"field {attr!r} is mutated from both a background-thread "
                "entry point and a public (request-thread) method without a "
                "common lock",
                "guard every mutation site with one shared lock, or confine "
                "the field to a single thread",
            )

    def _reachable_mutations(self, index, cls_qual, methods, root):
        """Mutations reachable from ``root`` (BFS over self-calls within
        the class, two levels deep), each with the locks held along the
        path. ``__init__`` is excluded: it happens-before thread start."""
        out = []
        seen: set[tuple[str, frozenset]] = set()
        queue: list[tuple[str, frozenset, int]] = [(root, frozenset(), 0)]
        while queue:
            name, path_held, depth = queue.pop(0)
            if name == "__init__" or (name, path_held) in seen:
                continue
            seen.add((name, path_held))
            facts = methods.get(name)
            if facts is None:
                continue
            for attr, held, line in facts.mutations:
                out.append((attr, frozenset(path_held | held), line))
            if depth >= 2:
                continue
            for callee, held, _ in facts.calls:
                if callee.startswith("self."):
                    queue.append(
                        (callee[len("self."):], frozenset(path_held | held), depth + 1)
                    )
        return out


class RuleC004:
    """``fork()``-flavored child creation in a threads-and-locks package.
    Incident class: the multi-process serving tier (PR 8). Every service
    module here starts threads and holds locks (batcher flusher, ingest
    writer, metrics registry locks, the tracer lock); a ``fork()`` child
    inherits a snapshot where those locks may be HELD by threads that do
    not exist in the child -- the next acquire deadlocks forever -- and
    where registries/rings are silently duplicated, so counters fork too.
    The fix shape is the one ``serving/procserver.py`` uses: spawn a
    FRESH interpreter (``subprocess.Popen`` or a ``get_context("spawn")``
    multiprocessing context) and hand state across explicitly (fds via
    ``pass_fds``, shared files by path).

    Flags, anywhere in the package:

    - ``os.fork()`` / ``os.forkpty()`` calls;
    - ``multiprocessing.set_start_method("fork")`` /
      ``get_context("fork")``;
    - ``Process(...)`` constructions whose context is the platform
      default or a fork context (on Linux the default IS fork) -- a
      ``get_context("spawn")``/``"forkserver"`` context is the negative;
    - lock/registry/tracer/batcher-shaped state passed as ``Process``
      args (inherited-across-fork hazard even when it pickles).
    """

    rule_id = "C004"
    severity = "error"

    #: dotted-arg name TOKENS (split on "."/"_") that look like
    #: cross-fork-hazardous state; token equality, not substring -- a
    #: substring match flagged 'wall_clock' (lock) and 'timeout_seconds'
    #: (cond), and C004 is error-severity
    _STATE_HINTS = frozenset((
        "lock", "locks", "rlock", "mutex", "registry", "tracer",
        "batcher", "sem", "semaphore", "cond", "condition",
    ))
    _SAFE_CONTEXTS = ("spawn", "forkserver")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        # one walk collects everything; modules that never touch fork/
        # multiprocessing (almost all of them) exit before any per-call
        # analysis, keeping the full-package sweep inside its budget
        mp_aliases, process_names, calls, assigns = self._collect(ctx)
        if not (mp_aliases or process_names) and not any(
            call_name(c) in ("os.fork", "os.forkpty") for c in calls
        ):
            return
        spawn_ctx, fork_ctx = self._context_names(assigns)
        for call in calls:
            name = call_name(call)
            if name in ("os.fork", "os.forkpty"):
                yield Finding(
                    self.rule_id, self.severity, ctx.path, call.lineno,
                    ctx.symbol_for(call),
                    "os.fork() in a package whose modules start threads "
                    "and hold locks: the child inherits possibly-held "
                    "locks with no owner thread",
                    "exec a fresh interpreter (subprocess.Popen) or use a "
                    "multiprocessing spawn context",
                )
                continue
            if name.endswith((".set_start_method", ".get_context")) or name in (
                "set_start_method", "get_context"
            ):
                root = name.split(".")[0]
                if "." in name and root not in mp_aliases and not (
                    root in fork_ctx or root in spawn_ctx
                ):
                    continue
                if call.args and isinstance(call.args[0], ast.Constant) and (
                    call.args[0].value == "fork"
                ):
                    yield Finding(
                        self.rule_id, self.severity, ctx.path, call.lineno,
                        ctx.symbol_for(call),
                        "explicit multiprocessing 'fork' start method: "
                        "forked children inherit this package's locks and "
                        "registries mid-state",
                        'use get_context("spawn") (fresh interpreter) and '
                        "pass state explicitly",
                    )
                continue
            is_process = False
            if name.endswith(".Process"):
                root = name.rsplit(".", 1)[0]
                if root in spawn_ctx:
                    # the documented fix shape -- still check the args
                    yield from self._check_args(ctx, call)
                    continue
                is_process = root in mp_aliases or root in fork_ctx
            elif name in process_names:
                # covers `from multiprocessing import Process` AND its
                # aliased form (`... import Process as P; P(...)`)
                is_process = True
            if is_process:
                yield Finding(
                    self.rule_id, self.severity, ctx.path, call.lineno,
                    ctx.symbol_for(call),
                    "multiprocessing.Process under the platform-default "
                    "start method (fork on Linux): the child inherits "
                    "this package's locks and registries mid-state",
                    'use get_context("spawn").Process or subprocess.Popen',
                )
                yield from self._check_args(ctx, call)

    def _check_args(self, ctx: ModuleContext, call: ast.Call) -> Iterator[Finding]:
        """Lock/registry-shaped state handed to a child process: even a
        spawn context duplicates it (or fails to pickle it at runtime);
        either way the two copies silently diverge."""
        arg_nodes: list[ast.AST] = list(call.args)
        for kw in call.keywords:
            arg_nodes.append(kw.value)
        for node in arg_nodes:
            for sub in ast.walk(node):
                d = dotted(sub)
                if d is None:
                    continue
                tokens = d.lower().replace(".", "_").split("_")
                if any(t in self._STATE_HINTS for t in tokens):
                    yield Finding(
                        self.rule_id, self.severity, ctx.path, call.lineno,
                        ctx.symbol_for(call),
                        f"{d!r} handed to a child process: lock/registry "
                        "state inherited across the process boundary "
                        "diverges silently (or deadlocks if fork-inherited "
                        "while held)",
                        "share by path/fd (ring file, pass_fds) and rebuild "
                        "the object in the child",
                    )
                    break

    @staticmethod
    def _collect(ctx: ModuleContext) -> tuple:
        """One pass over the module: multiprocessing import aliases,
        names bound to its Process class, every Call node, and every
        Assign-from-Call (context-variable candidates)."""
        mp_aliases: set[str] = set()
        process_names: set[str] = set()
        calls: list[ast.Call] = []
        assigns: list[ast.Assign] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                calls.append(node)
            elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                assigns.append(node)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "multiprocessing":
                        mp_aliases.add(alias.asname or "multiprocessing")
            elif isinstance(node, ast.ImportFrom):
                if node.module == "multiprocessing":
                    for alias in node.names:
                        if alias.name == "Process":
                            process_names.add(alias.asname or "Process")
                        if alias.name in ("get_context", "set_start_method"):
                            mp_aliases.add("")  # bare calls resolve to mp
        return mp_aliases, process_names, calls, assigns

    def _context_names(
        self, assigns: "list[ast.Assign]"
    ) -> tuple[set[str], set[str]]:
        """Names assigned from ``get_context("spawn"|"forkserver")`` vs
        ``get_context("fork")`` / bare ``get_context()``."""
        spawn_ctx: set[str] = set()
        fork_ctx: set[str] = set()
        for node in assigns:
            name = call_name(node.value)
            if not (name == "get_context" or name.endswith(".get_context")):
                continue
            method = None
            if node.value.args and isinstance(node.value.args[0], ast.Constant):
                method = node.value.args[0].value
            target_names = {
                t.id for t in node.targets if isinstance(t, ast.Name)
            }
            if method in self._SAFE_CONTEXTS:
                spawn_ctx |= target_names
            else:
                fork_ctx |= target_names
        return spawn_ctx, fork_ctx


class RuleC005:
    """Blocking call inside a function passed to
    ``Future.add_done_callback``. Incident class: the async scorer fast
    path (PR 12) finishes every ``/queries.json`` request -- plugins,
    serialization, the completion-ring push -- in a done-callback that
    runs ON THE MICRO-BATCHER'S FLUSHER THREAD; one blocking call there
    (fsync, SQL, socket I/O, ``time.sleep``, a timeout-less queue op --
    the C002 catalog -- or another future's ``.result()``) stalls every
    in-flight batch, not one request. The correct shape is the
    completion-retry queue in ``serving/procserver.py``: try once
    non-blocking, park overflow for a timer thread.

    ``.result()`` on the callback's OWN argument (or a parameter the
    future was forwarded to, one call level deep) is exempt: a done
    callback receives an already-resolved future, so that call cannot
    block. Propagates one level through intra-module calls, the C001
    pattern."""

    rule_id = "C005"
    severity = "error"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        index = _lock_index(ctx)
        for node in ast.walk(ctx.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_done_callback"
                and node.args
            ):
                continue
            caller_qual = ctx.symbol_for(node)
            yield from self._check_callback(
                ctx, index, caller_qual, node.args[0], node.lineno
            )

    def _check_callback(
        self, ctx, index, caller_qual, cb: ast.AST, reg_line: int
    ) -> Iterator[Finding]:
        # functools.partial(fn, ...): the callable is the first arg
        if isinstance(cb, ast.Call) and call_name(cb) in (
            "partial", "functools.partial"
        ) and cb.args:
            cb = cb.args[0]
        if isinstance(cb, ast.Lambda):
            params = {a.arg for a in cb.args.args}
            yield from self._scan(
                ctx, index, caller_qual, cb, params, set()
            )
            return
        name = dotted(cb)
        if name is None:
            return
        facts = index.lookup(caller_qual, name)
        if facts is None:
            return
        yield from self._scan(
            ctx, index, facts.qual, facts.node,
            self._params(facts.node), {facts.qual},
        )

    @staticmethod
    def _params(fn: ast.AST) -> set[str]:
        args = fn.args
        names = {a.arg for a in args.args + args.kwonlyargs}
        names.discard("self")
        return names

    def _scan(
        self, ctx, index, qual: str, fn: ast.AST, params: set[str],
        seen: set, depth: int = 0,
    ) -> Iterator[Finding]:
        """Walk one callback body (skipping nested defs -- they run on
        their own call stack) for blocking calls; recurse one level into
        intra-module callees."""
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        stack = list(body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(node, ast.Call):
                reason = _blocking_reason(node)
                if reason is None and isinstance(node.func, ast.Attribute):
                    if node.func.attr == "result":
                        recv = dotted(node.func.value) or ""
                        if recv not in params:
                            reason = "Future.result()"
                if reason is not None:
                    yield Finding(
                        self.rule_id, self.severity, ctx.path, node.lineno,
                        qual,
                        f"blocking call ({reason}) inside a "
                        "Future.add_done_callback callback: it runs on "
                        "the resolving thread (the micro-batcher's "
                        "flusher on the serving path) and stalls every "
                        "batch behind it",
                        "do the work non-blocking and park overflow on "
                        "another thread (the completion-retry-queue "
                        "shape in serving/procserver.py)",
                    )
                elif depth < 1:
                    name = call_name(node)
                    if name and (name.startswith("self.") or "." not in name):
                        callee = index.lookup(qual, name)
                        if callee is not None and callee.qual not in seen:
                            yield from self._scan(
                                ctx, index, callee.qual, callee.node,
                                self._params(callee.node),
                                seen | {callee.qual}, depth + 1,
                            )
            stack.extend(ast.iter_child_nodes(node))


RULES = (RuleC001, RuleC002, RuleC003, RuleC004, RuleC005)
