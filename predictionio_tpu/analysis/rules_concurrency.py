"""C-series rules: lock ordering, blocking-I/O-under-lock, unlocked shared
mutation. Built on a light lock-region walk (lexical ``with <lock>:``
nesting plus one level of intra-module call propagation) -- not a full CFG,
but exactly the shapes the PR-2/PR-3 races took.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterator

from predictionio_tpu.analysis.astutil import call_name, dotted, keyword, walk_calls
from predictionio_tpu.analysis.engine import Finding, ModuleContext

#: C003's blast radius: the modules whose state is touched by both request
#: threads and background writer/flusher threads
C003_SCOPE = (
    "data/ingest.py",
    "data/wal.py",
    "data/snapshot.py",
    "workflow/microbatch.py",
    "utils/metrics.py",
)

_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "Lock", "RLock", "Condition",
}

#: attribute calls that mutate a container in place
_MUTATORS = {
    "append", "extend", "insert", "pop", "remove", "clear", "add",
    "discard", "update", "setdefault", "popitem",
}


def _lock_index(ctx: ModuleContext) -> "_LockIndex":
    """One _LockIndex per module, shared by the three C rules."""
    cached = ctx.symbols.get("__lock_index__")
    if cached is None:
        cached = _LockIndex(ctx)
        ctx.symbols["__lock_index__"] = cached
    return cached


def _lock_id(expr: ast.AST) -> str | None:
    """Normalize a lock reference: ``self._lock`` -> ``_lock``, a bare
    module-level ``_lock`` stays ``_lock``."""
    d = dotted(expr)
    if d is None:
        return None
    if d.startswith("self."):
        return d[len("self."):]
    return d


class _LockIndex:
    """Per-module lock inventory + per-function lock-region facts."""

    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        self.locks: set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                if call_name(node.value) in _LOCK_CTORS:
                    for t in node.targets:
                        lid = _lock_id(t)
                        if lid:
                            self.locks.add(lid)
        #: qualname -> _FuncFacts
        self.funcs: dict[str, "_FuncFacts"] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # symbols[] maps a def to its own qualname ("Class.method")
                qual = ctx.symbols.get(id(node), node.name)
                facts = _FuncFacts(qual, node)
                _walk_regions(node, self.locks, facts)
                self.funcs[qual] = facts

    def lookup(self, caller_qual: str, callee: str) -> "_FuncFacts | None":
        """Resolve ``self.foo()`` / ``foo()`` to a function in this module;
        prefers the caller's own class."""
        if callee.startswith("self."):
            name = callee[len("self."):]
            cls = caller_qual.rsplit(".", 1)[0] if "." in caller_qual else ""
            hit = self.funcs.get(f"{cls}.{name}")
            if hit is not None:
                return hit
            for qual, facts in self.funcs.items():
                if qual.endswith(f".{name}"):
                    return facts
            return None
        return self.funcs.get(callee)


@dataclass
class _FuncFacts:
    qual: str
    node: ast.AST
    #: (lock, frozenset(held), line) at each with-acquisition
    acquisitions: list = field(default_factory=list)
    #: (reason, frozenset(held), line) for blocking calls
    blocking: list = field(default_factory=list)
    #: (callee dotted name, frozenset(held), line) for calls made
    calls: list = field(default_factory=list)
    #: (attr, frozenset(held), line) for self-attribute mutations
    mutations: list = field(default_factory=list)


def _walk_regions(fn: ast.AST, locks: set[str], facts: _FuncFacts) -> None:
    def visit(node: ast.AST, held: tuple) -> None:
        if isinstance(node, ast.With):
            acquired = []
            for item in node.items:
                lid = _lock_id(item.context_expr)
                if lid is not None and lid in locks:
                    facts.acquisitions.append((lid, frozenset(held), node.lineno))
                    acquired.append(lid)
            inner = held + tuple(a for a in acquired if a not in held)
            for child in node.body:
                visit(child, inner)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node is not fn:
            return  # nested defs run on their own call stack
        if isinstance(node, ast.Call):
            name = call_name(node)
            # lock.acquire() outside a with-statement counts as an
            # acquisition event (region tracking stays with-based)
            if isinstance(node.func, ast.Attribute) and node.func.attr == "acquire":
                lid = _lock_id(node.func.value)
                if lid in locks:
                    facts.acquisitions.append((lid, frozenset(held), node.lineno))
            reason = _blocking_reason(node)
            if reason is not None:
                facts.blocking.append((reason, frozenset(held), node.lineno))
            if name and (name.startswith("self.") or name in ("",) or "." not in name):
                facts.calls.append((name, frozenset(held), node.lineno))
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATORS
            ):
                recv = dotted(node.func.value) or ""
                if recv.startswith("self.") and recv.count(".") == 1:
                    facts.mutations.append(
                        (recv[len("self."):], frozenset(held), node.lineno)
                    )
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                d = dotted(t)
                if d and d.startswith("self.") and d.count(".") == 1:
                    facts.mutations.append(
                        (d[len("self."):], frozenset(held), node.lineno)
                    )
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in ast.iter_child_nodes(fn):
        visit(stmt, ())


def _blocking_reason(call: ast.Call) -> str | None:
    name = call_name(call)
    if name == "os.fsync":
        return "os.fsync"
    if isinstance(call.func, ast.Attribute):
        attr = call.func.attr
        if attr == "fsync":
            return "fsync"
        # span/trace export under a lock serializes every instrumented hot
        # path behind the exporter's I/O -- the classic tracing-overhead
        # incident shape (obs/ policy: ring-buffer under the lock, any
        # export/flush outside it). `.export()`/`.force_flush()` are the
        # OTel exporter verbs; a bare `.flush()` only counts on receivers
        # that look like tracing objects, so file/stream flushes stay
        # un-flagged.
        if attr in ("export", "export_spans", "force_flush"):
            return f"span export .{attr}()"
        if attr == "flush":
            recv = (dotted(call.func.value) or "").lower()
            if any(
                s in recv for s in ("trace", "span", "exporter", "telemetry")
            ):
                return f"span export .{attr}()"
        if attr in ("execute", "executemany", "commit", "rollback"):
            return f"SQL .{attr}()"
        if attr in ("connect", "sendall", "recv", "accept", "makefile"):
            return f"socket .{attr}()"
        if attr in ("put", "get"):
            recv = (dotted(call.func.value) or "").lower()
            if "queue" in recv or recv in ("q", "self.q"):
                if keyword(call, "timeout") is not None:
                    return None
                block_kw = keyword(call, "block")
                if block_kw is not None and isinstance(
                    block_kw.value, ast.Constant
                ) and block_kw.value.value is False:
                    return None
                return f"blocking queue .{attr}() without timeout"
    if name == "time.sleep":
        return "time.sleep"
    if name in ("urllib.request.urlopen", "urlopen"):
        return "urlopen"
    return None


class RuleC001:
    """Inconsistent lock-acquisition order (cycle in the module's lock
    graph). Incident class: the PR-2/PR-3 snapshot-GC and checkpoint-
    ordering races; a cycle here is a deadlock waiting for the right
    interleaving. Validated at runtime by ``analysis/lockwatch.py``."""

    rule_id = "C001"
    severity = "error"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        index = _lock_index(ctx)
        if len(index.locks) < 2:
            return
        # edges: lock A held while acquiring lock B (direct + one level of
        # intra-module call propagation)
        edges: dict[tuple[str, str], int] = {}
        for facts in index.funcs.values():
            for lock, held, line in facts.acquisitions:
                for h in held:
                    if h != lock:
                        edges.setdefault((h, lock), line)
            for callee, held, line in facts.calls:
                if not held:
                    continue
                target = index.lookup(facts.qual, callee)
                if target is None:
                    continue
                for lock, _, _ in target.acquisitions:
                    for h in held:
                        if h != lock:
                            edges.setdefault((h, lock), line)
        reported: set[frozenset] = set()
        for (a, b), line in sorted(edges.items(), key=lambda kv: kv[1]):
            if (b, a) in edges and frozenset((a, b)) not in reported:
                reported.add(frozenset((a, b)))
                yield Finding(
                    self.rule_id, self.severity, ctx.path, line,
                    "<module>",
                    f"inconsistent lock order: {a!r} -> {b!r} (line {line}) "
                    f"but also {b!r} -> {a!r} (line {edges[(b, a)]})",
                    "pick one global acquisition order and restructure the "
                    "second site to follow it",
                )


class RuleC002:
    """Blocking I/O while holding a lock. Incident: the WAL held its writer
    lock across the group-commit fsync, serializing appenders behind disk
    latency; same shape as fsync-under-lock in the snapshot store."""

    rule_id = "C002"
    severity = "warning"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        index = _lock_index(ctx)
        if not index.locks:
            return
        for facts in index.funcs.values():
            for reason, held, line in facts.blocking:
                if not held:
                    continue
                yield Finding(
                    self.rule_id, self.severity, ctx.path, line,
                    facts.qual,
                    f"blocking call ({reason}) while holding "
                    f"{', '.join(sorted(held))}",
                    "move the blocking call outside the critical section "
                    "(capture state under the lock, do I/O after release)",
                )


class RuleC003:
    """A field mutated from two threads' entry points with no common lock.
    Scoped to the modules where request threads and background writers
    share state. Entry points: ``threading.Thread(target=self.X)`` methods
    (background) vs public methods (request threads)."""

    rule_id = "C003"
    severity = "error"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not any(ctx.path.endswith(s) for s in C003_SCOPE):
            return
        index = _lock_index(ctx)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(ctx, index, node)

    def _check_class(self, ctx, index, cls: ast.ClassDef):
        cls_qual = ctx.symbols.get(id(cls), cls.name)
        methods = {
            q.rsplit(".", 1)[1]: f
            for q, f in index.funcs.items()
            if q.startswith(f"{cls_qual}.") and q.count(".") == cls_qual.count(".") + 1
        }
        bg_roots = set()
        for call in walk_calls(cls):
            if call_name(call).endswith("Thread"):
                kw = keyword(call, "target")
                if kw is not None:
                    d = dotted(kw.value) or ""
                    if d.startswith("self."):
                        bg_roots.add(d[len("self."):])
        if not bg_roots:
            return
        fg_roots = {
            name for name in methods
            if not name.startswith("_") and name not in bg_roots
        }
        # attr -> root kind -> list of locksets observed at mutation sites
        observed: dict[str, dict[str, list]] = {}
        lines: dict[str, int] = {}
        for kind, roots in (("bg", bg_roots), ("fg", fg_roots)):
            for root in roots:
                for attr, held, line in self._reachable_mutations(
                    index, cls_qual, methods, root
                ):
                    if attr in index.locks:
                        continue
                    observed.setdefault(attr, {}).setdefault(kind, []).append(held)
                    lines.setdefault(attr, line)
        for attr, by_kind in sorted(observed.items()):
            if "bg" not in by_kind or "fg" not in by_kind:
                continue
            locksets = by_kind["bg"] + by_kind["fg"]
            common = set(locksets[0])
            for ls in locksets[1:]:
                common &= set(ls)
            if common:
                continue
            yield Finding(
                self.rule_id, self.severity, ctx.path, lines[attr],
                cls_qual,
                f"field {attr!r} is mutated from both a background-thread "
                "entry point and a public (request-thread) method without a "
                "common lock",
                "guard every mutation site with one shared lock, or confine "
                "the field to a single thread",
            )

    def _reachable_mutations(self, index, cls_qual, methods, root):
        """Mutations reachable from ``root`` (BFS over self-calls within
        the class, two levels deep), each with the locks held along the
        path. ``__init__`` is excluded: it happens-before thread start."""
        out = []
        seen: set[tuple[str, frozenset]] = set()
        queue: list[tuple[str, frozenset, int]] = [(root, frozenset(), 0)]
        while queue:
            name, path_held, depth = queue.pop(0)
            if name == "__init__" or (name, path_held) in seen:
                continue
            seen.add((name, path_held))
            facts = methods.get(name)
            if facts is None:
                continue
            for attr, held, line in facts.mutations:
                out.append((attr, frozenset(path_held | held), line))
            if depth >= 2:
                continue
            for callee, held, _ in facts.calls:
                if callee.startswith("self."):
                    queue.append(
                        (callee[len("self."):], frozenset(path_held | held), depth + 1)
                    )
        return out


RULES = (RuleC001, RuleC002, RuleC003)
