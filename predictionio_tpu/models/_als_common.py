"""Shared pieces of the ALS-backed templates (recommendation, e-commerce).

One source of truth for the behaviors both ALS templates must agree on:
mesh-aware CSR packing, the fingerprinted step-checkpoint wiring
(preemption safety, SURVEY §5.4), the seen-items map, and the rank+format
tail of their ``itemScores`` responses (predict and the vectorized batch
path must rank identically). The cooccurrence-based templates keep their
own tails: their exclusion sentinel is 0, not -inf.
"""

from __future__ import annotations

import hashlib
import logging

import numpy as np

from predictionio_tpu.parallel.als import (
    ALSConfig,
    ALSModel,
    als_fit,
    als_fit_streamed,
    build_als_data,
)

logger = logging.getLogger("pio.als")


def prepare_als_data(
    ctx,
    params,
    users: np.ndarray,
    items: np.ndarray,
    values: np.ndarray,
    num_users: int,
    num_items: int,
    times: np.ndarray,
):
    """Pack COO interactions into padded CSR blocks sized for ctx's mesh.

    Rows pad to multiples of 8 * data_axis * model_axis: a model axis of
    1 (the default mesh) reproduces the historical layout, and a model
    axis > 1 makes the blocks ready for the ALX factor-sharded mode the
    fit side auto-selects on such meshes (resolve_factor_sharding).
    """
    config = ALSConfig(
        max_len=params.get_or("maxEventsPerUser", None),
        # length-bucketed packing: engine.json "buckets" (default 1 keeps
        # the single-block layout; the ML-20M bench uses 4)
        buckets=params.get_or("buckets", 1),
    )
    num_shards, model_shards = 1, 1
    try:
        num_shards = ctx.mesh.shape.get("data", 1)
        model_shards = ctx.mesh.shape.get("model", 1)
    except Exception:
        pass  # no devices available (pure-host tests)
    return build_als_data(
        users,
        items,
        values,
        num_users,
        num_items,
        config,
        times=times,
        num_shards=num_shards,
        model_shards=model_shards,
    )


#: packing knobs the PREPARATOR consumes; a natural mistake is putting
#: them in the algorithm block (the reference template had no preparator
#: params), where they would be silently ignored
PACKING_PARAM_KEYS = ("maxEventsPerUser", "buckets")


def warn_misplaced_packing_params(algo_params, template: str) -> None:
    misplaced = [
        k for k in PACKING_PARAM_KEYS
        if algo_params.get_or(k, None) is not None
    ]
    if misplaced:
        logger.warning(
            "%s: %s configure the PREPARATOR (put them under "
            '"preparator": {"params": {...}} in engine.json); they are '
            "ignored in the algorithm block",
            template, ", ".join(misplaced),
        )


def resolve_solver_override(config: ALSConfig, ctx) -> ALSConfig:
    """Apply the run-scoped ``pio.als_solver`` conf (``pio train
    --als-solver``) over the engine.json ``alsSolver`` param.

    The CLI flag is an operator override -- benchmarking the fused Pallas
    half-step against the XLA einsum path, or pinning "xla" if a jax/Mosaic
    upgrade regresses the kernel -- so it wins over the variant file.
    ``make_iteration`` validates the value.
    """
    import dataclasses

    solver = getattr(ctx, "runtime_conf", None) or {}
    solver = solver.get("pio.als_solver")
    if not solver:
        return config
    return dataclasses.replace(config, solver=str(solver))


def resolve_factor_sharding(config: ALSConfig, mesh) -> ALSConfig:
    """Resolve ``factor_sharding="auto"`` against the actual mesh.

    On a pure-ALS template a model axis > 1 has exactly one use -- ALX
    factor sharding -- so "auto" (the template default) selects it
    whenever ``pio.mesh_shape`` configures such an axis, and plain data
    parallelism otherwise. Explicit "replicated"/"model" pass through to
    the library untouched (als_fit validates them).
    """
    import dataclasses

    if config.factor_sharding != "auto":
        return config
    try:
        model = mesh.shape.get("model", 1) if mesh is not None else 1
    except Exception:
        model = 1
    return dataclasses.replace(
        config, factor_sharding="model" if model > 1 else "replicated"
    )


def build_seen(users: np.ndarray, items: np.ndarray) -> dict[int, set[int]]:
    """user index -> set of interacted item indices (serving-time filter).

    Sorted-split construction: one stable argsort + one ``np.unique``
    boundary scan, so interpreter time is O(distinct users), not O(events)
    -- this runs on EVERY model build and the per-event Python loop it
    replaces was a measurable slice of large builds. The dict-of-sets
    return type is the serving contract (``_seen_indices`` and fold-in
    both mutate copies of it)."""
    users = np.asarray(users)
    if users.size == 0:
        return {}
    order = np.argsort(users, kind="stable")
    sorted_users = users[order]
    sorted_items = np.asarray(items)[order]
    uniq, starts = np.unique(sorted_users, return_index=True)
    bounds = np.append(starts[1:], sorted_users.size)
    return {
        int(u): set(sorted_items[s:e].tolist())
        for u, s, e in zip(uniq.tolist(), starts.tolist(), bounds.tolist())
    }


def score_buffer_rows(num_items: int, floor: int = 64, cap: int | None = None) -> int:
    """Rows per batch-predict slice so the host [rows, items] score buffer
    stays ~200 MB f32 regardless of catalog size (a fixed row count would
    scale memory with num_items). One definition for every template's
    batch path."""
    rows = max(floor, 50_000_000 // max(num_items, 1))
    return min(rows, cap) if cap else rows


def partition_user_queries(user_index: dict[str, int], queries):
    """Split (qid, query) pairs into known-user rows [(qid, q, user_idx)]
    and fallback pairs [(qid, q)] -- the shared head of every template's
    batch_predict."""
    user_rows, fallback = [], []
    for qid, q in queries:
        user_idx = (
            user_index.get(str(q["user"]))
            if isinstance(q, dict) and "user" in q
            else None
        )
        if user_idx is None:
            fallback.append((qid, q))
        else:
            user_rows.append((qid, q, user_idx))
    return user_rows, fallback


class Shortlist:
    """Compact view of one request's score vector: the stage-2 contract
    of the two-stage MIPS retrieval path (``ops/mips``).

    ``indices`` are ascending catalog indices, ``scores`` their EXACT f32
    re-ranked scores (writable copy -- the seen/blackList filters write
    -inf through ``__setitem__``). The ascending order is load-bearing:
    ``topk_order``'s stable sort over the compact array then breaks score
    ties by global catalog index, byte-matching the full scan whenever
    the shortlist contains the true top-k. Items outside the shortlist
    silently absorb filter writes (they were not going to be served) and
    never appear in responses.
    """

    __slots__ = ("indices", "scores", "num_items")

    def __init__(self, indices: np.ndarray, scores: np.ndarray, num_items: int):
        self.indices = np.asarray(indices)
        self.scores = np.array(scores)  # writable copy: filters mutate it
        self.num_items = num_items

    @property
    def shape(self) -> tuple:
        """Mimics the dense score vector so mask-building code
        (``scores.shape[0]``) is retrieval-mode agnostic."""
        return (self.num_items,)

    def __setitem__(self, idx: int, value) -> None:
        pos = int(np.searchsorted(self.indices, idx))
        if pos < self.indices.size and self.indices[pos] == idx:
            self.scores[pos] = value

    def where_allowed(self, allowed: np.ndarray, sentinel=-np.inf) -> "Shortlist":
        """Apply a dense [num_items] bool mask (whiteList/categories)
        compactly: O(shortlist), never materializing dense scores.

        ``indices`` may carry ``num_items`` sentinels (search padding,
        guaranteed on catalogs smaller than the candidate budget) which
        are out of range for the dense mask -- they clamp to a valid row
        for the gather and always mask to ``sentinel``."""
        valid = self.indices < self.num_items
        safe = np.minimum(self.indices, max(self.num_items - 1, 0))
        self.scores = np.where(valid & allowed[safe], self.scores, sentinel)
        return self

    def copy(self) -> "Shortlist":
        return Shortlist(self.indices, self.scores, self.num_items)


def resolve_retrieval(params):
    """Parse the algorithm-params ``"retrieval"`` block into a
    ``RetrievalConfig`` (raising on unknown modes/knobs -- validated at
    train time so a typo fails the build, not the first query)."""
    from predictionio_tpu.ops.mips import RetrievalConfig

    return RetrievalConfig.from_params(params.get_or("retrieval", None))


def retrieval_index(als_model: ALSModel, retrieval, kind: str = "dot"):
    """The lazily-built, model-cached device ``RetrievalIndex`` for mips
    mode, or None for scan mode (callers fall through to the host
    matmul). ``kind="cosine"`` indexes the norm-normalized item factors
    so similar-items queries run as MIPS over unit vectors (sum of anchor
    cosines == dot with the summed normalized anchors). The cache lives
    on the model object (the ``_item_norms`` precedent) and never
    pickles; fold-in publishes a NEW ALSModel, so swapped factor tables
    can never serve a stale index."""
    if retrieval is None or retrieval.mode != "mips":
        return None
    from predictionio_tpu.ops.mips import RetrievalIndex

    cache = getattr(als_model, "_retrieval_cache", None)
    if cache is None:
        cache = {}
        als_model._retrieval_cache = cache
    key = (kind, retrieval)
    index = cache.get(key)
    if index is None:
        if kind == "cosine":
            norms = np.maximum(als_model.item_norms, 1e-12)
            table = als_model.item_factors / norms[:, None]
        else:
            table = als_model.item_factors
        index = RetrievalIndex(table, retrieval)
        cache[key] = index
    return index


def score_known_user(als_model: ALSModel, user_idx: int, retrieval=None):
    """One user's item scores: the dense vector (scan) or the stage-2
    ``Shortlist`` (mips). The unbatched predict path and
    ``batch_score_known_users`` both route through the same index, so
    batched and unbatched responses rank identically in either mode.

    Mips re-ranks on the HOST: the device search picks the shortlist, but
    the response scores come from the same gathered-row matvec the scan
    path runs (``score_items_for_user``'s einsum, whose per-row reduction
    is height-independent), so they are bitwise the full product at those
    rows -- a shortlist that contains the true top-k yields a
    byte-identical response, ULP ties included."""
    index = retrieval_index(als_model, retrieval)
    if index is None:
        return als_model.score_items_for_user(user_idx)
    idx, _ = index.search(als_model.user_factors[user_idx][None, :])
    return _host_rerank(als_model, idx[0], user_idx)


def _host_rerank(als_model: ALSModel, short: np.ndarray, user_idx: int) -> "Shortlist":
    """Exact scores for one user's shortlist, as the scan path computes
    them: a gathered-row f32 matvec, bitwise equal to
    ``score_items_for_user`` at the shortlisted rows. Sentinel slots
    (index == num_items, search padding) stay -inf and drop in the
    format tail."""
    num_items = als_model.item_factors.shape[0]
    in_range = short < num_items
    vals = np.einsum(
        "ik,k->i",
        als_model.item_factors[short[in_range]],
        als_model.user_factors[user_idx],
    )
    scores = np.full(short.shape, -np.inf, vals.dtype)
    scores[in_range] = vals
    return Shortlist(short, scores, num_items)


def similar_item_scores(als_model: ALSModel, anchors: list[int], retrieval=None):
    """Summed cosine similarity of all items against the anchors: dense
    (scan) or a ``Shortlist`` through the cosine index (mips), where the
    stage-1 query is the sum of the anchors' unit vectors -- the same
    ranking objective, one packed-table scan instead of one dense pass
    per anchor. The shortlist then re-ranks on the host by replaying the
    scan path's per-anchor arithmetic (``similar_items`` gathered to the
    shortlist rows, summed in anchor order), so the response is bitwise
    the scan response whenever the shortlist holds the true top-k."""
    index = retrieval_index(als_model, retrieval, kind="cosine")
    if index is None:
        sims = None
        for idx in anchors:
            s = als_model.similar_items(idx)
            sims = s if sims is None else sims + s
        return sims
    norms = np.maximum(als_model.item_norms[anchors], 1e-12)
    query = (als_model.item_factors[anchors] / norms[:, None]).sum(axis=0)
    idx, _ = index.search(query[None, :])
    short = idx[0]
    num_items = als_model.item_factors.shape[0]
    in_range = short < num_items
    rows = short[in_range]
    sims = None
    for a in anchors:
        v = als_model.item_factors[a]
        row_norms = als_model.item_norms[rows] * (als_model.item_norms[a] + 1e-12)
        s = np.einsum("ik,k->i", als_model.item_factors[rows], v) / np.maximum(
            row_norms, 1e-12
        )
        sims = s if sims is None else sims + s
    scores = np.full(short.shape, -np.inf, sims.dtype if sims is not None else np.float32)
    if sims is not None:
        scores[in_range] = sims
    return Shortlist(short, scores, num_items)


def batch_score_known_users(
    als_model: ALSModel, user_rows, respond, *, retrieval=None
) -> list:
    """Score known users in bounded slices over the host-cached factors;
    ``respond(scores_row, qid, query, user_idx)`` builds each response.
    One definition for every ALS-factor batch path.

    Scan mode materializes [rows, items] f32 matmul slices; mips mode
    (``retrieval: {"mode": "mips"}``) runs the device-resident two-stage
    kernel and hands ``respond`` a ``Shortlist`` per row -- peak host
    score memory drops from O(items) to O(shortlist) per row, which is
    what lifts the catalog cap (ISSUE 16 / ALX arxiv 2112.02194).
    """
    out = []
    index = retrieval_index(als_model, retrieval)
    if index is not None:
        # the buffer is [rows, shortlist] now; budget rows against it
        rows_per_slice = score_buffer_rows(index.config.shortlist)
        for start in range(0, len(user_rows), rows_per_slice):
            part = user_rows[start : start + rows_per_slice]
            idxs = np.fromiter((u for _, _, u in part), dtype=np.int64)
            short_idx, _ = index.search(als_model.user_factors[idxs])
            # host re-rank per row with the single-query matvec shape:
            # batched mips responses stay bitwise equal to unbatched ones
            # (scan's batched sgemm drifts a ULP from its own sgemv path)
            out.extend(
                respond(
                    _host_rerank(als_model, short_idx[row], user_idx),
                    qid, q, user_idx,
                )
                for row, (qid, q, user_idx) in enumerate(part)
            )
        return out
    rows_per_slice = score_buffer_rows(als_model.item_factors.shape[0])
    for start in range(0, len(user_rows), rows_per_slice):
        part = user_rows[start : start + rows_per_slice]
        idxs = np.fromiter((u for _, _, u in part), dtype=np.int64)
        # einsum, not sgemm: BLAS results depend on matrix shape, so the
        # batched product would sit a ULP off ``score_items_for_user`` and
        # off the mips host re-rank -- einsum's per-row reduction makes
        # every scoring path (scan/mips, batched/unbatched) bitwise equal,
        # at ~2x sgemm for the k=16 contraction on scan-sized catalogs
        scores = np.einsum(
            "bk,ik->bi", als_model.user_factors[idxs], als_model.item_factors
        )
        out.extend(
            respond(scores[row], qid, q, user_idx)
            for row, (qid, q, user_idx) in enumerate(part)
        )
    return out


def topk_order(scores: np.ndarray, num: int) -> np.ndarray:
    """Indices of the top-``num`` scores, descending, ties by ascending
    position -- a pure function of the (score, position) multiset.

    Selection is O(items) argpartition + O(num log num) sort instead of a
    full O(items log items) argsort: this runs once PER REQUEST on the
    serving hot path, and at large catalogs it is what the batched
    scorer's amortized matmul would otherwise hide behind. The canonical
    tie order matters beyond aesthetics: argpartition permutes its input
    arbitrarily, so "stable sort of the partitioned slice" would order
    equal scores differently for a dense vector than for a mips
    ``Shortlist`` holding the same values -- threshold ties are therefore
    re-selected by position explicitly. NaN/-inf sentinels rank after
    every finite score. ONE definition for every template's ranking
    tail -- batched, unbatched, scan, and mips responses must tie-break
    identically.
    """
    n = scores.shape[0]
    if 0 < num < n:
        cand = np.argpartition(-scores, num - 1)[:num]
        vals = scores[cand]
        if not np.isnan(vals).any():
            t = vals.min()
            head = np.flatnonzero(scores > t)
            # lowest positions among scores == t fill the remaining slots
            ties = np.flatnonzero(scores == t)[: num - head.size]
            cand = np.concatenate([head, ties])
            return cand[np.lexsort((cand, -scores[cand]))]
        # NaN reached the top slice: fall through to the full stable sort
        # (argsort ranks NaN last; ascending-position ties come free)
    return np.argsort(-scores, kind="stable")[:num]


def topk_item_scores(item_ids: list[str], scores, num: int) -> dict:
    """Rank + format tail shared by every template response: descending
    top-``num``, excluded entries carried as -inf and dropped here. A
    ``Shortlist`` ranks over its compact arrays (same ``topk_order``, so
    mips- and scan-mode responses tie-break identically whenever the
    shortlist holds the true top-k); the finite mask is one vectorized
    pass over the top-k slice, not a per-item ``np.isfinite`` call."""
    if isinstance(scores, Shortlist):
        order = topk_order(scores.scores, num)
        finite = np.isfinite(scores.scores[order])
        return {
            "itemScores": [
                {"item": item_ids[int(scores.indices[j])],
                 "score": float(scores.scores[j])}
                for j, ok in zip(order, finite)
                if ok
            ]
        }
    order = topk_order(scores, num)
    finite = np.isfinite(scores[order])
    return {
        "itemScores": [
            {"item": item_ids[j], "score": float(scores[j])}
            for j, ok in zip(order, finite)
            if ok
        ]
    }


def _vocab_hash(ids: list[str]) -> str:
    h = hashlib.sha256()
    for s in ids:
        h.update(s.encode())
        h.update(b"\x00")
    return h.hexdigest()[:16]


def fit_with_checkpoint(
    ctx,
    als_data,
    config: ALSConfig,
    mesh,
    *,
    user_ids: list[str],
    item_ids: list[str],
    interval: int,
    name: str = "als",
) -> ALSModel:
    """``als_fit`` wrapped in fingerprinted step checkpoints.

    Checkpointed factors are only meaningful against the id vocabularies
    they were trained on. Events ingested between crash and resume change
    num_users/num_items -- restoring would crash on shape mismatch or
    silently misalign factor rows with the new vocabulary. Counts alone
    are not enough (delete one user + add another keeps the count but
    renumbers rows), so the vocabularies themselves are hashed too. A
    mismatch discards the checkpoints and trains fresh with a warning.

    ``interval`` <= 0 disables checkpointing entirely.

    With ``pio train --profile`` (runtime conf ``pio.profile``) a per-step
    telemetry journal (``<profile-dir>/<name>-telemetry.jsonl``: wall
    time, edges/sec, achieved GB/s against the bytes-moved model,
    recompile count) is written alongside the ``jax.profiler`` trace the
    workflow captures -- the cheap always-parseable view vs the deep one.
    """
    config = resolve_factor_sharding(config, mesh)
    config = resolve_solver_override(config, ctx)
    telemetry = _build_telemetry(ctx, als_data, config, mesh, name)
    checkpoint = ctx.checkpoint_manager(name) if interval > 0 else None
    init, start_iteration, callback = None, 0, None
    if checkpoint is not None:
        num_users, num_items = len(user_ids), len(item_ids)
        fingerprint = {
            "num_users": num_users,
            "num_items": num_items,
            "user_vocab": _vocab_hash(user_ids),
            "item_vocab": _vocab_hash(item_ids),
            "rank": config.rank,
        }
        latest = checkpoint.latest_step()
        if latest is not None:  # only a --resume run can see a step here
            meta = checkpoint.read_meta()
            if meta != fingerprint:
                logger.warning(
                    "%s checkpoint fingerprint %s does not match current"
                    " dataset %s (events changed between crash and resume?);"
                    " discarding checkpoints and training fresh",
                    name,
                    meta,
                    fingerprint,
                )
                checkpoint.reset()
            else:
                state = checkpoint.restore(
                    {
                        "users": np.zeros((num_users, config.rank), np.float32),
                        "items": np.zeros((num_items, config.rank), np.float32),
                        "iteration": 0,
                    }
                )
                init = (state["users"], state["items"])
                start_iteration = int(state["iteration"]) + 1
        checkpoint.write_meta(fingerprint)

        def callback(it, users_np, items_np):
            checkpoint.save(
                it, {"users": users_np, "items": items_np, "iteration": it}
            )

    from predictionio_tpu.obs.trace import global_tracer
    from predictionio_tpu.parallel.stream import StreamedALSData

    # alsFeed "streamed": the preparator handed a disk block store, not
    # resident edge arrays -- train through ALX device-resident epochs.
    # Same checkpoints, same callback contract, bit-identical factors at
    # equal shapes (als_fit_streamed's own invariant).
    fit = (
        als_fit_streamed if isinstance(als_data, StreamedALSData) else als_fit
    )
    try:
        with global_tracer().span(
            "als.fit", attrs={"name": name, "iterations": config.iterations}
        ):
            model = fit(
                als_data,
                config,
                mesh,
                callback=callback,
                callback_interval=interval,
                init=init,
                start_iteration=start_iteration,
                telemetry=telemetry,
            )
    finally:
        if telemetry is not None:
            telemetry.close()
    if checkpoint is not None:
        checkpoint.close()
    return model


def _build_telemetry(ctx, als_data, config: ALSConfig, mesh, name: str):
    """A ``TrainTelemetry`` journal when the run is profiled
    (``pio.profile`` runtime conf), else None (the un-profiled loop must
    not pay per-step device syncs)."""
    import os

    profile_dir = (getattr(ctx, "runtime_conf", None) or {}).get("pio.profile")
    if not profile_dir:
        return None
    try:
        from predictionio_tpu.obs.telemetry import TrainTelemetry
        from predictionio_tpu.parallel.als import (
            modeled_bytes_per_iteration,
            real_edges,
            resolve_solver,
        )

        try:
            platform = mesh.devices.flat[0].platform if mesh is not None else "cpu"
        except Exception:
            platform = "cpu"
        solver = resolve_solver(config.solver, platform)
        itemsize = 2 if config.dtype == "bfloat16" else 4
        return TrainTelemetry(
            os.path.join(str(profile_dir), f"{name}-telemetry.jsonl"),
            edges=real_edges(als_data),
            modeled_bytes_per_iter=modeled_bytes_per_iteration(
                als_data, config.rank, itemsize, fused=solver == "pallas"
            ),
            meta={
                "name": name,
                "rank": config.rank,
                "solver": solver,
                "platform": platform,
                "dtype": config.dtype,
                "iterations": config.iterations,
            },
        )
    except Exception:
        # telemetry must never fail a training run
        logger.warning("profile telemetry setup failed", exc_info=True)
        return None
