"""Shared pieces of the ALS-backed templates (recommendation, e-commerce).

One source of truth for the behaviors both ALS templates must agree on:
mesh-aware CSR packing, the fingerprinted step-checkpoint wiring
(preemption safety, SURVEY §5.4), the seen-items map, and the rank+format
tail of their ``itemScores`` responses (predict and the vectorized batch
path must rank identically). The cooccurrence-based templates keep their
own tails: their exclusion sentinel is 0, not -inf.
"""

from __future__ import annotations

import hashlib
import logging

import numpy as np

from predictionio_tpu.parallel.als import ALSConfig, ALSModel, als_fit, build_als_data

logger = logging.getLogger("pio.als")


def prepare_als_data(
    ctx,
    params,
    users: np.ndarray,
    items: np.ndarray,
    values: np.ndarray,
    num_users: int,
    num_items: int,
    times: np.ndarray,
):
    """Pack COO interactions into padded CSR blocks sized for ctx's mesh.

    Rows pad to multiples of 8 * data_axis * model_axis: a model axis of
    1 (the default mesh) reproduces the historical layout, and a model
    axis > 1 makes the blocks ready for the ALX factor-sharded mode the
    fit side auto-selects on such meshes (resolve_factor_sharding).
    """
    config = ALSConfig(
        max_len=params.get_or("maxEventsPerUser", None),
        # length-bucketed packing: engine.json "buckets" (default 1 keeps
        # the single-block layout; the ML-20M bench uses 4)
        buckets=params.get_or("buckets", 1),
    )
    num_shards, model_shards = 1, 1
    try:
        num_shards = ctx.mesh.shape.get("data", 1)
        model_shards = ctx.mesh.shape.get("model", 1)
    except Exception:
        pass  # no devices available (pure-host tests)
    return build_als_data(
        users,
        items,
        values,
        num_users,
        num_items,
        config,
        times=times,
        num_shards=num_shards,
        model_shards=model_shards,
    )


#: packing knobs the PREPARATOR consumes; a natural mistake is putting
#: them in the algorithm block (the reference template had no preparator
#: params), where they would be silently ignored
PACKING_PARAM_KEYS = ("maxEventsPerUser", "buckets")


def warn_misplaced_packing_params(algo_params, template: str) -> None:
    misplaced = [
        k for k in PACKING_PARAM_KEYS
        if algo_params.get_or(k, None) is not None
    ]
    if misplaced:
        logger.warning(
            "%s: %s configure the PREPARATOR (put them under "
            '"preparator": {"params": {...}} in engine.json); they are '
            "ignored in the algorithm block",
            template, ", ".join(misplaced),
        )


def resolve_solver_override(config: ALSConfig, ctx) -> ALSConfig:
    """Apply the run-scoped ``pio.als_solver`` conf (``pio train
    --als-solver``) over the engine.json ``alsSolver`` param.

    The CLI flag is an operator override -- benchmarking the fused Pallas
    half-step against the XLA einsum path, or pinning "xla" if a jax/Mosaic
    upgrade regresses the kernel -- so it wins over the variant file.
    ``make_iteration`` validates the value.
    """
    import dataclasses

    solver = getattr(ctx, "runtime_conf", None) or {}
    solver = solver.get("pio.als_solver")
    if not solver:
        return config
    return dataclasses.replace(config, solver=str(solver))


def resolve_factor_sharding(config: ALSConfig, mesh) -> ALSConfig:
    """Resolve ``factor_sharding="auto"`` against the actual mesh.

    On a pure-ALS template a model axis > 1 has exactly one use -- ALX
    factor sharding -- so "auto" (the template default) selects it
    whenever ``pio.mesh_shape`` configures such an axis, and plain data
    parallelism otherwise. Explicit "replicated"/"model" pass through to
    the library untouched (als_fit validates them).
    """
    import dataclasses

    if config.factor_sharding != "auto":
        return config
    try:
        model = mesh.shape.get("model", 1) if mesh is not None else 1
    except Exception:
        model = 1
    return dataclasses.replace(
        config, factor_sharding="model" if model > 1 else "replicated"
    )


def build_seen(users: np.ndarray, items: np.ndarray) -> dict[int, set[int]]:
    """user index -> set of interacted item indices (serving-time filter)."""
    seen: dict[int, set[int]] = {}
    for u, i in zip(users, items):
        seen.setdefault(int(u), set()).add(int(i))
    return seen


def score_buffer_rows(num_items: int, floor: int = 64, cap: int | None = None) -> int:
    """Rows per batch-predict slice so the host [rows, items] score buffer
    stays ~200 MB f32 regardless of catalog size (a fixed row count would
    scale memory with num_items). One definition for every template's
    batch path."""
    rows = max(floor, 50_000_000 // max(num_items, 1))
    return min(rows, cap) if cap else rows


def partition_user_queries(user_index: dict[str, int], queries):
    """Split (qid, query) pairs into known-user rows [(qid, q, user_idx)]
    and fallback pairs [(qid, q)] -- the shared head of every template's
    batch_predict."""
    user_rows, fallback = [], []
    for qid, q in queries:
        user_idx = (
            user_index.get(str(q["user"]))
            if isinstance(q, dict) and "user" in q
            else None
        )
        if user_idx is None:
            fallback.append((qid, q))
        else:
            user_rows.append((qid, q, user_idx))
    return user_rows, fallback


def batch_score_known_users(als_model: ALSModel, user_rows, respond) -> list:
    """Score known users in bounded [rows, items] matmul slices over the
    host-cached factors; ``respond(scores_row, qid, query, user_idx)``
    builds each response. One definition for every ALS-factor batch path.
    """
    out = []
    rows_per_slice = score_buffer_rows(als_model.item_factors.shape[0])
    for start in range(0, len(user_rows), rows_per_slice):
        part = user_rows[start : start + rows_per_slice]
        idxs = np.fromiter((u for _, _, u in part), dtype=np.int64)
        scores = als_model.user_factors[idxs] @ als_model.item_factors.T
        out.extend(
            respond(scores[row], qid, q, user_idx)
            for row, (qid, q, user_idx) in enumerate(part)
        )
    return out


def topk_order(scores: np.ndarray, num: int) -> np.ndarray:
    """Indices of the top-``num`` scores, descending (stable tie order).

    Selection is O(items) argpartition + O(num log num) sort instead of a
    full O(items log items) argsort: this runs once PER REQUEST on the
    serving hot path, and at large catalogs it is what the batched
    scorer's amortized matmul would otherwise hide behind. NaN/-inf
    sentinels partition to the tail exactly as they sort. ONE definition
    for every template's ranking tail -- batched and unbatched responses
    must tie-break identically.
    """
    n = scores.shape[0]
    if 0 < num < n:
        cand = np.argpartition(-scores, num - 1)[:num]
        return cand[np.argsort(-scores[cand], kind="stable")]
    return np.argsort(-scores, kind="stable")[:num]


def topk_item_scores(item_ids: list[str], scores: np.ndarray, num: int) -> dict:
    """Rank + format tail shared by every template response: descending
    top-``num``, excluded entries carried as -inf and dropped here."""
    return {
        "itemScores": [
            {"item": item_ids[j], "score": float(scores[j])}
            for j in topk_order(scores, num)
            if np.isfinite(scores[j])
        ]
    }


def _vocab_hash(ids: list[str]) -> str:
    h = hashlib.sha256()
    for s in ids:
        h.update(s.encode())
        h.update(b"\x00")
    return h.hexdigest()[:16]


def fit_with_checkpoint(
    ctx,
    als_data,
    config: ALSConfig,
    mesh,
    *,
    user_ids: list[str],
    item_ids: list[str],
    interval: int,
    name: str = "als",
) -> ALSModel:
    """``als_fit`` wrapped in fingerprinted step checkpoints.

    Checkpointed factors are only meaningful against the id vocabularies
    they were trained on. Events ingested between crash and resume change
    num_users/num_items -- restoring would crash on shape mismatch or
    silently misalign factor rows with the new vocabulary. Counts alone
    are not enough (delete one user + add another keeps the count but
    renumbers rows), so the vocabularies themselves are hashed too. A
    mismatch discards the checkpoints and trains fresh with a warning.

    ``interval`` <= 0 disables checkpointing entirely.

    With ``pio train --profile`` (runtime conf ``pio.profile``) a per-step
    telemetry journal (``<profile-dir>/<name>-telemetry.jsonl``: wall
    time, edges/sec, achieved GB/s against the bytes-moved model,
    recompile count) is written alongside the ``jax.profiler`` trace the
    workflow captures -- the cheap always-parseable view vs the deep one.
    """
    config = resolve_factor_sharding(config, mesh)
    config = resolve_solver_override(config, ctx)
    telemetry = _build_telemetry(ctx, als_data, config, mesh, name)
    checkpoint = ctx.checkpoint_manager(name) if interval > 0 else None
    init, start_iteration, callback = None, 0, None
    if checkpoint is not None:
        num_users, num_items = len(user_ids), len(item_ids)
        fingerprint = {
            "num_users": num_users,
            "num_items": num_items,
            "user_vocab": _vocab_hash(user_ids),
            "item_vocab": _vocab_hash(item_ids),
            "rank": config.rank,
        }
        latest = checkpoint.latest_step()
        if latest is not None:  # only a --resume run can see a step here
            meta = checkpoint.read_meta()
            if meta != fingerprint:
                logger.warning(
                    "%s checkpoint fingerprint %s does not match current"
                    " dataset %s (events changed between crash and resume?);"
                    " discarding checkpoints and training fresh",
                    name,
                    meta,
                    fingerprint,
                )
                checkpoint.reset()
            else:
                state = checkpoint.restore(
                    {
                        "users": np.zeros((num_users, config.rank), np.float32),
                        "items": np.zeros((num_items, config.rank), np.float32),
                        "iteration": 0,
                    }
                )
                init = (state["users"], state["items"])
                start_iteration = int(state["iteration"]) + 1
        checkpoint.write_meta(fingerprint)

        def callback(it, users_np, items_np):
            checkpoint.save(
                it, {"users": users_np, "items": items_np, "iteration": it}
            )

    from predictionio_tpu.obs.trace import global_tracer

    try:
        with global_tracer().span(
            "als.fit", attrs={"name": name, "iterations": config.iterations}
        ):
            model = als_fit(
                als_data,
                config,
                mesh,
                callback=callback,
                callback_interval=interval,
                init=init,
                start_iteration=start_iteration,
                telemetry=telemetry,
            )
    finally:
        if telemetry is not None:
            telemetry.close()
    if checkpoint is not None:
        checkpoint.close()
    return model


def _build_telemetry(ctx, als_data, config: ALSConfig, mesh, name: str):
    """A ``TrainTelemetry`` journal when the run is profiled
    (``pio.profile`` runtime conf), else None (the un-profiled loop must
    not pay per-step device syncs)."""
    import os

    profile_dir = (getattr(ctx, "runtime_conf", None) or {}).get("pio.profile")
    if not profile_dir:
        return None
    try:
        from predictionio_tpu.obs.telemetry import TrainTelemetry
        from predictionio_tpu.parallel.als import (
            modeled_bytes_per_iteration,
            real_edges,
            resolve_solver,
        )

        try:
            platform = mesh.devices.flat[0].platform if mesh is not None else "cpu"
        except Exception:
            platform = "cpu"
        solver = resolve_solver(config.solver, platform)
        itemsize = 2 if config.dtype == "bfloat16" else 4
        return TrainTelemetry(
            os.path.join(str(profile_dir), f"{name}-telemetry.jsonl"),
            edges=real_edges(als_data),
            modeled_bytes_per_iter=modeled_bytes_per_iteration(
                als_data, config.rank, itemsize, fused=solver == "pallas"
            ),
            meta={
                "name": name,
                "rank": config.rank,
                "solver": solver,
                "platform": platform,
                "dtype": config.dtype,
                "iterations": config.iterations,
            },
        )
    except Exception:
        # telemetry must never fail a training run
        logger.warning("profile telemetry setup failed", exc_info=True)
        return None
