"""DASE components of the Universal Recommender template.

Query contract: ``{"user": "u1", "num": 4, "blackList": [...],
"fields": [{"name": "category", "values": ["books"], "bias": -1}]}``
-> ``{"itemScores": [...]}``. ``bias < 0`` filters, ``bias >= 0``
multiplies matching items' scores (UR business-rule semantics).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from predictionio_tpu.controller import (
    DataSource,
    Engine,
    EvalInfo,
    FirstServing,
    IdentityPreparator,
    TPUAlgorithm,
)
from predictionio_tpu.controller.base import SanityCheck
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.ops.cooccurrence import cooccurrence_indicators
from predictionio_tpu.ops.ragged import pack_padded_csr

import logging

from predictionio_tpu.models._als_common import topk_order
from predictionio_tpu.models._streaming import (
    StreamingHandle,
    live_target_events,
    streaming_handle_or_none,
)

logger = logging.getLogger("pio.universal")


@dataclass
class MultiEventData(SanityCheck):
    """Per-event-type COO interactions over one shared user/item universe."""

    event_names: list[str]                      # [0] is primary
    per_event: dict[str, tuple[np.ndarray, np.ndarray, np.ndarray]]  # (u, i, t)
    user_ids: list[str]
    item_ids: list[str]
    item_properties: dict[str, dict]            # item id -> properties

    def sanity_check(self) -> None:
        primary = self.event_names[0]
        if primary not in self.per_event or self.per_event[primary][0].size == 0:
            raise ValueError(f"no events of primary type {primary!r} found")


class URDataSource(DataSource):
    """Params: appName, eventNames (primary first; default ["buy", "view"]);
    ``"reader": "streaming"`` trains every event type's cross-occurrence
    through the retention-bounded sharded reader over one shared entity
    universe, and serves user queries from live event-store reads."""

    def _read(self) -> MultiEventData:
        event_names = self.params.get_or("eventNames", ["buy", "view"])
        events = PEventStore.find(
            self.params.appName,
            event_names=event_names,
            target_entity_type="item",
        )
        user_index: dict[str, int] = {}
        item_index: dict[str, int] = {}
        raw: dict[str, list[tuple[int, int, float]]] = {n: [] for n in event_names}
        for e in events:
            if e.target_entity_id is None:
                continue
            u = user_index.setdefault(e.entity_id, len(user_index))
            i = item_index.setdefault(e.target_entity_id, len(item_index))
            raw[e.event].append((u, i, e.event_time.timestamp()))
        per_event = {}
        for name, triples in raw.items():
            if triples:
                arr = np.array(triples, dtype=np.float64)
                per_event[name] = (
                    arr[:, 0].astype(np.int64),
                    arr[:, 1].astype(np.int64),
                    arr[:, 2],
                )
            else:
                per_event[name] = (
                    np.zeros(0, np.int64),
                    np.zeros(0, np.int64),
                    np.zeros(0, np.float64),
                )
        item_props = {
            iid: pm.to_dict()
            for iid, pm in PEventStore.aggregate_properties(
                self.params.appName, entity_type="item"
            ).items()
        }
        return MultiEventData(
            event_names=list(event_names),
            per_event=per_event,
            user_ids=list(user_index),
            item_ids=list(item_index),
            item_properties=item_props,
        )

    def read_training(self, ctx):
        handle = streaming_handle_or_none(
            self.params, ["buy", "view"], probe_primary_only=True
        )
        if handle is not None:
            handle.empty_message = (
                f"no events of primary type {handle.event_names[0]!r} found"
            )
        return handle if handle is not None else self._read()

    def read_eval(self, ctx):
        """Hold out each user's most recent PRIMARY interaction."""
        data = self._read()
        data.sanity_check()  # empty store: fail with the real message, not IndexError
        primary = data.event_names[0]
        u, i, t = data.per_event[primary]
        order = np.lexsort((t, u))
        u, i, t = u[order], i[order], t[order]
        last = np.r_[u[1:] != u[:-1], True]
        held = {int(uu): int(ii) for uu, ii, l in zip(u, i, last) if l}
        train = MultiEventData(
            event_names=data.event_names,
            per_event={
                **data.per_event,
                primary: (u[~last], i[~last], t[~last]),
            },
            user_ids=data.user_ids,
            item_ids=data.item_ids,
            item_properties=data.item_properties,
        )
        pairs = [
            (
                {"user": data.user_ids[uu], "num": self.params.get_or("evalK", 10)},
                [data.item_ids[ii]],
            )
            for uu, ii in held.items()
        ]
        return [(train, EvalInfo(fold=0), pairs)]


@dataclass
class URModel:
    event_names: list[str]
    item_ids: list[str]
    item_index: dict[str, int]
    #: per event type: reverse indicator index -- history item j ->
    #: [(primary item p, weight)] (inverted from the per-p top-k table so a
    #: query costs O(history * hits), not O(history * items * k))
    indicators: dict[str, dict[int, list[tuple[int, float]]]]
    #: user id -> {event type -> [item indices]}
    user_history: dict[str, dict[str, list[int]]]
    item_properties: dict[str, dict]
    #: "model": the trained-in map above; "live": per-query event-store
    #: read (O(entities) serving -- the streaming reader's contract, and
    #: fresh events enter the history without retrain). Old pickles
    #: predate these fields; readers use getattr defaults.
    history_mode: str = "model"
    app_name: str = ""
    channel_name: str = None


def _invert_indicators(
    idx: np.ndarray, vals: np.ndarray
) -> dict[int, list[tuple[int, float]]]:
    inverted: dict[int, list[tuple[int, float]]] = {}
    for p in range(idx.shape[0]):
        for j, v in zip(idx[p], vals[p]):
            if v > 0:
                inverted.setdefault(int(j), []).append((p, float(v)))
    return inverted


def _user_history(model: "URModel", user: str) -> dict[str, list[int]]:
    """{event type -> [item indices]} for the query user.

    Live mode reads the event store per request (the streaming reader's
    serving contract); a store error degrades to an empty history rather
    than a 500.
    """
    if getattr(model, "history_mode", "model") != "live":
        return dict(model.user_history.get(user, {}))
    out: dict[str, list[int]] = {}
    for e in live_target_events(model, user):
        j = model.item_index.get(e.target_entity_id)
        if j is not None:
            out.setdefault(e.event, []).append(j)
    return out


class URAlgorithm(TPUAlgorithm):
    """Params: topK (indicators per anchor, default 50), maxEventsPerUser,
    chunk."""

    def train(self, ctx, data) -> URModel:
        max_len = self.params.get_or("maxEventsPerUser", None)
        chunk = self.params.get_or("chunk", 4096)
        top_k = self.params.get_or("topK", 50)
        mesh = self.mesh_or_none(ctx)  # user rows dp-sharded, psum acc
        streamed = isinstance(data, StreamingHandle)
        if streamed:
            return self._train_streaming(ctx, data, max_len, chunk, top_k, mesh)
        n_users, n_items = len(data.user_ids), len(data.item_ids)

        def to_csr(triples):
            uu, ii, tt = triples
            return pack_padded_csr(
                uu, ii, np.ones(uu.size, np.float32), n_users, n_items,
                times=tt, max_len=max_len,
            )

        from predictionio_tpu.ops.cooccurrence import distinct_user_counts

        primary_csr = to_csr(data.per_event[data.event_names[0]])
        # diagonals are distinct-user counts: O(nnz) on host, no extra matmuls
        primary_counts = distinct_user_counts(primary_csr)
        indicators: dict[str, tuple[np.ndarray, np.ndarray]] = {}
        for name in data.event_names:
            if data.per_event[name][0].size == 0:
                continue
            is_primary = name == data.event_names[0]
            csr = primary_csr if is_primary else to_csr(data.per_event[name])
            col_counts = (
                primary_counts if is_primary else distinct_user_counts(csr)
            )
            # fused on-device cooc -> LLR -> top-k: only the [items, topK]
            # indicators leave the device, never the [items, items] matrix
            indicators[name] = _invert_indicators(
                *cooccurrence_indicators(
                    primary_csr,
                    None if is_primary else csr,
                    top_k=top_k,
                    llr_row_totals=primary_counts,
                    llr_col_totals=col_counts,
                    total=n_users,
                    drop_diagonal=is_primary,
                    chunk=chunk,
                    mesh=mesh,
                )
            )
        history: dict[str, dict[str, list[int]]] = {}
        for name in data.event_names:
            uu, ii, _ = data.per_event[name]
            for u, i in zip(uu, ii):
                history.setdefault(data.user_ids[int(u)], {}).setdefault(
                    name, []
                ).append(int(i))
        return URModel(
            event_names=data.event_names,
            item_ids=data.item_ids,
            item_index={iid: j for j, iid in enumerate(data.item_ids)},
            indicators=indicators,
            user_history=history,
            item_properties=data.item_properties,
        )

    def _train_streaming(self, ctx, src, max_len, chunk, top_k, mesh) -> URModel:
        """Every event type's CSR through the sharded reader over ONE
        shared entity universe (store_multi_event_chunks' shared
        encoders); indicators come out bit-identical to the materialized
        path. Costs 1 + 2 * len(event_names) scans -- bounded memory is
        the trade."""
        from predictionio_tpu.data.store import PEventStore
        from predictionio_tpu.models._streaming import (
            streaming_multi_event_sources,
        )
        from predictionio_tpu.parallel.mesh import local_mesh
        from predictionio_tpu.parallel.reader import (
            build_cooc_csr_sharded,
            distinct_user_counts_sharded,
            universe_pass,
        )

        mesh = mesh or local_mesh(1, 1)
        sources, users_enc, items_enc, universe_ready = (
            streaming_multi_event_sources(
                src, runtime_conf=getattr(ctx, "runtime_conf", None)
            )
        )
        if not universe_ready:
            # fix the shared universe before any build (snapshot replay
            # comes back with the encoders already complete)
            universe_pass(sources)
        n_users, n_items = len(users_enc.ids), len(items_enc.ids)

        primary = src.event_names[0]
        primary_csr = build_cooc_csr_sharded(
            sources[primary], n_users, n_items, mesh,
            max_len=max_len, chunk=chunk,
        )
        primary_counts = distinct_user_counts_sharded(primary_csr)
        indicators = {}
        for name in src.event_names:
            is_primary = name == primary
            csr = (
                primary_csr if is_primary
                else build_cooc_csr_sharded(
                    sources[name], n_users, n_items, mesh,
                    max_len=max_len, chunk=chunk,
                )
            )
            if csr.global_edges == 0 and not is_primary:
                # GLOBAL emptiness (from the counts pass): every process
                # takes the same branch, so the collective indicator
                # build below never diverges across the mesh
                continue
            col_counts = (
                primary_counts if is_primary
                else distinct_user_counts_sharded(csr)
            )
            indicators[name] = _invert_indicators(
                *cooccurrence_indicators(
                    primary_csr,
                    None if is_primary else csr,
                    top_k=top_k,
                    llr_row_totals=primary_counts,
                    llr_col_totals=col_counts,
                    total=n_users,
                    drop_diagonal=is_primary,
                    chunk=chunk,
                    mesh=mesh,
                )
            )
        item_props = {
            iid: pm.to_dict()
            for iid, pm in PEventStore.aggregate_properties(
                src.app_name, entity_type="item",
                channel_name=src.channel_name,
            ).items()
        }
        return URModel(
            event_names=list(src.event_names),
            item_ids=items_enc.ids,
            item_index={iid: j for j, iid in enumerate(items_enc.ids)},
            indicators=indicators,
            user_history={},
            item_properties=item_props,
            history_mode="live",
            app_name=src.app_name,
            channel_name=src.channel_name,
        )

    @staticmethod
    def _rule_multiplier(model: URModel, rule, cache: dict | None) -> np.ndarray:
        """One ``fields`` rule's per-item multiplier. The match scan is
        O(items) of python property probing -- by far the dominant cost of
        a rule-carrying query -- so batch_predict memoizes it per DISTINCT
        rule across the whole batch."""
        name, values = rule.get("name"), set(map(str, rule.get("values", [])))
        bias = float(rule.get("bias", -1))
        key = (name, tuple(sorted(values)), bias)
        if cache is not None and key in cache:
            return cache[key]
        matches = np.array(
            [
                str(model.item_properties.get(iid, {}).get(name)) in values
                or bool(
                    isinstance(model.item_properties.get(iid, {}).get(name), list)
                    and values
                    & set(map(str, model.item_properties[iid][name]))
                )
                for iid in model.item_ids
            ]
        )
        mult = (
            np.where(matches, 1.0, 0.0)
            if bias < 0
            else np.where(matches, bias, 1.0)
        )
        if cache is not None:
            cache[key] = mult
        return mult

    def _predict_impl(
        self,
        model: URModel,
        query,
        rule_cache: dict | None = None,
        history_memo: dict | None = None,
    ) -> dict:
        num = int(query.get("num", 10))
        user = str(query.get("user", ""))
        if history_memo is not None:
            if user not in history_memo:
                history_memo[user] = _user_history(model, user)
            history = dict(history_memo[user])  # copied before any mutation
        else:
            history = _user_history(model, user)
        # item-anchored queries act as view-history of the primary type
        if "items" in query:
            anchors = [
                model.item_index[str(i)]
                for i in query["items"]
                if str(i) in model.item_index
            ]
            history[model.event_names[0]] = (
                history.get(model.event_names[0], []) + anchors
            )
        if not history:
            return {"itemScores": []}
        # CCO scoring via the reverse index: each history item j credits the
        # primary items whose top-k correlators include j
        scores = np.zeros(len(model.item_ids), dtype=np.float64)
        for name, items in history.items():
            inverted = model.indicators.get(name)
            if inverted is None:
                continue
            for j in set(items):
                for p, v in inverted.get(j, ()):
                    scores[p] += v
        exclude = {
            j
            for items in history.values()
            for j in items
        } if query.get("unseenOnly", True) else set()
        for b in query.get("blackList") or []:
            if str(b) in model.item_index:
                exclude.add(model.item_index[str(b)])
        # business rules: fields filters/boosts over item properties
        multipliers = np.ones(len(model.item_ids))
        for rule in query.get("fields") or []:
            multipliers *= self._rule_multiplier(model, rule, rule_cache)
        scores = scores * multipliers
        for j in exclude:
            scores[j] = 0.0
        order = topk_order(scores, num)
        return {
            "itemScores": [
                {"item": model.item_ids[j], "score": float(scores[j])}
                for j in order
                if scores[j] > 0
            ]
        }

    def predict(self, model: URModel, query) -> dict:
        return self._predict_impl(model, query)

    def batch_predict(self, model: URModel, queries):
        """Bulk scoring with per-batch memoization: business-rule match
        masks are built ONCE per distinct rule (they cost an O(items)
        python property scan each) and live user-history reads once per
        distinct user, instead of once per query. Scoring itself stays the
        reverse-index walk (already O(history * hits), not O(items));
        malformed queries raise predict()'s normal error."""
        rule_cache: dict = {}
        history_memo: dict = {}
        return [
            (qid, self._predict_impl(model, q, rule_cache, history_memo))
            for qid, q in queries
        ]


def engine_factory() -> Engine:
    return Engine(
        data_source_class=URDataSource,
        preparator_class=IdentityPreparator,
        algorithm_class_map={"ur": URAlgorithm},
        serving_class=FirstServing,
    )
