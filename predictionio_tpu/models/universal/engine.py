"""DASE components of the Universal Recommender template.

Query contract: ``{"user": "u1", "num": 4, "blackList": [...],
"fields": [{"name": "category", "values": ["books"], "bias": -1}]}``
-> ``{"itemScores": [...]}``. ``bias < 0`` filters, ``bias >= 0``
multiplies matching items' scores (UR business-rule semantics).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from predictionio_tpu.controller import (
    DataSource,
    Engine,
    EvalInfo,
    FirstServing,
    IdentityPreparator,
    TPUAlgorithm,
)
from predictionio_tpu.controller.base import SanityCheck
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.ops.cooccurrence import cooccurrence_indicators
from predictionio_tpu.ops.ragged import pack_padded_csr


@dataclass
class MultiEventData(SanityCheck):
    """Per-event-type COO interactions over one shared user/item universe."""

    event_names: list[str]                      # [0] is primary
    per_event: dict[str, tuple[np.ndarray, np.ndarray, np.ndarray]]  # (u, i, t)
    user_ids: list[str]
    item_ids: list[str]
    item_properties: dict[str, dict]            # item id -> properties

    def sanity_check(self) -> None:
        primary = self.event_names[0]
        if primary not in self.per_event or self.per_event[primary][0].size == 0:
            raise ValueError(f"no events of primary type {primary!r} found")


class URDataSource(DataSource):
    """Params: appName, eventNames (primary first; default ["buy", "view"])."""

    def _read(self) -> MultiEventData:
        event_names = self.params.get_or("eventNames", ["buy", "view"])
        events = PEventStore.find(
            self.params.appName,
            event_names=event_names,
            target_entity_type="item",
        )
        user_index: dict[str, int] = {}
        item_index: dict[str, int] = {}
        raw: dict[str, list[tuple[int, int, float]]] = {n: [] for n in event_names}
        for e in events:
            if e.target_entity_id is None:
                continue
            u = user_index.setdefault(e.entity_id, len(user_index))
            i = item_index.setdefault(e.target_entity_id, len(item_index))
            raw[e.event].append((u, i, e.event_time.timestamp()))
        per_event = {}
        for name, triples in raw.items():
            if triples:
                arr = np.array(triples, dtype=np.float64)
                per_event[name] = (
                    arr[:, 0].astype(np.int64),
                    arr[:, 1].astype(np.int64),
                    arr[:, 2],
                )
            else:
                per_event[name] = (
                    np.zeros(0, np.int64),
                    np.zeros(0, np.int64),
                    np.zeros(0, np.float64),
                )
        item_props = {
            iid: pm.to_dict()
            for iid, pm in PEventStore.aggregate_properties(
                self.params.appName, entity_type="item"
            ).items()
        }
        return MultiEventData(
            event_names=list(event_names),
            per_event=per_event,
            user_ids=list(user_index),
            item_ids=list(item_index),
            item_properties=item_props,
        )

    def read_training(self, ctx) -> MultiEventData:
        return self._read()

    def read_eval(self, ctx):
        """Hold out each user's most recent PRIMARY interaction."""
        data = self._read()
        data.sanity_check()  # empty store: fail with the real message, not IndexError
        primary = data.event_names[0]
        u, i, t = data.per_event[primary]
        order = np.lexsort((t, u))
        u, i, t = u[order], i[order], t[order]
        last = np.r_[u[1:] != u[:-1], True]
        held = {int(uu): int(ii) for uu, ii, l in zip(u, i, last) if l}
        train = MultiEventData(
            event_names=data.event_names,
            per_event={
                **data.per_event,
                primary: (u[~last], i[~last], t[~last]),
            },
            user_ids=data.user_ids,
            item_ids=data.item_ids,
            item_properties=data.item_properties,
        )
        pairs = [
            (
                {"user": data.user_ids[uu], "num": self.params.get_or("evalK", 10)},
                [data.item_ids[ii]],
            )
            for uu, ii in held.items()
        ]
        return [(train, EvalInfo(fold=0), pairs)]


@dataclass
class URModel:
    event_names: list[str]
    item_ids: list[str]
    item_index: dict[str, int]
    #: per event type: reverse indicator index -- history item j ->
    #: [(primary item p, weight)] (inverted from the per-p top-k table so a
    #: query costs O(history * hits), not O(history * items * k))
    indicators: dict[str, dict[int, list[tuple[int, float]]]]
    #: user id -> {event type -> [item indices]}
    user_history: dict[str, dict[str, list[int]]]
    item_properties: dict[str, dict]


def _invert_indicators(
    idx: np.ndarray, vals: np.ndarray
) -> dict[int, list[tuple[int, float]]]:
    inverted: dict[int, list[tuple[int, float]]] = {}
    for p in range(idx.shape[0]):
        for j, v in zip(idx[p], vals[p]):
            if v > 0:
                inverted.setdefault(int(j), []).append((p, float(v)))
    return inverted


class URAlgorithm(TPUAlgorithm):
    """Params: topK (indicators per anchor, default 50), maxEventsPerUser,
    chunk."""

    def train(self, ctx, data: MultiEventData) -> URModel:
        n_users, n_items = len(data.user_ids), len(data.item_ids)
        max_len = self.params.get_or("maxEventsPerUser", None)
        chunk = self.params.get_or("chunk", 4096)
        top_k = self.params.get_or("topK", 50)
        mesh = self.mesh_or_none(ctx)  # user rows dp-sharded, psum acc

        def to_csr(triples):
            uu, ii, tt = triples
            return pack_padded_csr(
                uu, ii, np.ones(uu.size, np.float32), n_users, n_items,
                times=tt, max_len=max_len,
            )

        from predictionio_tpu.ops.cooccurrence import distinct_user_counts

        primary_csr = to_csr(data.per_event[data.event_names[0]])
        # diagonals are distinct-user counts: O(nnz) on host, no extra matmuls
        primary_counts = distinct_user_counts(primary_csr)
        indicators: dict[str, tuple[np.ndarray, np.ndarray]] = {}
        for name in data.event_names:
            if data.per_event[name][0].size == 0:
                continue
            is_primary = name == data.event_names[0]
            csr = primary_csr if is_primary else to_csr(data.per_event[name])
            col_counts = (
                primary_counts if is_primary else distinct_user_counts(csr)
            )
            # fused on-device cooc -> LLR -> top-k: only the [items, topK]
            # indicators leave the device, never the [items, items] matrix
            indicators[name] = _invert_indicators(
                *cooccurrence_indicators(
                    primary_csr,
                    None if is_primary else csr,
                    top_k=top_k,
                    llr_row_totals=primary_counts,
                    llr_col_totals=col_counts,
                    total=n_users,
                    drop_diagonal=is_primary,
                    chunk=chunk,
                    mesh=mesh,
                )
            )
        history: dict[str, dict[str, list[int]]] = {}
        for name in data.event_names:
            uu, ii, _ = data.per_event[name]
            for u, i in zip(uu, ii):
                history.setdefault(data.user_ids[int(u)], {}).setdefault(
                    name, []
                ).append(int(i))
        return URModel(
            event_names=data.event_names,
            item_ids=data.item_ids,
            item_index={iid: j for j, iid in enumerate(data.item_ids)},
            indicators=indicators,
            user_history=history,
            item_properties=data.item_properties,
        )

    def predict(self, model: URModel, query) -> dict:
        num = int(query.get("num", 10))
        history = dict(model.user_history.get(str(query.get("user", "")), {}))
        # item-anchored queries act as view-history of the primary type
        if "items" in query:
            anchors = [
                model.item_index[str(i)]
                for i in query["items"]
                if str(i) in model.item_index
            ]
            history[model.event_names[0]] = (
                history.get(model.event_names[0], []) + anchors
            )
        if not history:
            return {"itemScores": []}
        # CCO scoring via the reverse index: each history item j credits the
        # primary items whose top-k correlators include j
        scores = np.zeros(len(model.item_ids), dtype=np.float64)
        for name, items in history.items():
            inverted = model.indicators.get(name)
            if inverted is None:
                continue
            for j in set(items):
                for p, v in inverted.get(j, ()):
                    scores[p] += v
        exclude = {
            j
            for items in history.values()
            for j in items
        } if query.get("unseenOnly", True) else set()
        for b in query.get("blackList") or []:
            if str(b) in model.item_index:
                exclude.add(model.item_index[str(b)])
        # business rules: fields filters/boosts over item properties
        multipliers = np.ones(len(model.item_ids))
        for rule in query.get("fields") or []:
            name, values = rule.get("name"), set(map(str, rule.get("values", [])))
            bias = float(rule.get("bias", -1))
            matches = np.array(
                [
                    str(model.item_properties.get(iid, {}).get(name)) in values
                    or bool(
                        isinstance(model.item_properties.get(iid, {}).get(name), list)
                        and values
                        & set(map(str, model.item_properties[iid][name]))
                    )
                    for iid in model.item_ids
                ]
            )
            if bias < 0:
                multipliers *= np.where(matches, 1.0, 0.0)
            else:
                multipliers *= np.where(matches, bias, 1.0)
        scores = scores * multipliers
        for j in exclude:
            scores[j] = 0.0
        order = np.argsort(-scores)[:num]
        return {
            "itemScores": [
                {"item": model.item_ids[j], "score": float(scores[j])}
                for j in order
                if scores[j] > 0
            ]
        }


def engine_factory() -> Engine:
    return Engine(
        data_source_class=URDataSource,
        preparator_class=IdentityPreparator,
        algorithm_class_map={"ur": URAlgorithm},
        serving_class=FirstServing,
    )
