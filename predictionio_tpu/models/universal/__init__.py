"""Universal Recommender template: correlated cross-occurrence + LLR.

Reference counterpart: the community Universal Recommender (Mahout CCO/LLR
scored through Elasticsearch) -- SURVEY.md section 2.5 #37, BASELINE.json
config #4. Multi-event: the FIRST name in ``eventNames`` is the primary
(conversion) event; every other type contributes a cross-occurrence
indicator matrix ``LLR(A_primary^T A_t)``. Scoring sums indicator weights
over the user's per-type histories, with business rules (blacklist,
property filters/boosts) applied host-side at serving time.
"""

from predictionio_tpu.models.universal.engine import (
    URAlgorithm,
    URDataSource,
    engine_factory,
)

__all__ = ["URAlgorithm", "URDataSource", "engine_factory"]
