"""DASE components of the similar-product template.

Query contract: ``{"items": ["i1"], "num": 4, "blackList": [...]}`` ->
``{"itemScores": [{"item": ..., "score": ...}]}``; a ``{"user": ...}`` query
anchors on the user's own interaction history.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from predictionio_tpu.controller import (
    DataSource,
    Engine,
    EvalInfo,
    FirstServing,
    IdentityPreparator,
    TPUAlgorithm,
)
from predictionio_tpu.controller.base import SanityCheck
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.ops.cooccurrence import (
    cooccurrence_indicators,
    distinct_user_counts,
)
from predictionio_tpu.models._als_common import (
    Shortlist,
    resolve_retrieval,
    topk_order,
)
from predictionio_tpu.models._streaming import (
    StreamingHandle,
    live_target_events,
    streaming_handle_or_none,
)
from predictionio_tpu.ops.ragged import pack_padded_csr


@dataclass
class InteractionData(SanityCheck):
    users: np.ndarray
    items: np.ndarray
    times: np.ndarray
    user_ids: list[str]
    item_ids: list[str]

    def sanity_check(self) -> None:
        if self.users.size == 0:
            raise ValueError("no interaction events found")


class SimilarProductDataSource(DataSource):
    """Params: appName, eventNames (default ["view", "buy"]),
    maxEventsPerUser; ``"reader": "streaming"`` trains through the
    retention-bounded sharded reader (each process keeps only its
    data-shard's user rows) and serves user-anchored queries from live
    event-store reads."""

    def _read(self) -> InteractionData:
        ds = PEventStore.dataset(
            self.params.appName,
            event_names=self.params.get_or("eventNames", ["view", "buy"]),
            target_entity_type="item",
        )
        valid = ds.target_entity_ids >= 0
        return InteractionData(
            users=ds.entity_ids[valid],
            items=ds.target_entity_ids[valid],
            times=ds.event_times[valid],
            user_ids=ds.entity_id_vocab,
            item_ids=ds.target_entity_id_vocab,
        )

    def read_training(self, ctx):
        handle = streaming_handle_or_none(
            self.params, ["view", "buy"],
            empty_message="no interaction events found",
        )
        return handle if handle is not None else self._read()

    def read_eval(self, ctx):
        """Hold out each user's most recent interaction; query with the rest."""
        data = self._read()
        data.sanity_check()  # empty store: fail with the real message, not IndexError
        order = np.lexsort((data.times, data.users))
        users, items = data.users[order], data.items[order]
        last_of_user = np.r_[users[1:] != users[:-1], True]
        train_mask = ~last_of_user
        history: dict[int, list[int]] = {}
        for u, i, keep in zip(users, items, train_mask):
            if keep:
                history.setdefault(int(u), []).append(int(i))
        pairs = []
        for u, i, is_last in zip(users, items, last_of_user):
            if is_last and history.get(int(u)):
                pairs.append(
                    (
                        {
                            "items": [data.item_ids[j] for j in history[int(u)]],
                            "num": self.params.get_or("evalK", 10),
                        },
                        [data.item_ids[int(i)]],
                    )
                )
        train = InteractionData(
            users=users[train_mask],
            items=items[train_mask],
            times=data.times[order][train_mask],
            user_ids=data.user_ids,
            item_ids=data.item_ids,
        )
        return [(train, EvalInfo(fold=0), pairs)]

    def read_replay(self, ctx, spec):
        """Time-travel replay fold (``pio eval --replay``): the
        cooccurrence model trains on interactions strictly before the
        boundary; each held-out user's query anchors on their TRAINING
        prefix items only (anchoring on held-out events would both leak
        the future and self-exclude the actuals). Users with no prefix
        history stay in the fold with an empty anchor list and score as
        misses -- the honest cold-user accounting."""
        from predictionio_tpu.eval.split import ReplayFold, split_interactions

        data = self._read()
        cut = split_interactions(data.users, data.items, data.times, spec)
        train = InteractionData(
            users=data.users[cut.train_mask],
            items=data.items[cut.train_mask],
            times=data.times[cut.train_mask],
            user_ids=data.user_ids,
            item_ids=data.item_ids,
        )
        history: dict[int, list[int]] = {}
        for u, i in zip(train.users.tolist(), train.items.tolist()):
            hist = history.setdefault(int(u), [])
            if int(i) not in hist:
                hist.append(int(i))
        pairs = [
            (
                {
                    "items": [
                        data.item_ids[j] for j in history.get(int(u), [])
                    ],
                    "num": spec.k,
                },
                [data.item_ids[int(i)] for i in items],
            )
            for u, items in cut.holdout.items()
        ]
        return ReplayFold(train, pairs, cut.bounds)


@dataclass
class SimilarityModel:
    item_ids: list[str]
    item_index: dict[str, int]
    top_indices: np.ndarray  # [items, k]
    top_values: np.ndarray   # [items, k]
    user_history: dict[str, list[int]]
    #: "model": user-anchored queries read the trained-in map above;
    #: "live": per-query event-store read (O(entities) serving model --
    #: the streaming reader's contract, and fresh events anchor without
    #: retrain). Old pickles predate these; readers use getattr defaults.
    history_mode: str = "model"
    app_name: str = ""
    channel_name: str = None
    event_names: list[str] = None


def _user_anchor_items(model: "SimilarityModel", user: str) -> list[int]:
    """The user's interacted item indices to anchor a {"user": ...} query.

    Live mode reads the event store per request (fresh interactions anchor
    immediately, the model carries no O(edges) map); a store error
    degrades to no anchors rather than a 500.
    """
    if getattr(model, "history_mode", "model") != "live":
        return model.user_history.get(user, [])
    return [
        model.item_index[e.target_entity_id]
        for e in live_target_events(model, user)
        if e.target_entity_id in model.item_index
    ]


class CooccurrenceAlgorithm(TPUAlgorithm):
    """Params: topK (indicators per item, default 50), llr (default True),
    chunk (users per device matmul chunk), retrieval ({"mode":
    "scan"|"mips"} -- mips serves from a compact union of the anchors'
    indicator entries instead of a dense [items] buffer; scores are EXACT
    here since each anchor touches only its topK indicator columns, so
    the knob trades nothing and exists for the shared engine-param
    surface; the quantization knobs are ignored)."""

    @property
    def _retrieval(self):
        conf = getattr(self, "_retrieval_conf", None)
        if conf is None:
            conf = resolve_retrieval(self.params)
            self._retrieval_conf = conf
        return conf

    def train(self, ctx, data) -> SimilarityModel:
        self._retrieval  # a retrieval typo fails the build, not a query
        chunk = self.params.get_or("chunk", 4096)
        mesh = self.mesh_or_none(ctx)  # user rows dp-sharded, psum acc
        streamed = isinstance(data, StreamingHandle)
        if streamed:
            from predictionio_tpu.models._streaming import streaming_coo_source
            from predictionio_tpu.parallel.mesh import local_mesh
            from predictionio_tpu.parallel.reader import (
                build_cooc_csr_sharded,
                distinct_user_counts_sharded,
            )

            mesh = mesh or local_mesh(1, 1)
            source, users_enc, items_enc = streaming_coo_source(
                data, runtime_conf=getattr(ctx, "runtime_conf", None)
            )
            csr = build_cooc_csr_sharded(
                source, None, None, mesh,
                max_len=self.params.get_or("maxEventsPerUser", None),
                chunk=chunk,
            )
            user_ids, item_ids = users_enc.ids, items_enc.ids
            totals_fn = lambda: distinct_user_counts_sharded(csr)
        else:
            csr = pack_padded_csr(
                data.users,
                data.items,
                np.ones(data.users.size, dtype=np.float32),
                num_rows=len(data.user_ids),
                num_cols=len(data.item_ids),
                times=data.times,
                max_len=self.params.get_or("maxEventsPerUser", None),
            )
            user_ids, item_ids = data.user_ids, data.item_ids
            totals_fn = lambda: distinct_user_counts(csr)
        # fused on-device cooc -> (LLR) -> top-k; the self-cooccurrence
        # diagonal (= per-item distinct-user counts) comes from the O(nnz)
        # host pass so the [items, items] matrix never leaves the device
        llr_kwargs = {}
        if self.params.get_or("llr", True):
            totals = totals_fn()
            llr_kwargs = dict(
                llr_row_totals=totals,
                llr_col_totals=totals,
                total=len(user_ids),
            )
        idx, vals = cooccurrence_indicators(
            csr,
            top_k=self.params.get_or("topK", 50),
            chunk=chunk,
            mesh=mesh,
            **llr_kwargs,
        )
        if streamed:
            # no O(edges) history map exists; user queries read the store
            history: dict[str, list[int]] = {}
            mode = "live"
        else:
            history = {}
            for u, i in zip(data.users, data.items):
                history.setdefault(data.user_ids[int(u)], []).append(int(i))
            mode = "model"
        return SimilarityModel(
            item_ids=item_ids,
            item_index={iid: j for j, iid in enumerate(item_ids)},
            top_indices=np.asarray(idx),
            top_values=np.asarray(vals),
            user_history=history,
            history_mode=mode,
            app_name=data.app_name if streamed else "",
            channel_name=data.channel_name if streamed else None,
            event_names=list(data.event_names) if streamed else None,
        )

    @staticmethod
    def _resolve_anchors(model: SimilarityModel, query) -> list[int]:
        if "items" in query:
            return [
                model.item_index[str(i)]
                for i in query["items"]
                if str(i) in model.item_index
            ]
        if "user" in query:
            return _user_anchor_items(model, str(query["user"]))
        raise ValueError("query must contain 'items' or 'user'")

    @staticmethod
    def _anchor_contributions(model: SimilarityModel, anchors: list[int]):
        """(cols, vals): the anchors' positive indicator entries, flattened
        -- one gather over the [items, k] tables instead of a python loop
        over every (anchor, k) pair."""
        idx = model.top_indices[anchors].ravel()
        vals = model.top_values[anchors].ravel().astype(np.float64)
        keep = vals > 0
        return idx[keep], vals[keep]

    @classmethod
    def _compact_scores(cls, model: SimilarityModel, anchors: list[int]) -> Shortlist:
        """The anchors' summed indicator scores as a compact ``Shortlist``
        (ascending union of touched columns): O(anchors * topK) memory
        instead of a dense [items] buffer, and EXACT -- indicator tables
        are already top-K sparse, so the union IS the support. The f64
        accumulation matches the dense path bit-for-bit."""
        cols, vals = cls._anchor_contributions(model, anchors)
        uniq, inv = np.unique(cols, return_inverse=True)
        scores = np.zeros(uniq.size, np.float64)
        np.add.at(scores, inv, vals)
        return Shortlist(uniq, scores, len(model.item_ids))

    @staticmethod
    def _topk_response(model: SimilarityModel, scores, query,
                       anchors: list[int]) -> dict:
        """Shared exclusion + ranking tail (predict and batch_predict must
        rank identically). The exclusion sentinel here is 0, not -inf:
        only positively-scored items are ever emitted. A ``Shortlist``
        ranks over its compact arrays -- ascending indices mean the stable
        sort breaks ties by catalog index exactly like the dense path."""
        scores = scores.copy()
        exclude = set(anchors)
        for b in query.get("blackList") or []:
            if str(b) in model.item_index:
                exclude.add(model.item_index[str(b)])
        for j in exclude:
            scores[j] = 0.0
        if isinstance(scores, Shortlist):
            order = topk_order(scores.scores, int(query.get("num", 10)))
            return {
                "itemScores": [
                    {"item": model.item_ids[int(scores.indices[j])],
                     "score": float(scores.scores[j])}
                    for j in order
                    if scores.scores[j] > 0
                ]
            }
        order = topk_order(scores, int(query.get("num", 10)))
        return {
            "itemScores": [
                {"item": model.item_ids[int(j)], "score": float(scores[j])}
                for j in order
                if scores[j] > 0
            ]
        }

    def predict(self, model: SimilarityModel, query) -> dict:
        anchors = self._resolve_anchors(model, query)
        if not anchors:
            return {"itemScores": []}
        if self._retrieval.mode == "mips":
            return self._topk_response(
                model, self._compact_scores(model, anchors), query, anchors
            )
        scores = np.zeros(len(model.item_ids), np.float64)
        cols, vals = self._anchor_contributions(model, anchors)
        np.add.at(scores, cols, vals)
        return self._topk_response(model, scores, query, anchors)

    def batch_predict(self, model: SimilarityModel, queries):
        """Vectorized bulk scoring: the whole batch's anchor contributions
        accumulate into ONE [B, items] buffer with a single scatter-add
        (memory-bounded slices), instead of a python dict walk per query.
        Live user-anchor lookups are memoized per distinct user for the
        batch. Cold queries answer empty; malformed queries raise
        predict()'s normal error through the fallback loop."""
        from predictionio_tpu.models._als_common import score_buffer_rows

        resolved, out, fallback = [], [], []
        live_memo: dict[str, list[int]] = {}
        for qid, q in queries:
            if not isinstance(q, dict) or not ("items" in q or "user" in q):
                fallback.append((qid, q))
                continue
            if "items" not in q and getattr(model, "history_mode", "model") == "live":
                user = str(q["user"])
                if user not in live_memo:
                    live_memo[user] = _user_anchor_items(model, user)
                anchors = live_memo[user]
            else:
                anchors = self._resolve_anchors(model, q)
            if not anchors:
                out.append((qid, {"itemScores": []}))
            else:
                resolved.append((qid, q, anchors))
        # malformed queries raise predict()'s error BEFORE the vectorized
        # work: one bad query must not cost the batch its completed scoring
        out.extend((qid, self.predict(model, q)) for qid, q in fallback)
        if self._retrieval.mode == "mips":
            # compact per-row accumulation: peak score memory is
            # O(anchors * topK) per row, never the [B, items] buffer below
            out.extend(
                (
                    qid,
                    self._topk_response(
                        model, self._compact_scores(model, anchors), q, anchors
                    ),
                )
                for qid, q, anchors in resolved
            )
            return out
        n_items = len(model.item_ids)
        # halved: this buffer accumulates in f64 (predict's dtype -- the
        # batched and single paths must sum identically) while
        # score_buffer_rows budgets for f32
        rows_per_slice = max(1, score_buffer_rows(n_items) // 2)
        for start in range(0, len(resolved), rows_per_slice):
            part = resolved[start : start + rows_per_slice]
            scores = np.zeros((len(part), n_items), np.float64)
            row_ids, col_ids, vals = [], [], []
            for row, (_, _, anchors) in enumerate(part):
                cols, v = self._anchor_contributions(model, anchors)
                row_ids.append(np.full(cols.size, row, np.int64))
                col_ids.append(cols)
                vals.append(v)
            np.add.at(
                scores,
                (np.concatenate(row_ids), np.concatenate(col_ids)),
                np.concatenate(vals),
            )
            out.extend(
                (qid, self._topk_response(model, scores[row], q, anchors))
                for row, (qid, q, anchors) in enumerate(part)
            )
        return out


def engine_factory() -> Engine:
    return Engine(
        data_source_class=SimilarProductDataSource,
        preparator_class=IdentityPreparator,
        algorithm_class_map={"cooccurrence": CooccurrenceAlgorithm},
        serving_class=FirstServing,
    )
