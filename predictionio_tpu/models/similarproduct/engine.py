"""DASE components of the similar-product template.

Query contract: ``{"items": ["i1"], "num": 4, "blackList": [...]}`` ->
``{"itemScores": [{"item": ..., "score": ...}]}``; a ``{"user": ...}`` query
anchors on the user's own interaction history.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from predictionio_tpu.controller import (
    DataSource,
    Engine,
    EvalInfo,
    FirstServing,
    IdentityPreparator,
    TPUAlgorithm,
)
from predictionio_tpu.controller.base import SanityCheck
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.ops.cooccurrence import (
    cooccurrence_indicators,
    distinct_user_counts,
)
from predictionio_tpu.ops.ragged import pack_padded_csr


@dataclass
class InteractionData(SanityCheck):
    users: np.ndarray
    items: np.ndarray
    times: np.ndarray
    user_ids: list[str]
    item_ids: list[str]

    def sanity_check(self) -> None:
        if self.users.size == 0:
            raise ValueError("no interaction events found")


class SimilarProductDataSource(DataSource):
    """Params: appName, eventNames (default ["view", "buy"]), maxEventsPerUser."""

    def _read(self) -> InteractionData:
        ds = PEventStore.dataset(
            self.params.appName,
            event_names=self.params.get_or("eventNames", ["view", "buy"]),
            target_entity_type="item",
        )
        valid = ds.target_entity_ids >= 0
        return InteractionData(
            users=ds.entity_ids[valid],
            items=ds.target_entity_ids[valid],
            times=ds.event_times[valid],
            user_ids=ds.entity_id_vocab,
            item_ids=ds.target_entity_id_vocab,
        )

    def read_training(self, ctx) -> InteractionData:
        return self._read()

    def read_eval(self, ctx):
        """Hold out each user's most recent interaction; query with the rest."""
        data = self._read()
        data.sanity_check()  # empty store: fail with the real message, not IndexError
        order = np.lexsort((data.times, data.users))
        users, items = data.users[order], data.items[order]
        last_of_user = np.r_[users[1:] != users[:-1], True]
        train_mask = ~last_of_user
        history: dict[int, list[int]] = {}
        for u, i, keep in zip(users, items, train_mask):
            if keep:
                history.setdefault(int(u), []).append(int(i))
        pairs = []
        for u, i, is_last in zip(users, items, last_of_user):
            if is_last and history.get(int(u)):
                pairs.append(
                    (
                        {
                            "items": [data.item_ids[j] for j in history[int(u)]],
                            "num": self.params.get_or("evalK", 10),
                        },
                        [data.item_ids[int(i)]],
                    )
                )
        train = InteractionData(
            users=users[train_mask],
            items=items[train_mask],
            times=data.times[order][train_mask],
            user_ids=data.user_ids,
            item_ids=data.item_ids,
        )
        return [(train, EvalInfo(fold=0), pairs)]


@dataclass
class SimilarityModel:
    item_ids: list[str]
    item_index: dict[str, int]
    top_indices: np.ndarray  # [items, k]
    top_values: np.ndarray   # [items, k]
    user_history: dict[str, list[int]]


class CooccurrenceAlgorithm(TPUAlgorithm):
    """Params: topK (indicators per item, default 50), llr (default True),
    chunk (users per device matmul chunk)."""

    def train(self, ctx, data: InteractionData) -> SimilarityModel:
        csr = pack_padded_csr(
            data.users,
            data.items,
            np.ones(data.users.size, dtype=np.float32),
            num_rows=len(data.user_ids),
            num_cols=len(data.item_ids),
            times=data.times,
            max_len=self.params.get_or("maxEventsPerUser", None),
        )
        # fused on-device cooc -> (LLR) -> top-k; the self-cooccurrence
        # diagonal (= per-item distinct-user counts) comes from the O(nnz)
        # host pass so the [items, items] matrix never leaves the device
        llr_kwargs = {}
        if self.params.get_or("llr", True):
            totals = distinct_user_counts(csr)
            llr_kwargs = dict(
                llr_row_totals=totals,
                llr_col_totals=totals,
                total=len(data.user_ids),
            )
        idx, vals = cooccurrence_indicators(
            csr,
            top_k=self.params.get_or("topK", 50),
            chunk=self.params.get_or("chunk", 4096),
            mesh=self.mesh_or_none(ctx),  # user rows dp-sharded, psum acc
            **llr_kwargs,
        )
        history: dict[str, list[int]] = {}
        for u, i in zip(data.users, data.items):
            history.setdefault(data.user_ids[int(u)], []).append(int(i))
        return SimilarityModel(
            item_ids=data.item_ids,
            item_index={iid: j for j, iid in enumerate(data.item_ids)},
            top_indices=idx,
            top_values=vals,
            user_history=history,
        )

    def predict(self, model: SimilarityModel, query) -> dict:
        num = int(query.get("num", 10))
        if "items" in query:
            anchors = [
                model.item_index[str(i)]
                for i in query["items"]
                if str(i) in model.item_index
            ]
        elif "user" in query:
            anchors = model.user_history.get(str(query["user"]), [])
        else:
            raise ValueError("query must contain 'items' or 'user'")
        if not anchors:
            return {"itemScores": []}
        scores: dict[int, float] = {}
        for a in anchors:
            for j, v in zip(model.top_indices[a], model.top_values[a]):
                if v > 0:
                    scores[int(j)] = scores.get(int(j), 0.0) + float(v)
        exclude = set(anchors)
        for b in query.get("blackList") or []:
            if str(b) in model.item_index:
                exclude.add(model.item_index[str(b)])
        ranked = sorted(
            ((j, s) for j, s in scores.items() if j not in exclude),
            key=lambda kv: -kv[1],
        )[:num]
        return {
            "itemScores": [
                {"item": model.item_ids[j], "score": s} for j, s in ranked
            ]
        }


def engine_factory() -> Engine:
    return Engine(
        data_source_class=SimilarProductDataSource,
        preparator_class=IdentityPreparator,
        algorithm_class_map={"cooccurrence": CooccurrenceAlgorithm},
        serving_class=FirstServing,
    )
