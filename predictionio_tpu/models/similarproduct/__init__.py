"""Similar-Product template: item-item cooccurrence over implicit events.

Reference counterpart: predictionio-template-similar-product -- SURVEY.md
section 2.5 #37, BASELINE.json config #3 ("item-item cooccurrence over
implicit view/buy events"). Cooccurrence runs as chunked one-hot matmuls on
the MXU (``ops.cooccurrence``); optional LLR weighting de-noises popular
items.
"""

from predictionio_tpu.models.similarproduct.engine import (
    CooccurrenceAlgorithm,
    SimilarProductDataSource,
    engine_factory,
)

__all__ = ["CooccurrenceAlgorithm", "SimilarProductDataSource", "engine_factory"]
