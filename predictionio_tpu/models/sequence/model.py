"""Self-attentive sequential recommender (SASRec-style) on the device mesh.

The long-context model family: per-user event histories (the reference
streams these unboundedly through ``PEvents``; SURVEY.md section 5.7) become
item sequences, and a causal transformer predicts the next item. TPU-first
design:

- batch shards over the mesh ``data`` axis (dp); the SEQUENCE dim shards
  over the ``seq`` axis (sp) -- attention across shards runs as ring
  attention (``parallel.ring_attention``), K/V blocks hopping the ICI ring,
  so histories longer than one chip's memory train without replication;
- everything position-local (embedding lookup, LayerNorm, the pointwise
  FFN) needs no communication under sp: XLA keeps it shard-local;
- next-item loss is full-softmax cross-entropy against the tied item
  embedding matrix -- one [B*T, D] x [D, V] matmul on the MXU.
"""

from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from predictionio_tpu.parallel.mesh import (
    check_steps_ran,
    fetch_global,
    put_global,
)
from predictionio_tpu.utils.jax_compat import IS_LEGACY_JAX
from predictionio_tpu.ops.flash_attention import flash_attention
from predictionio_tpu.parallel.ring_attention import plain_attention, ring_attention
from predictionio_tpu.parallel.ulysses import ulysses_attention


@dataclass(frozen=True)
class SASRecConfig:
    num_items: int              # real item vocab; id 0 is reserved for padding
    max_len: int = 64
    embed_dim: int = 32
    num_heads: int = 2
    num_blocks: int = 2
    ffn_dim: int = 64
    dropout: float = 0.0
    learning_rate: float = 1e-3
    batch_size: int = 256
    epochs: int = 10
    seed: int = 0
    seq_parallel: str = "ring"  # "ring" | "ulysses" (all-to-all head scatter)
    #: intra-shard attention: "auto" = Pallas flash attention on TPU, the
    #: materialized-score reference elsewhere; "flash" / "plain" force it
    attention: str = "auto"

    def __post_init__(self):
        if self.embed_dim % self.num_heads:
            raise ValueError(
                f"embed_dim={self.embed_dim} must be divisible by "
                f"num_heads={self.num_heads}"
            )
        if self.attention not in ("auto", "flash", "plain"):
            raise ValueError(
                f"attention={self.attention!r} must be one of"
                " 'auto' | 'flash' | 'plain'"
            )
        if self.seq_parallel not in ("ring", "ulysses"):
            raise ValueError(
                f"seq_parallel={self.seq_parallel!r}: want 'ring' or 'ulysses'"
            )

    @property
    def vocab(self) -> int:
        return self.num_items + 1  # +1 for the padding id 0


class _MultiHeadSelfAttention(nn.Module):
    """Causal MHA whose score computation is mesh-aware: ring attention when
    the mesh has a >1 ``seq`` axis, plain attention otherwise."""

    config: SASRecConfig
    mesh: object = None

    @nn.compact
    def __call__(self, x, pad_mask):
        c = self.config
        b, t, d = x.shape
        h = c.num_heads
        head_dim = d // h
        qkv = nn.Dense(3 * d, use_bias=False, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        reshape = lambda a: a.reshape(b, t, h, head_dim)
        q, k, v = reshape(q), reshape(k), reshape(v)
        mesh = self.mesh
        backend = jax.default_backend()
        use_flash = c.attention == "flash" or (
            c.attention == "auto" and backend == "tpu"
        )
        if mesh is not None and mesh.shape.get("seq", 1) > 1:
            if c.seq_parallel == "ulysses":
                # ulysses gathers full sequences per chip, so the flash
                # kernel slots in as its local attention
                out = ulysses_attention(q, k, v, mesh, axis_name="seq",
                                        causal=True, mask=pad_mask,
                                        use_flash=use_flash)
            else:
                # ring attention IS the online softmax across shards; its
                # per-step scores are already [Tl, Tl] blocks, so "flash"
                # asks for nothing it does not already do
                out = ring_attention(q, k, v, mesh, axis_name="seq",
                                     causal=True, mask=pad_mask)
        elif use_flash:
            # O(T*D) memory: scores never materialize (ops/flash_attention)
            out = flash_attention(
                q, k, v, pad_mask, causal=True,
                interpret=backend != "tpu",
            )
        else:
            out = plain_attention(q, k, v, causal=True, mask=pad_mask)
        return nn.Dense(d, use_bias=False, name="proj")(out.reshape(b, t, d))


class SASRec(nn.Module):
    config: SASRecConfig
    mesh: object = None

    @nn.compact
    def __call__(self, seq, deterministic: bool = True):
        """seq: [B, T] int32, 0 = padding. Returns hidden states [B, T, D]."""
        c = self.config
        pad_mask = seq > 0
        x = nn.Embed(c.vocab, c.embed_dim, name="item_embed")(seq)
        x = x * (c.embed_dim**0.5)
        pos = jnp.arange(seq.shape[1])[None, :]
        x = x + nn.Embed(c.max_len, c.embed_dim, name="pos_embed")(pos)
        x = nn.Dropout(c.dropout, deterministic=deterministic)(x)
        for i in range(c.num_blocks):
            a = nn.LayerNorm(name=f"ln_att_{i}")(x)
            a = _MultiHeadSelfAttention(c, self.mesh, name=f"att_{i}")(a, pad_mask)
            x = x + nn.Dropout(c.dropout, deterministic=deterministic)(a)
            f = nn.LayerNorm(name=f"ln_ffn_{i}")(x)
            f = nn.Dense(c.ffn_dim, name=f"ffn_in_{i}")(f)
            f = nn.Dense(c.embed_dim, name=f"ffn_out_{i}")(nn.relu(f))
            x = x + nn.Dropout(c.dropout, deterministic=deterministic)(f)
        x = nn.LayerNorm(name="ln_out")(x)
        return x * pad_mask[..., None]


def _logits(params, hidden):
    """Tied-embedding output head: [B,T,D] x [V,D]^T -> [B,T,V]."""
    table = params["item_embed"]["embedding"]
    return jnp.einsum("btd,vd->btv", hidden, table)


def make_train_step(model: SASRec, optimizer):
    def loss_fn(params, batch, rng):
        hidden = model.apply(
            {"params": params}, batch["seq"], deterministic=False,
            rngs={"dropout": rng},
        )
        logits = _logits(params, hidden)
        targets = batch["target"]                     # [B, T], 0 = no target
        mask = (targets > 0).astype(jnp.float32)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
        return (ce * mask).sum() / jnp.maximum(mask.sum(), 1.0)

    def train_step(params, opt_state, batch, rng):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch, rng)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return train_step


def train_sasrec(
    config: SASRecConfig,
    sequences: np.ndarray,   # [N, T] int32 padded item ids (0 = pad)
    mesh,
    log_every: int = 0,
):
    """Train on next-item prediction; returns (params pytree on host, losses).

    Inputs/targets are the sequence and its left-shift: position t predicts
    the item at t+1. The [N, T] matrix shards over (data, seq).
    """
    t = sequences.shape[1]
    if t != config.max_len:
        raise ValueError(f"sequences padded to {t}, config.max_len={config.max_len}")
    sp = mesh.shape.get("seq", 1)
    if t % sp:
        raise ValueError(f"max_len={t} must divide over seq axis size {sp}")

    model = SASRec(config, mesh)
    rng = jax.random.PRNGKey(config.seed)
    # dummy batch = one row per data-shard: shard_map needs divisibility
    dp0 = max(mesh.shape.get("data", 1), 1)
    params = model.init(rng, jnp.zeros((dp0, t), jnp.int32))["params"]
    rep = NamedSharding(mesh, P())
    dp_axis = "data" if "data" in mesh.axis_names else None
    sp_axis = "seq" if "seq" in mesh.axis_names else None
    seq_shard = NamedSharding(mesh, P(dp_axis, sp_axis))
    # put_global/jitted-init: on multi-process meshes every rank holds
    # identical params (same PRNGKey); placement and Adam-state creation
    # must not touch non-addressable shards eagerly
    params = jax.tree_util.tree_map(lambda a: put_global(a, rep), params)
    optimizer = optax.adam(config.learning_rate)
    opt_state = jax.jit(optimizer.init)(params)

    step_fn = jax.jit(
        make_train_step(model, optimizer),
        in_shardings=(rep, None, {"seq": seq_shard, "target": seq_shard}, None),
        out_shardings=(rep, None, rep),
        # same legacy-jax hazard the NCF trainer hit (pio check J002):
        # donating the adam-state pytree under sharded placement pairs
        # donated buffers with wrong-shaped outputs in old XLA. Params
        # carry the bulk of the memory; moments re-donate once the floor
        # moves past the fixed runtime
        donate_argnums=(0,) if IS_LEGACY_JAX else (0, 1),
    )

    inputs = sequences.astype(np.int32)
    targets = np.zeros_like(inputs)
    targets[:, :-1] = inputs[:, 1:]

    np_rng = np.random.default_rng(config.seed)
    n = inputs.shape[0]
    dp = mesh.shape.get("data", 1)
    losses = []
    step = 0
    for _ in range(config.epochs):
        order = np_rng.permutation(n)
        for start in range(0, n, config.batch_size):
            take = order[start : start + config.batch_size]
            usable = (take.size // dp) * dp
            if not usable:
                continue
            take = take[:usable]
            # identical permutation on every rank (same seed): put_global
            # hands each process exactly its addressable (data, seq) shards
            batch = {
                "seq": put_global(inputs[take], seq_shard),
                "target": put_global(targets[take], seq_shard),
            }
            params, opt_state, loss = step_fn(
                params, opt_state, batch, jax.random.fold_in(rng, step)
            )
            step += 1
            if log_every and step % log_every == 0:
                losses.append(float(loss))
    check_steps_ran(step, n, dp, "sequence")
    return jax.tree_util.tree_map(fetch_global, params), losses


def _score_fn(config: SASRecConfig):
    """Jitted forward + vocab projection in ONE program, cached per config.

    Fusing the projection matters on remote-tunnel backends: the old path
    dispatched the transformer forward and the [D] x [V, D] einsum as
    separate eager calls, paying a round trip each, per query.
    """
    if config not in _SCORE_CACHE:
        model = SASRec(config, None)

        @jax.jit
        def score(params, seqs, last):
            hidden = model.apply({"params": params}, seqs)       # [B, T, D]
            h_last = jnp.take_along_axis(
                hidden, last[:, None, None].astype(jnp.int32), axis=1
            )[:, 0, :]                                           # [B, D]
            return h_last @ params["item_embed"]["embedding"].T  # [B, V]

        _SCORE_CACHE[config] = score
    return _SCORE_CACHE[config]


_SCORE_CACHE: dict = {}


def score_next_items_batch(params, config: SASRecConfig, prefixes) -> np.ndarray:
    """Scores over the item vocab for the next item after each prefix.

    ``prefixes``: list of 1-D id arrays (no padding); each uses its last
    max_len entries. Returns [B, num_items] (column i scores item id i+1 --
    id 0 is the padding token and is dropped). The batch pads to the next
    power of two internally, so arbitrary caller batch sizes compile at
    most log2(max_B) distinct programs (<2x padded compute) instead of one
    per size.
    """
    t = config.max_len
    b = len(prefixes)
    padded_b = 1 << (b - 1).bit_length() if b > 1 else 1
    seqs = np.zeros((padded_b, t), np.int32)
    last = np.zeros((padded_b,), np.int32)
    for i, p in enumerate(prefixes):
        tail = np.asarray(p, np.int32)[-t:]
        seqs[i, : len(tail)] = tail
        last[i] = max(len(tail) - 1, 0)
    scores = np.asarray(
        _score_fn(config)(params, jnp.asarray(seqs), jnp.asarray(last))
    )
    return scores[:b, 1:]


def score_next_items(params, config: SASRecConfig, prefix: np.ndarray) -> np.ndarray:
    """Single-prefix convenience over :func:`score_next_items_batch`."""
    return score_next_items_batch(params, config, [prefix])[0]
