"""Sequential-recommendation template (SASRec-style, ring-attention sp)."""

from predictionio_tpu.models.sequence.engine import (
    SASRecAlgorithm,
    SequenceDataSource,
    SequencePreparator,
    engine_factory,
)
from predictionio_tpu.models.sequence.model import (
    SASRec,
    SASRecConfig,
    score_next_items,
    train_sasrec,
)

__all__ = [
    "SASRec",
    "SASRecConfig",
    "SASRecAlgorithm",
    "SequenceDataSource",
    "SequencePreparator",
    "engine_factory",
    "score_next_items",
    "train_sasrec",
]
