"""DASE components of the sequential-recommendation template.

Per-user event histories -> next-item prediction. Query contracts:
``{"user": "u1", "num": 4}`` (recommend from the user's stored history) and
``{"items": ["i3", "i9"], "num": 4}`` (session-based: recommend from an
explicit prefix). Response: ``{"itemScores": [{"item", "score"}, ...]}``.

The reference has no sequence model (nothing in MLlib's template zoo is
sequential beyond MarkovChain in ``e2``); this family is the long-context
path of the rebuild (SURVEY.md section 5.7): histories can exceed one chip
via the ``seq`` mesh axis + ring attention.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from predictionio_tpu.controller import (
    DataSource,
    Engine,
    EvalInfo,
    FirstServing,
    Preparator,
    TPUAlgorithm,
)
from predictionio_tpu.controller.base import SanityCheck
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.models._als_common import score_buffer_rows, topk_item_scores
from predictionio_tpu.models.sequence.model import (
    SASRecConfig,
    score_next_items,
    score_next_items_batch,
    train_sasrec,
)


@dataclass
class SequencesData(SanityCheck):
    """Per-user time-ordered item-index sequences + vocabularies.

    Item indices are 0-based here; the model shifts by +1 (0 = padding).
    """

    sequences: list[np.ndarray]
    user_ids: list[str]
    item_ids: list[str]
    #: carried for serving-time live history reads (historyMode "live")
    app_name: str = ""
    channel_name: str = None
    event_names: list[str] = None

    def sanity_check(self) -> None:
        if not self.sequences:
            raise ValueError("no event sequences found -- check appName/eventNames")

    @property
    def num_items(self) -> int:
        return len(self.item_ids)


class SequenceDataSource(DataSource):
    """Groups item-interaction events per user, ordered by event time.

    Params: ``appName`` (required), ``eventNames`` (default
    ``["view", "buy", "rate"]``), ``minSeqLen`` (drop shorter histories,
    default 2), ``evalFolds``/``evalK`` for read_eval.
    """

    def _read(self) -> SequencesData:
        ds = PEventStore.dataset(
            self.params.appName,
            event_names=self.params.get_or("eventNames", ["view", "buy", "rate"]),
            target_entity_type="item",
        )
        valid = ds.target_entity_ids >= 0
        users = ds.entity_ids[valid]
        items = ds.target_entity_ids[valid]
        times = ds.event_times[valid]
        min_len = self.params.get_or("minSeqLen", 2)
        # one vectorized (user, time) sort, then a grouped scan -- the same
        # grouping idiom as the similar-product / UR templates
        sequences, seq_user_ids = [], []
        if users.size:
            order = np.lexsort((times, users))
            users, items = users[order], items[order]
            boundaries = np.flatnonzero(np.diff(users)) + 1
            for hist, u in zip(
                np.split(items, boundaries), users[np.r_[0, boundaries]]
            ):
                if len(hist) >= min_len:
                    sequences.append(hist.astype(np.int64))
                    seq_user_ids.append(ds.entity_id_vocab[int(u)])
        return SequencesData(
            sequences=sequences,
            user_ids=seq_user_ids,
            item_ids=ds.target_entity_id_vocab,
            app_name=self.params.appName,
            channel_name=self.params.get_or("channelName", None),
            event_names=self.params.get_or(
                "eventNames", ["view", "buy", "rate"]
            ),
        )

    def read_training(self, ctx) -> SequencesData:
        return self._read()

    def read_eval(self, ctx):
        """Leave-one-out per fold: hold out each user's last item as the
        actual, query on the preceding history (the SASRec protocol)."""
        data = self._read()
        folds = self.params.get_or("evalFolds", 1)
        eval_k = self.params.get_or("evalK", 10)
        out = []
        for f in range(folds):
            train_seqs, pairs, users = [], [], []
            for uid, seq in zip(data.user_ids, data.sequences):
                if len(seq) < 3:
                    train_seqs.append(seq)
                    users.append(uid)
                    continue
                cut = len(seq) - 1 - (f % max(len(seq) - 2, 1))
                train_seqs.append(seq[:cut])
                users.append(uid)
                pairs.append(
                    (
                        {"items": [data.item_ids[i] for i in seq[:cut]],
                         "num": eval_k},
                        [data.item_ids[seq[cut]]],
                    )
                )
            out.append(
                (
                    SequencesData(train_seqs, users, data.item_ids),
                    EvalInfo(fold=f),
                    pairs,
                )
            )
        return out


@dataclass
class PackedSequences(SanityCheck):
    matrix: np.ndarray            # [N, max_len] int32, ids shifted +1, 0 = pad
    data: SequencesData

    def sanity_check(self) -> None:
        self.data.sanity_check()


class SequencePreparator(Preparator):
    """Pad/left-truncate histories to maxLen and shift ids (+1, 0 = pad).

    Params: ``maxLen`` (default 64; must be divisible by the mesh seq-axis
    size when sequence parallelism is on).
    """

    def prepare(self, ctx, data: SequencesData) -> PackedSequences:
        max_len = self.params.get_or("maxLen", 64)
        matrix = np.zeros((len(data.sequences), max_len), np.int32)
        for row, seq in enumerate(data.sequences):
            tail = seq[-max_len:] + 1
            matrix[row, : len(tail)] = tail
        return PackedSequences(matrix=matrix, data=data)


@dataclass
class SASRecModel:
    params: dict
    config: SASRecConfig
    item_ids: list[str]
    item_index: dict[str, int]
    histories: dict[str, np.ndarray]   # user id -> shifted (+1) id sequence
    #: "model": queries continue the TRAINED-IN history above; "live":
    #: per-query event-store read -- session-based serving: events
    #: ingested after training extend the sequence the model continues,
    #: with no retrain, and the model stays O(entities). Old pickles
    #: predate these fields; readers use getattr defaults.
    history_mode: str = "model"
    app_name: str = ""
    channel_name: str = None
    event_names: list[str] = None


class SASRecAlgorithm(TPUAlgorithm):
    """Params: embedDim, numHeads, numBlocks, ffnDim, dropout, learningRate,
    batchSize, epochs, seed, maxLen (must match the preparator's), and
    seqParallel ("ring" | "ulysses") selecting the sequence-parallel
    attention strategy when the mesh has a >1 ``seq`` axis."""

    def train(self, ctx, prepared: PackedSequences) -> SASRecModel:
        p = self.params
        data = prepared.data
        max_len = p.get_or("maxLen", None)
        if max_len is not None and max_len != prepared.matrix.shape[1]:
            raise ValueError(
                f"algorithm maxLen={max_len} != preparator maxLen="
                f"{prepared.matrix.shape[1]}; set both to the same value "
                "(or drop the algorithm's)"
            )
        config = SASRecConfig(
            num_items=data.num_items,
            max_len=prepared.matrix.shape[1],
            embed_dim=p.get_or("embedDim", 32),
            num_heads=p.get_or("numHeads", 2),
            num_blocks=p.get_or("numBlocks", 2),
            ffn_dim=p.get_or("ffnDim", 64),
            dropout=p.get_or("dropout", 0.0),
            learning_rate=p.get_or("learningRate", 1e-3),
            batch_size=p.get_or("batchSize", 256),
            epochs=p.get_or("epochs", 10),
            seed=p.get_or("seed", 0),
            seq_parallel=p.get_or("seqParallel", "ring"),
            attention=p.get_or("attention", "auto"),
        )
        history_mode = self.params.get_or("historyMode", "model")
        if history_mode not in ("model", "live"):
            # before the (expensive) training run, not after
            raise ValueError(
                f"historyMode must be 'model' or 'live', got {history_mode!r}"
            )
        params, _ = train_sasrec(config, prepared.matrix, ctx.mesh)
        # live mode: O(entities) model; queries read fresh histories
        histories = {} if history_mode == "live" else {
            uid: seq + 1 for uid, seq in zip(data.user_ids, data.sequences)
        }
        return SASRecModel(
            params=params,
            config=config,
            item_ids=data.item_ids,
            item_index={iid: j for j, iid in enumerate(data.item_ids)},
            histories=histories,
            history_mode=history_mode,
            app_name=data.app_name,
            channel_name=data.channel_name,
            event_names=data.event_names,
        )

    @staticmethod
    def _resolve_prefix(model: SASRecModel, query):
        """The sequence to continue: explicit ``items`` anchor or the user's
        training history. None/empty means a cold query (empty response)."""
        if query.get("items"):
            return np.asarray(
                [
                    model.item_index[str(i)] + 1
                    for i in query["items"]
                    if str(i) in model.item_index
                ],
                np.int32,
            )
        user = str(query.get("user"))
        if getattr(model, "history_mode", "model") != "live":
            return model.histories.get(user)
        from predictionio_tpu.models._streaming import live_target_events

        # time-ASCENDING: the sequence the model continues; keep the tail
        events = sorted(
            live_target_events(model, user), key=lambda e: e.event_time
        )
        seq = [
            model.item_index[e.target_entity_id] + 1
            for e in events
            if e.target_entity_id in model.item_index
        ]
        if not seq:
            return None
        # FULL history, untruncated: the unseenOnly exclusion must cover
        # everything the user saw (model mode passes full sequences too);
        # the scorer itself keeps only the max_len tail
        return np.asarray(seq, np.int32)

    @staticmethod
    def _topk_response(model: SASRecModel, scores: np.ndarray, query, prefix) -> dict:
        """Shared exclusion + ranking tail (predict and batch_predict must
        rank identically)."""
        scores = scores.astype(np.float64)
        exclude = (
            {int(i) - 1 for i in prefix} if query.get("unseenOnly", True) else set()
        )
        exclude |= {
            model.item_index[str(b)]
            for b in (query.get("blackList") or [])
            if str(b) in model.item_index
        }
        for j in exclude:
            scores[j] = -np.inf
        return topk_item_scores(model.item_ids, scores, int(query.get("num", 10)))

    def predict(self, model: SASRecModel, query) -> dict:
        prefix = self._resolve_prefix(model, query)
        if prefix is None or len(prefix) == 0:
            return {"itemScores": []}
        scores = score_next_items(model.params, model.config, prefix)
        return self._topk_response(model, scores, query, prefix)

    def batch_predict(self, model: SASRecModel, queries):
        """Vectorized bulk scoring: fixed-size slices of prefixes run the
        transformer forward + vocab projection as ONE device program per
        slice (score_next_items_batch) instead of two dispatches per
        query. Cold/malformed queries fall through to predict()."""
        resolved, fallback = [], []
        for qid, q in queries:
            prefix = self._resolve_prefix(model, q) if isinstance(q, dict) else None
            if prefix is None or len(prefix) == 0:
                fallback.append((qid, q))
            else:
                resolved.append((qid, q, prefix))
        out = []
        if resolved:
            # bound the host [rows, vocab] buffer like the other batch
            # paths; score_next_items_batch pads each slice to a power of
            # two internally, so round DOWN to one so full slices don't
            # overshoot the buffer budget (625 -> 1024 would)
            rows = score_buffer_rows(len(model.item_ids), floor=16, cap=1024)
            rows = 1 << (rows.bit_length() - 1)
            for start in range(0, len(resolved), rows):
                part = resolved[start : start + rows]
                scores = score_next_items_batch(
                    model.params, model.config, [p for _, _, p in part]
                )
                out.extend(
                    (qid, self._topk_response(model, scores[row], q, prefix))
                    for row, (qid, q, prefix) in enumerate(part)
                )
        out.extend((qid, self.predict(model, q)) for qid, q in fallback)
        return out


def engine_factory() -> Engine:
    return Engine(
        data_source_class=SequenceDataSource,
        preparator_class=SequencePreparator,
        algorithm_class_map={"sasrec": SASRecAlgorithm},
        serving_class=FirstServing,
    )
