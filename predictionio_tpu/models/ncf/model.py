"""NeuMF model + sharded training step.

Pure functions over a params pytree (flax.linen for init/apply), so the
training step jits cleanly with explicit shardings:

- params: embeddings sharded over the ``model`` axis on the EMBEDDING dim,
  MLP kernels sharded on their hidden dim (tensor parallelism);
- batch: sharded over the ``data`` axis (data parallelism);
- optimizer: optax Adam; gradients reduce over data via jit's implicit psum.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from predictionio_tpu.parallel.mesh import (
    check_steps_ran,
    fetch_global,
    put_global,
)
from predictionio_tpu.utils.jax_compat import IS_LEGACY_JAX, broadcast_one_to_all


@dataclass
class NCFConfig:
    num_users: int
    num_items: int
    embed_dim: int = 32
    hidden: tuple = (64, 32)
    learning_rate: float = 0.01
    implicit: bool = False      # BCE over sampled negatives vs MSE on ratings
    negatives: int = 4
    batch_size: int = 4096
    epochs: int = 5
    seed: int = 0


class NeuMF(nn.Module):
    config: NCFConfig

    @nn.compact
    def __call__(self, user_ids, item_ids):
        c = self.config
        gmf_u = nn.Embed(c.num_users, c.embed_dim, name="gmf_user")(user_ids)
        gmf_i = nn.Embed(c.num_items, c.embed_dim, name="gmf_item")(item_ids)
        mlp_u = nn.Embed(c.num_users, c.embed_dim, name="mlp_user")(user_ids)
        mlp_i = nn.Embed(c.num_items, c.embed_dim, name="mlp_item")(item_ids)
        gmf = gmf_u * gmf_i
        h = jnp.concatenate([mlp_u, mlp_i], axis=-1)
        for i, width in enumerate(c.hidden):
            h = nn.relu(nn.Dense(width, name=f"mlp_{i}")(h))
        fused = jnp.concatenate([gmf, h], axis=-1)
        return nn.Dense(1, name="out")(fused)[..., 0]


def param_shardings(mesh, params) -> Any:
    """Embedding tables + MLP kernels shard over the 'model' axis.

    Tensors whose trailing dim doesn't divide the model-axis size (e.g. the
    [*, 1] output head) stay replicated."""
    model_size = mesh.shape.get("model", 1)

    def spec_for(path: tuple, leaf) -> P:
        names = [getattr(p, "key", str(p)) for p in path]
        shardable = (
            leaf.ndim == 2 and model_size > 1 and leaf.shape[-1] % model_size == 0
        )
        if shardable and ("embedding" in names or "kernel" in names):
            return P(None, "model")  # [vocab, embed/model] or [in, out/model]
        return P()

    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(mesh, spec_for(path, leaf)), params
    )


def make_train_step(model: NeuMF, optimizer, implicit: bool):
    def loss_fn(params, batch):
        logits = model.apply({"params": params}, batch["user"], batch["item"])
        if implicit:
            return optax.sigmoid_binary_cross_entropy(logits, batch["label"]).mean()
        return ((logits - batch["label"]) ** 2).mean()

    def train_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return train_step


def train_ncf(
    config: NCFConfig,
    users: np.ndarray,
    items: np.ndarray,
    labels: np.ndarray,
    mesh,
    checkpoint=None,
    log_every: int = 0,
):
    """Full training loop; returns the trained params pytree (host)."""
    model = NeuMF(config)
    rng = jax.random.PRNGKey(config.seed)
    params = model.init(
        rng, jnp.zeros((1,), jnp.int32), jnp.zeros((1,), jnp.int32)
    )["params"]
    p_shard = param_shardings(mesh, params)
    data_shard = NamedSharding(mesh, P("data"))
    # put_global (not device_put): every process initialized identical
    # params from the same PRNGKey; on a multi-process mesh each
    # contributes its addressable shards of the tp layout
    params = jax.tree_util.tree_map(put_global, params, p_shard)
    optimizer = optax.adam(config.learning_rate)
    # init AFTER placement, jitted: adam's mu/nu zeros_like the sharded
    # params and inherit the tp layout (eager zeros_like on non-addressable
    # multi-process arrays would fail)
    opt_state = jax.jit(optimizer.init)(params)

    step_fn = jax.jit(
        make_train_step(model, optimizer, config.implicit),
        in_shardings=(
            p_shard,
            None,
            {"user": data_shard, "item": data_shard, "label": data_shard},
        ),
        out_shardings=(p_shard, None, NamedSharding(mesh, P())),
        # donating the tp-sharded adam state miscompiles on legacy (0.4.x)
        # jax: XLA pairs the donated buffers with wrong-shaped outputs.
        # Params alone carry the bulk of the memory; the moments re-donate
        # once the floor moves past the fixed runtime
        donate_argnums=(0,) if IS_LEGACY_JAX else (0, 1),
    )

    np_rng = np.random.default_rng(config.seed)
    n = users.size
    batch = config.batch_size
    n_devices = mesh.shape.get("data", 1)
    n_proc = jax.process_count()
    step = 0
    start_epoch = 0
    # resume must stay rank-SYMMETRIC on multi-process meshes: only rank 0
    # holds a checkpoint manager, but fetch/put of sharded state are
    # collectives every rank joins; the restored state broadcasts from
    # rank 0 so ranks never diverge
    latest = checkpoint.latest_step() if checkpoint is not None else None
    any_checkpoint = checkpoint is not None
    if n_proc > 1:
        flags = broadcast_one_to_all(
            np.int64([1 if any_checkpoint else 0, -1 if latest is None else latest])
        )
        any_checkpoint = bool(int(flags[0]))
        latest = None if int(flags[1]) < 0 else int(flags[1])
    if latest is not None:
        host_state = {
            "params": jax.tree_util.tree_map(fetch_global, params),
            "opt_state": jax.tree_util.tree_map(fetch_global, opt_state),
            "epoch": 0,
        }
        if checkpoint is not None:
            host_state = checkpoint.restore(host_state)
        if n_proc > 1:
            host_state = broadcast_one_to_all(host_state)
        params = jax.tree_util.tree_map(put_global, host_state["params"], p_shard)
        # restore Adam's moments too -- a zeroed mu/nu after resume would
        # spike the first post-resume updates
        opt_state = jax.tree_util.tree_map(
            lambda a, b: put_global(np.asarray(a), b.sharding)
            if hasattr(b, "sharding")
            else a,
            host_state["opt_state"],
            opt_state,
        )
        start_epoch = int(host_state["epoch"]) + 1

    losses = []
    for epoch in range(start_epoch, config.epochs):
        order = np_rng.permutation(n)
        for start in range(0, n, batch):
            take = order[start : start + batch]
            if take.size < max(n_devices, 1):
                continue
            usable = (take.size // n_devices) * n_devices
            take = take[:usable]
            # every process computes the same permutation (same seed), so
            # put_global can hand each exactly its addressable batch shards
            b = {
                "user": put_global(users[take], data_shard),
                "item": put_global(items[take], data_shard),
                "label": put_global(labels[take].astype(np.float32), data_shard),
            }
            params, opt_state, loss = step_fn(params, opt_state, b)
            step += 1
            if log_every and step % log_every == 0:
                losses.append(float(loss))
        if any_checkpoint:
            # the fetches are collectives: when ANY rank checkpoints, EVERY
            # rank joins them each epoch (only rank 0 writes); with no
            # checkpointing anywhere, nobody pays the per-epoch allgather
            epoch_state = {
                "params": jax.tree_util.tree_map(fetch_global, params),
                "opt_state": jax.tree_util.tree_map(fetch_global, opt_state),
                "epoch": epoch,
            }
            if checkpoint is not None:
                checkpoint.save(epoch, epoch_state)
    if start_epoch < config.epochs:
        check_steps_ran(step, n, n_devices, "example")
    return jax.tree_util.tree_map(fetch_global, params), losses


def make_implicit_batches(
    users: np.ndarray, items: np.ndarray, num_items: int, negatives: int, rng
):
    """Positive pairs + sampled negatives -> (users, items, labels)."""
    pos_set = set(zip(users.tolist(), items.tolist()))
    neg_u = np.repeat(users, negatives)
    neg_i = rng.integers(0, num_items, size=neg_u.size)
    keep = np.array([(u, i) not in pos_set for u, i in zip(neg_u, neg_i)])
    all_u = np.concatenate([users, neg_u[keep]])
    all_i = np.concatenate([items, neg_i[keep]])
    all_y = np.concatenate([np.ones(users.size), np.zeros(int(keep.sum()))])
    return all_u, all_i, all_y.astype(np.float32)
