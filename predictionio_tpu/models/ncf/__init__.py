"""Neural Collaborative Filtering template (NeuMF: GMF + MLP).

BASELINE.json config #5: "MLP matrix factorization as Pallas kernel on TPU
mesh" -- the one template with NO reference counterpart (the reference
predates neural recommenders; SURVEY.md section 2.6 flags embedding-table TP
as the natural extension). Design:

- flax model: GMF (elementwise product of user/item embeddings) + MLP tower
  over the concat, fused into one score (NeuMF, He et al. 2017 shape);
- training: optax Adam, jitted step over the ('data', 'model') mesh -- batch
  sharded over data, embedding + hidden dims sharded over model (tensor
  parallelism of the tables);
- serving: a Pallas kernel scores ALL items for one user in a single fused
  pass (gather-free broadcast + both branches + top-k on host).
"""

from predictionio_tpu.models.ncf.engine import NCFAlgorithm, engine_factory

__all__ = ["NCFAlgorithm", "engine_factory"]
