"""DASE components of the Neural-CF template.

Query contract matches the recommendation template:
``{"user": "u1", "num": 4}`` -> ``{"itemScores": [...]}``.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

import numpy as np

from predictionio_tpu.controller import Engine, FirstServing, TPUAlgorithm
from predictionio_tpu.models._als_common import (
    partition_user_queries,
    score_buffer_rows,
    topk_item_scores,
)
from predictionio_tpu.models.ncf.kernel import (
    make_all_items_scorer,
    make_batch_scorer,
    reference_score_all_items,
)
from predictionio_tpu.models.ncf.model import (
    NCFConfig,
    make_implicit_batches,
    train_ncf,
)
from predictionio_tpu.models.recommendation.engine import (
    RatingsData,
    RecommendationDataSource,
)
from predictionio_tpu.controller.base import Preparator


#: guards first-query scorer construction across serving threads
#: (reentrant: scorer() builds through batch_scorer() under the same lock)
_SCORER_BUILD_LOCK = threading.RLock()


class NCFPreparator(Preparator):
    """NCF consumes the COO directly; no CSR packing needed."""

    def prepare(self, ctx, training_data: RatingsData):
        from predictionio_tpu.models._streaming import StreamingHandle

        if isinstance(training_data, StreamingHandle):
            # NCF shares RecommendationDataSource, whose '"reader":
            # "streaming"' mode hands back a handle with no edge arrays;
            # NCF's SGD needs the materialized COO. Fail here with the
            # template named instead of an opaque AttributeError downstream.
            raise ValueError(
                "the NCF template does not support the streaming sharded "
                'reader; remove "reader": "streaming" from the datasource '
                "params (NCF training consumes the materialized COO arrays)"
            )
        return training_data


@dataclass
class NCFModel:
    params: dict
    user_index: dict[str, int]
    item_ids: list[str]
    item_index: dict[str, int]
    seen: dict[int, set[int]]
    use_pallas: bool
    #: "model": the trained-in seen map; "live": per-query event-store
    #: read (O(entities) serving model; fresh interactions filter with no
    #: retrain). Old pickles predate these; readers use getattr defaults.
    seen_mode: str = "model"
    app_name: str = ""
    channel_name: str = None
    event_names: list[str] = None
    #: lazily-built device-resident scorer (tables uploaded once); holds
    #: device buffers and a jit closure, so it must never be pickled into
    #: the model blob -- __getstate__ strips it and deploy rebuilds it via
    #: NCFAlgorithm.warm_up (a cold query would otherwise pay the build)
    _scorer: object = field(default=None, init=False, repr=False, compare=False)
    _batch_scorer: object = field(
        default=None, init=False, repr=False, compare=False
    )

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_scorer"] = None
        state["_batch_scorer"] = None
        return state

    def __setstate__(self, state):
        # blobs pickled by older releases predate the scorer fields;
        # dataclass unpickling bypasses __init__, so default them here or
        # every access raises AttributeError
        state.setdefault("_scorer", None)
        state.setdefault("_batch_scorer", None)
        self.__dict__.update(state)

    def _pallas_with_fallback(self):
        """Pallas all-items scorer that degrades to the XLA reference path.

        A model trained with usePallas=True can deploy onto a host whose
        backend cannot lower the kernel (CPU fallback after an accelerator
        outage). Build failures and first-call lowering failures both log
        once and permanently swap in reference_score_all_items -- a
        working slower path beats a serving endpoint that 500s forever.
        """
        import logging

        n = len(self.item_ids)
        log = logging.getLogger("pio.ncf")
        try:
            fast = make_all_items_scorer(self.params, n, interpret=False)
        except Exception as exc:
            log.warning(
                "Pallas scorer build failed (%s); serving via the XLA "
                "reference path", exc,
            )
            return lambda u: reference_score_all_items(self.params, u, n)
        def score(user_idx):
            nonlocal fast
            if fast is not None:
                try:
                    return fast(user_idx)
                except Exception as exc:
                    # drop the dead scorer so its device-resident tables
                    # (full embedding + MLP uploads) are freed, not pinned
                    # for the model's serving lifetime on a degraded host
                    fast = None
                    log.warning(
                        "Pallas scorer failed at call time (%s); falling "
                        "back to the XLA reference path permanently", exc,
                    )
            return reference_score_all_items(self.params, user_idx, n)

        return score

    def scorer(self):
        # the query server is a ThreadingHTTPServer: concurrent first
        # queries must not each upload the tables and compile the kernel
        # (double-checked under a module lock; a per-model lock would not
        # survive pickling)
        if self._scorer is None:
            with _SCORER_BUILD_LOCK:
                if self._scorer is None:
                    if self.use_pallas:
                        self._scorer = self._pallas_with_fallback()
                    else:
                        # route single queries through the SAME jitted
                        # program family the micro-batched path uses
                        # (bucket of 1): batched and unbatched serving
                        # answers stay numerically identical, and a lone
                        # query still beats the numpy reference walk
                        try:
                            batch = self.batch_scorer()
                            self._scorer = lambda u: batch(
                                np.asarray([u], np.int32)
                            )[0]
                        except Exception:
                            # the fallback serves, but batched and single
                            # answers are no longer the same program --
                            # say so, or the identity loss is undebuggable
                            import logging

                            logging.getLogger("pio.ncf").warning(
                                "batch scorer build failed; single-query "
                                "serving falls back to the numpy reference "
                                "path (batched/unbatched responses may "
                                "differ at float precision)", exc_info=True,
                            )
                            n = len(self.item_ids)
                            self._scorer = (
                                lambda u: reference_score_all_items(
                                    self.params, u, n
                                )
                            )
        return self._scorer

    def batch_scorer(self):
        if self._batch_scorer is None:
            with _SCORER_BUILD_LOCK:
                if self._batch_scorer is None:
                    self._batch_scorer = make_batch_scorer(
                        self.params, len(self.item_ids)
                    )
        return self._batch_scorer


class NCFAlgorithm(TPUAlgorithm):
    """Params: embedDim, hidden, learningRate, epochs, batchSize, implicit,
    negatives, seed, usePallas (serving kernel; auto-off on CPU)."""

    def train(self, ctx, data: RatingsData) -> NCFModel:
        import jax

        p = self.params
        config = NCFConfig(
            num_users=data.num_users,
            num_items=data.num_items,
            embed_dim=p.get_or("embedDim", 32),
            hidden=tuple(p.get_or("hidden", [64, 32])),
            learning_rate=p.get_or("learningRate", 0.01),
            implicit=p.get_or("implicit", False),
            negatives=p.get_or("negatives", 4),
            batch_size=p.get_or("batchSize", 4096),
            epochs=p.get_or("epochs", 5),
            seed=p.get_or("seed", 0),
        )
        users, items, labels = data.users, data.items, data.ratings
        if config.implicit:
            users, items, labels = make_implicit_batches(
                users, items, data.num_items, config.negatives,
                np.random.default_rng(config.seed),
            )
        checkpoint = None
        if p.get_or("checkpoint", True):
            # keyed on the workflow's stable run_key (variant+params hash),
            # so `pio train --resume` after preemption finds the crashed
            # attempt's epochs -- the round-1 instance-id key could not
            checkpoint = ctx.checkpoint_manager("ncf")
        seen_mode = p.get_or("seenFilter", "model")
        if seen_mode not in ("model", "live"):
            # before the (expensive) training run, not after
            raise ValueError(
                f"seenFilter must be 'model' or 'live', got {seen_mode!r}"
            )
        params, _ = train_ncf(
            config, users, items, labels, ctx.mesh, checkpoint=checkpoint
        )
        if seen_mode == "live" and getattr(data, "eval_fold", False):
            # a live read would -inf every held-out item (they still exist
            # in the store) and zero eval metrics; fold data carries its
            # train edges, so the trained-in map is correct there
            seen_mode = "model"
        seen: dict[int, set[int]] = {}
        if seen_mode == "model":
            for u, i in zip(data.users, data.items):
                seen.setdefault(int(u), set()).add(int(i))
        backend = jax.devices()[0].platform
        return NCFModel(
            params=params,
            user_index={uid: j for j, uid in enumerate(data.user_ids)},
            item_ids=data.item_ids,
            item_index={iid: j for j, iid in enumerate(data.item_ids)},
            seen=seen,
            use_pallas=p.get_or("usePallas", backend not in ("cpu",)),
            seen_mode=seen_mode,
            app_name=getattr(data, "app_name", ""),
            channel_name=getattr(data, "channel_name", None),
            event_names=getattr(data, "event_names", None),
        )

    def warm_up(self, model: NCFModel) -> None:
        """Build both serving scorers at deploy (tables upload + kernel
        compile), not on the first unlucky query: /queries.json serves
        through scorer(), the batch-predict workflow through
        batch_scorer() -- prepare_deploy precedes both."""
        model.scorer()
        model.batch_scorer()

    @staticmethod
    def _seen(model: NCFModel, query, user_idx, cache=None) -> set[int]:
        if getattr(model, "seen_mode", "model") != "live":
            return model.seen.get(user_idx, set())
        from predictionio_tpu.models._streaming import live_seen_indices

        return live_seen_indices(model, str(query.get("user")), cache)

    @staticmethod
    def _topk_response(model: NCFModel, scores: np.ndarray, query, user_idx,
                       seen_cache=None) -> dict:
        """Shared exclusion + ranking tail (predict and batch_predict must
        rank identically)."""
        exclude = {
            model.item_index[str(b)]
            for b in (query.get("blackList") or [])
            if str(b) in model.item_index
        }
        if query.get("unseenOnly", True):
            exclude |= NCFAlgorithm._seen(model, query, user_idx, seen_cache)
        scores = scores.astype(np.float64)
        for j in exclude:
            scores[j] = -np.inf
        return topk_item_scores(
            model.item_ids, scores, int(query.get("num", 10))
        )

    def predict(self, model: NCFModel, query) -> dict:
        user_idx = model.user_index.get(str(query.get("user")))
        if user_idx is None:
            return {"itemScores": []}
        return self._topk_response(model, model.scorer()(user_idx), query, user_idx)

    def batch_predict(self, model: NCFModel, queries):
        """Vectorized bulk scoring: chunks of known users score against the
        full catalog in ONE device program each (make_batch_scorer),
        instead of a 2-round-trip dispatch per query -- the reference's
        P2LAlgorithm broadcast batchPredict, as XLA batching. Cold users
        and malformed queries fall through to predict()."""
        user_rows, fallback = partition_user_queries(model.user_index, queries)
        out = []
        if user_rows:
            # bound the host [rows, items] score buffer (the device-side
            # pair budget caps only the on-device intermediates)
            rows_per_slice = score_buffer_rows(len(model.item_ids))
            scorer = model.batch_scorer()
            seen_cache: dict = {}
            for start in range(0, len(user_rows), rows_per_slice):
                part = user_rows[start : start + rows_per_slice]
                scores = scorer(
                    np.fromiter((u for _, _, u in part), dtype=np.int32)
                )
                out.extend(
                    (qid, self._topk_response(model, scores[row], q, user_idx,
                                              seen_cache=seen_cache))
                    for row, (qid, q, user_idx) in enumerate(part)
                )
        out.extend((qid, self.predict(model, q)) for qid, q in fallback)
        return out


def engine_factory() -> Engine:
    # NCF shares RecommendationDataSource, so it inherits the time-travel
    # replay hook (read_replay) and works with `pio eval --replay` as-is:
    # the replay fold is a RatingsData slice, which NCFPreparator re-reads
    # with implicit weights exactly like the train path.
    return Engine(
        data_source_class=RecommendationDataSource,
        preparator_class=NCFPreparator,
        algorithm_class_map={"ncf": NCFAlgorithm},
        serving_class=FirstServing,
    )
