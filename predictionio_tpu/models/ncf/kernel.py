"""Pallas kernel: fused all-items NeuMF scoring for one user.

The serving hot path scores EVERY item for a user (then top-k). Done naively
that is four HBM-bound passes (gmf mult, concat, two dense layers). This
kernel fuses the whole NeuMF head over item tiles resident in VMEM:

    score[i] = w_out . [gmf_u * gmf_item[i] ; mlp(mlp_u ++ mlp_item[i])]

Item embedding tables stream through VMEM in (TILE_I, E) blocks; the user's
vectors and the MLP weights (small) are broadcast to every grid step. One
HBM read of the tables per query -> bandwidth-bound at the theoretical
minimum. On CPU test backends the kernel runs in interpret mode.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np

from predictionio_tpu.utils.jax_compat import pallas as pl

# 1024 = XLA's tile for 1-D f32 arrays (8 sublanes x 128 lanes): the
# kernel's output block must match it exactly -- real TPU lowering rejects
# a T(512) Mosaic layout against XLA's T(1024) (interpret mode cannot see
# the mismatch), and 2-D (1, TILE) output blocks fail the (8, 128)
# divisibility rule
TILE_I = 1024


def _ncf_score_kernel(
    gmf_item_ref,  # [TILE_I, E]
    mlp_item_ref,  # [TILE_I, E]
    gmf_user_ref,  # [1, E]
    mlp_user_ref,  # [1, E]
    w0u_ref,       # [E, H0]   (user half of the first MLP kernel)
    w0i_ref,       # [E, H0]   (item half)
    b0_ref,        # [1, H0]
    w1_ref,        # [H0, H1]
    b1_ref,        # [1, H1]
    wog_ref,       # [1, E]    (output weights, gmf part)
    woh_ref,       # [1, H1]   (output weights, mlp part)
    bo_ref,        # [1, 1]
    out_ref,       # [TILE_I]
):
    gmf = gmf_item_ref[:] * gmf_user_ref[0][None, :]
    # first dense over the concat == split matmul (avoids concat in VMEM)
    h = (
        mlp_user_ref[:] @ w0u_ref[:]
        + mlp_item_ref[:] @ w0i_ref[:]
        + b0_ref[0][None, :]
    )
    h = jnp.maximum(h, 0.0)
    h = jnp.maximum(h @ w1_ref[:] + b1_ref[0][None, :], 0.0)
    # final projections as multiply+reduce (VPU) -- a [., 1] matmul would
    # fight the 128-lane tiling for no gain
    score = (
        jnp.sum(gmf * wog_ref[0][None, :], axis=1)
        + jnp.sum(h * woh_ref[0][None, :], axis=1)
        + bo_ref[0, 0]
    )
    out_ref[:] = score


def _mlp_depth(params) -> int:
    return len([k for k in params if k.startswith("mlp_") and k[4:].isdigit()])


def make_all_items_scorer(params, num_items: int, interpret: bool):
    """Build a host-callable ``score(user_index) -> np.ndarray[num_items]``.

    The item tables and MLP weights upload to the device ONCE at build
    time, and each call is a single jitted dispatch (the user-row gather
    runs on device) plus one result fetch. The per-call construction this
    replaces re-uploaded ~13 operands and re-dispatched eagerly -- on the
    remote-tunnel TPU backend that cost ~860 ms/query in round-trips; the
    cached scorer measures ~2 orders of magnitude faster.

    The kernel is specialized to the default 2-hidden-layer tower; other
    depths fall back to the (XLA-fused anyway) reference head.
    """
    if _mlp_depth(params) != 2:
        return lambda user_index: reference_score_all_items(
            params, user_index, num_items
        )
    e = params["gmf_user"]["embedding"].shape[1]
    h0 = params["mlp_0"]["kernel"].shape[1]
    h1 = params["mlp_1"]["kernel"].shape[1]

    gmf_items = np.asarray(params["gmf_item"]["embedding"], np.float32)
    mlp_items = np.asarray(params["mlp_item"]["embedding"], np.float32)
    padded = ((num_items + TILE_I - 1) // TILE_I) * TILE_I
    if padded != gmf_items.shape[0]:
        pad = padded - gmf_items.shape[0]
        gmf_items = np.pad(gmf_items, ((0, pad), (0, 0)))
        mlp_items = np.pad(mlp_items, ((0, pad), (0, 0)))

    w0 = np.asarray(params["mlp_0"]["kernel"], np.float32)   # [2E, H0]
    out_w = np.asarray(params["out"]["kernel"], np.float32)  # [E+H1, 1]
    device = jax.devices()[0] if not interpret else None
    put = (lambda a: jax.device_put(jnp.asarray(a), device)) if device else jnp.asarray
    gmf_items_d = put(gmf_items)
    mlp_items_d = put(mlp_items)
    gmf_user_tab = put(np.asarray(params["gmf_user"]["embedding"], np.float32))
    mlp_user_tab = put(np.asarray(params["mlp_user"]["embedding"], np.float32))
    weights = (
        put(w0[:e]),
        put(w0[e:]),
        put(np.asarray(params["mlp_0"]["bias"], np.float32)[None, :]),
        put(np.asarray(params["mlp_1"]["kernel"], np.float32)),
        put(np.asarray(params["mlp_1"]["bias"], np.float32)[None, :]),
        put(np.asarray(out_w[:e, 0])[None, :]),
        put(np.asarray(out_w[e:, 0])[None, :]),
        put(np.asarray(params["out"]["bias"], np.float32).reshape(1, 1)),
    )

    grid = padded // TILE_I
    tile_spec = lambda: pl.BlockSpec((TILE_I, e), lambda i: (i, 0))
    rep = lambda r, c: pl.BlockSpec((r, c), lambda i: (0, 0))
    call = pl.pallas_call(
        _ncf_score_kernel,
        out_shape=jax.ShapeDtypeStruct((padded,), jnp.float32),
        grid=(grid,),
        in_specs=[
            tile_spec(),
            tile_spec(),
            rep(1, e),
            rep(1, e),
            rep(e, h0),
            rep(e, h0),
            rep(1, h0),
            rep(h0, h1),
            rep(1, h1),
            rep(1, e),
            rep(1, h1),
            rep(1, 1),
        ],
        out_specs=pl.BlockSpec((TILE_I,), lambda i: (i,)),
        interpret=interpret,
    )

    @jax.jit
    def score(user_idx):
        gmf_u = jax.lax.dynamic_slice_in_dim(gmf_user_tab, user_idx, 1)
        mlp_u = jax.lax.dynamic_slice_in_dim(mlp_user_tab, user_idx, 1)
        return call(gmf_items_d, mlp_items_d, gmf_u, mlp_u, *weights)

    return lambda user_index: np.asarray(score(np.int32(user_index)))[:num_items]


def ncf_score_all_items(params, user_index: int, num_items: int, interpret: bool):
    """One-shot convenience around :func:`make_all_items_scorer` (tests,
    oracles). Serving paths should build the scorer once and reuse it."""
    return make_all_items_scorer(params, num_items, interpret)(user_index)


def make_batch_scorer(params, num_items: int, pair_budget: int = 2_000_000):
    """Host-callable ``scores(user_indices [U]) -> np [U, num_items]``.

    The ``pio batchpredict`` engine of NCF: one jitted device call scores a
    whole chunk of users against the full catalog (the reference's
    P2LAlgorithm broadcast-batchPredict parallelism as a single XLA
    program), instead of one 2-round-trip dispatch per query. Works for
    ANY tower depth (plain jnp forward, not the depth-2 Pallas kernel).
    Chunks are sized so the [U, I, feature] intermediates stay bounded
    (~``pair_budget`` user-item pairs per call); the python-visible
    function accepts any U and slices internally.
    """
    depth = _mlp_depth(params)
    dev_params = jax.tree_util.tree_map(
        lambda a: jnp.asarray(np.asarray(a, np.float32)), dict(params)
    )

    @jax.jit
    def chunk_scores(user_idx):                              # [u] -> [u, I]
        gmf_u = dev_params["gmf_user"]["embedding"][user_idx]     # [u, E]
        mlp_u = dev_params["mlp_user"]["embedding"][user_idx]
        gmf_i = dev_params["gmf_item"]["embedding"][:num_items]   # [I, E]
        mlp_i = dev_params["mlp_item"]["embedding"][:num_items]
        u, e = gmf_u.shape
        gmf = gmf_u[:, None, :] * gmf_i[None, :, :]               # [u, I, E]
        h = jnp.concatenate(
            [
                jnp.broadcast_to(mlp_u[:, None, :], (u, num_items, e)),
                jnp.broadcast_to(mlp_i[None, :, :], (u, num_items, e)),
            ],
            axis=-1,
        )
        for layer in range(depth):
            h = jnp.maximum(
                h @ dev_params[f"mlp_{layer}"]["kernel"]
                + dev_params[f"mlp_{layer}"]["bias"],
                0.0,
            )
        fused = jnp.concatenate([gmf, h], axis=-1)
        return (
            fused @ dev_params["out"]["kernel"] + dev_params["out"]["bias"]
        )[..., 0]

    chunk = max(1, pair_budget // max(num_items, 1))

    def bucket(n: int) -> int:
        # pad ragged calls to the next power of two, not to the full
        # chunk: offline bulk runs still see the one big chunk shape, but
        # a serving micro-batch of 16 must not pay a 400-row program.
        # Compiled-shape count stays bounded at log2(chunk).
        b = 1
        while b < n:
            b <<= 1
        return min(b, chunk)

    def scores(user_indices) -> np.ndarray:
        user_indices = np.asarray(user_indices, np.int32)
        out = np.empty((user_indices.size, num_items), np.float32)
        for start in range(0, user_indices.size, chunk):
            part = user_indices[start : start + chunk]
            n = part.size
            pad = bucket(n)
            if n < pad:
                part = np.pad(part, (0, pad - n))
            out[start : start + n] = np.asarray(
                chunk_scores(jnp.asarray(part))
            )[:n]
        return out

    return scores


def reference_score_all_items(params, user_index: int, num_items: int) -> np.ndarray:
    """Plain-numpy NeuMF head for ANY tower depth (kernel oracle + CPU path)."""
    gmf_u = np.asarray(params["gmf_user"]["embedding"][user_index])
    mlp_u = np.asarray(params["mlp_user"]["embedding"][user_index])
    gmf_i = np.asarray(params["gmf_item"]["embedding"][:num_items])
    mlp_i = np.asarray(params["mlp_item"]["embedding"][:num_items])
    gmf = gmf_i * gmf_u
    h = np.concatenate([np.broadcast_to(mlp_u, mlp_i.shape), mlp_i], axis=1)
    for layer in range(_mlp_depth(params)):
        h = np.maximum(
            h @ np.asarray(params[f"mlp_{layer}"]["kernel"])
            + np.asarray(params[f"mlp_{layer}"]["bias"]),
            0.0,
        )
    fused = np.concatenate([gmf, h], axis=1)
    return (
        fused @ np.asarray(params["out"]["kernel"]) + np.asarray(params["out"]["bias"])
    )[:, 0]
