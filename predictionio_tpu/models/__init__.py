"""Engine templates: the model zoo the reference ecosystem ships.

Reference counterparts (SURVEY.md section 2.5 #37 -- template repos define
the zoo): recommendation (MLlib ALS), classification (NaiveBayes/LogReg),
similar-product (cooccurrence), universal recommender (CCO/LLR), plus the
new Neural-CF Pallas template (BASELINE.json config #5). Each template is a
complete DASE engine usable via engine.json or programmatically.
"""
