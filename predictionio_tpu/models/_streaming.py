"""Shared plumbing for the templates' streaming sharded-reader mode.

One definition of: the lazy DataSource handle (``"reader": "streaming"``),
its construction from datasource params, and the serving-time live
event-store lookup that replaces O(edges) trained-in history maps.
The recommendation, similar-product, and universal templates all build on
these; template-specific behavior (bucketing, multi-event universes,
index mapping) stays in the engines.
"""

from __future__ import annotations

import datetime as _dt
import logging
from dataclasses import dataclass, field

from predictionio_tpu.controller.base import SanityCheck

logger = logging.getLogger("pio.streaming")


@dataclass
class StreamingHandle(SanityCheck):
    """Lazy training handle: no arrays, just where/what to stream.

    The preparator/algorithm streams the store's chunked columnar scan
    through parallel.reader; each process retains only its data-shard's
    edges.
    """

    app_name: str
    app_id: int
    channel_id: int | None
    channel_name: str | None
    event_names: list[str]
    rating_key: str = "rating"
    chunk_rows: int = 262_144
    #: events whose absence means "no data" (UR probes the primary type
    #: only); None probes all of event_names
    probe_event_names: list[str] | None = None
    empty_message: str = "no events found -- check appName and eventNames"
    #: template-specific DATASOURCE knobs the preparator/algorithm need
    #: (e.g. e-commerce buyWeight/buyEvents): DASE keeps per-component
    #: params separate, so values configured on the datasource must ride
    #: the handle to reach the streaming build
    extras: dict = field(default_factory=dict)
    #: EXCLUSIVE scan bound captured when the handle is created: every
    #: pass on every process reads the identical event prefix, so writes
    #: landing mid-train can neither crash pass 2 (an entity pass 1 never
    #: counted) nor make multi-host processes derive divergent layouts.
    #: It is also the snapshot layer's coverage boundary.
    until_time: _dt.datetime = field(
        default_factory=lambda: _dt.datetime.now(_dt.timezone.utc)
    )

    def sanity_check(self) -> None:
        from predictionio_tpu.data import storage

        probe = list(
            storage.get_l_events().find(
                app_id=self.app_id,
                channel_id=self.channel_id,
                event_names=self.probe_event_names or self.event_names,
                limit=1,
            )
        )
        if not probe:
            raise ValueError(self.empty_message)


def build_streaming_handle(
    params,
    default_event_names: list[str],
    probe_primary_only: bool = False,
    empty_message: str | None = None,
) -> StreamingHandle:
    """Build the scan descriptor a datasource's params pin down --
    unconditionally. ``streaming_handle_or_none`` gates it on the
    ``"reader": "streaming"`` opt-in for training; the continuous-learning
    loop (``DataSource.online_handle``) builds one regardless, because the
    handle is also the identity of the snapshot the loop refreshes and the
    WAL filter it follows."""
    from predictionio_tpu.data.store import resolve_app_channel

    event_names = params.get_or("eventNames", default_event_names)
    app_id, channel_id = resolve_app_channel(
        params.appName, params.get_or("channelName", None)
    )
    return StreamingHandle(
        app_name=params.appName,
        app_id=app_id,
        channel_id=channel_id,
        channel_name=params.get_or("channelName", None),
        event_names=list(event_names),
        rating_key=params.get_or("ratingKey", "rating"),
        chunk_rows=params.get_or("chunkRows", 262_144),
        probe_event_names=[event_names[0]] if probe_primary_only else None,
        empty_message=empty_message
        or "no events found -- check appName and eventNames",
    )


def streaming_handle_or_none(
    params,
    default_event_names: list[str],
    probe_primary_only: bool = False,
    empty_message: str | None = None,
) -> StreamingHandle | None:
    """The shared ``read_training`` branch: a StreamingHandle when the
    datasource params opt in (``"reader": "streaming"``), else None."""
    if params.get_or("reader", "materialized") != "streaming":
        return None
    return build_streaming_handle(
        params, default_event_names, probe_primary_only, empty_message
    )


def live_target_events(model, user: str) -> list:
    """The query user's item-target events, read live from the store.

    Reads the model's ``app_name``/``channel_name``/``event_names``
    (getattr-safe: pickled models may predate the fields). Degrades to an
    empty list -- with one warning -- on any store error: serving must
    not 500 because a backend blinked. An unresolvable app short-circuits
    without a per-request failing lookup.
    """
    app_name = getattr(model, "app_name", "")
    if not user or not app_name:
        return []
    from predictionio_tpu.data.store import LEventStore

    try:
        return list(
            LEventStore.find(
                app_name,
                entity_type="user",
                entity_id=user,
                channel_name=getattr(model, "channel_name", None),
                event_names=getattr(model, "event_names", None) or None,
                target_entity_type="item",
            )
        )
    except Exception:
        logger.warning(
            "live history lookup failed; serving without user history",
            exc_info=True,
        )
        return []


def live_seen_indices(model, user: str, cache: dict | None = None) -> set[int]:
    """The user's already-interacted item indices, read live.

    THE live seen-lookup (recommendation, NCF, and e-commerce all filter
    through it): item ids map through ``model.item_index``; ``cache``
    memoizes per user for bulk paths. Store errors degrade inside
    live_target_events.
    """
    key = user
    if cache is not None and key in cache:
        return cache[key]
    out = {
        model.item_index[e.target_entity_id]
        for e in live_target_events(model, user)
        if e.target_entity_id in model.item_index
    }
    if cache is not None:
        cache[key] = out
    return out


def _agree_until_time(handle: StreamingHandle) -> None:
    """Multi-process launches: adopt rank 0's captured scan bound.

    Each process captures ``until_time`` at its own handle creation, so
    wall-clock skew between launches would bound their scans differently
    -- exactly the divergent-layout bug the bound exists to kill. The
    bound is broadcast as integer microseconds and reconstructed with
    integer arithmetic, so every process derives a bit-identical datetime
    (and therefore an identical ``event_time_ms`` cutoff)."""
    until = getattr(handle, "until_time", None)
    if until is None:
        return
    try:
        import jax

        if jax.process_count() <= 1:
            return
        import numpy as np

        from predictionio_tpu.utils.jax_compat import broadcast_one_to_all

        local_us = int(until.timestamp() * 1e6)
        agreed_us = int(broadcast_one_to_all(np.int64(local_us)))
        # EVERY rank adopts the reconstructed value -- rank 0 included:
        # int(timestamp()*1e6) can truncate 1us below the original
        # datetime, so keeping the original on rank 0 could still put its
        # ms cutoff one ahead of everyone else's at a boundary
        handle.until_time = _dt.datetime.fromtimestamp(
            agreed_us // 10**6, tz=_dt.timezone.utc
        ) + _dt.timedelta(microseconds=agreed_us % 10**6)
    except Exception:
        logger.warning(
            "could not agree on a cross-process scan bound; using the"
            " local one",
            exc_info=True,
        )


def _snapshot_for_handle(handle: StreamingHandle, runtime_conf):
    """The handle's ready training snapshot, or None (mode off, backend
    without the columnar scan, or any snapshot-layer failure -- training
    must degrade to the direct scan, never die on a cache)."""
    from predictionio_tpu.data import storage
    from predictionio_tpu.data.snapshot import (
        SnapshotSpec,
        SnapshotStore,
        snapshot_settings,
    )

    mode, root = snapshot_settings(runtime_conf)
    if mode == "off":
        return None
    if mode == "use":
        try:
            import jax

            if jax.process_count() > 1:
                # per-host snapshot state differs (local disks, different
                # build histories); "use" would let each process replay a
                # DIFFERENT prefix. "refresh" converges every process onto
                # the agreed bound exactly -- same rows, same layout.
                logger.info(
                    "multi-process launch: snapshot mode 'use' escalated to"
                    " 'refresh' so every process replays the agreed bound"
                )
                mode = "refresh"
        except Exception:
            pass
    le = storage.get_l_events()
    spec = SnapshotSpec(
        app_id=handle.app_id,
        channel_id=handle.channel_id,
        event_names=tuple(handle.event_names) if handle.event_names else None,
        rating_key=handle.rating_key,
    )
    try:
        return SnapshotStore(root, spec).ensure(
            le,
            mode,
            until_time=getattr(handle, "until_time", None),
            chunk_rows=handle.chunk_rows,
        )
    except Exception:
        logger.warning(
            "training snapshot unavailable for app %r; falling back to the"
            " direct store scan",
            handle.app_name,
            exc_info=True,
        )
        return None


def snapshot_ratings_arrays(handle: StreamingHandle, runtime_conf=None):
    """Materialized COO arrays replayed from the handle's ready snapshot
    generation, or None when snapshots are off/unavailable.

    Returns ``(users, items, ratings, times, user_ids, item_ids)`` --
    the exact shape a datasource's materialized ``_read`` produces, but
    served from the PR-3 memmap columns: a replay evaluation under
    ``--snapshot-mode use`` trains its prefix with zero SQL scans, and a
    second run replays the same pinned generation bit-for-bit.
    """
    import numpy as np

    snap = _snapshot_for_handle(handle, runtime_conf)
    if snap is None:
        return None
    from predictionio_tpu.parallel.reader import snapshot_coo_chunks

    source, users_enc, items_enc = snapshot_coo_chunks(
        snap, chunk_rows=handle.chunk_rows
    )
    chunks = list(source())
    if chunks:
        users = np.concatenate([c[0] for c in chunks])
        items = np.concatenate([c[1] for c in chunks])
        ratings = np.concatenate([c[2] for c in chunks])
        times = np.concatenate([c[3] for c in chunks])
    else:
        users = np.empty(0, np.int64)
        items = np.empty(0, np.int64)
        ratings = np.empty(0, np.float32)
        times = np.empty(0, np.float64)
    return users, items, ratings, times, list(users_enc.ids), list(items_enc.ids)


def streaming_coo_source(
    handle: StreamingHandle,
    runtime_conf=None,
    event_values: dict[str, float] | None = None,
):
    """(source, users_enc, items_enc) for a handle: snapshot-served memmap
    replay when ``--snapshot-mode`` enables it, else the bounded store
    scan. Both yield bit-identical chunk streams over the same prefix."""
    from predictionio_tpu.data import storage
    from predictionio_tpu.parallel.reader import (
        snapshot_coo_chunks,
        store_coo_chunks,
    )

    _agree_until_time(handle)
    snap = _snapshot_for_handle(handle, runtime_conf)
    if snap is not None:
        return snapshot_coo_chunks(
            snap, chunk_rows=handle.chunk_rows, event_values=event_values
        )
    return store_coo_chunks(
        storage.get_l_events(),
        handle.app_id,
        channel_id=handle.channel_id,
        event_names=handle.event_names,
        rating_key=handle.rating_key,
        chunk_rows=handle.chunk_rows,
        event_values=event_values,
        until_time=getattr(handle, "until_time", None),
    )


def streaming_multi_event_sources(handle: StreamingHandle, runtime_conf=None):
    """Per-event-type sources over one shared universe (the UR build):
    snapshot replay when enabled, else the bounded multi-type store scan.
    Returns ``(sources, users_enc, items_enc, universe_ready)`` --
    ``universe_ready`` is True when the encoders are already complete
    (snapshot replay), letting the caller skip the priming scan."""
    from predictionio_tpu.data import storage
    from predictionio_tpu.parallel.reader import (
        snapshot_multi_event_chunks,
        store_multi_event_chunks,
    )

    _agree_until_time(handle)
    snap = _snapshot_for_handle(handle, runtime_conf)
    if snap is not None:
        sources, users_enc, items_enc = snapshot_multi_event_chunks(
            snap, handle.event_names, chunk_rows=handle.chunk_rows
        )
        return sources, users_enc, items_enc, True
    sources, users_enc, items_enc = store_multi_event_chunks(
        storage.get_l_events(),
        handle.app_id,
        handle.event_names,
        channel_id=handle.channel_id,
        chunk_rows=handle.chunk_rows,
        until_time=getattr(handle, "until_time", None),
    )
    return sources, users_enc, items_enc, False


def resolve_als_feed(preparator_params, runtime_conf=None) -> str:
    """The ALS feed mode: ``pio train --als-feed`` (runtime conf
    ``pio.als_feed``) overrides the engine's ``alsFeed`` preparator param;
    default ``resident`` (device-resident edge arrays, the pre-PR-10
    path). ``streamed`` packs a disk block store and trains through
    ALX device-resident epochs (``als_fit_streamed``)."""
    conf = runtime_conf or {}
    feed = (
        conf.get("pio.als_feed")
        or preparator_params.get_or("alsFeed", "resident")
    )
    if feed not in ("resident", "streamed"):
        raise ValueError(
            f"alsFeed must be 'resident' or 'streamed', got {feed!r}"
        )
    return feed


def build_streaming_als(handle: StreamingHandle, preparator_params, mesh,
                        event_values: dict[str, float] | None = None,
                        runtime_conf=None):
    """The shared streaming ALS build both ALS-family templates run:
    chunked store scan (or snapshot memmap replay) -> retention-bounded
    sharded pack. Returns ``(users_enc, items_enc, als_data)``; the caller
    assembles its own template-specific data carrier around the
    vocabularies. ``runtime_conf`` (the RuntimeContext's) carries the
    ``pio.snapshot_mode``/``pio.snapshot_dir`` opt-in.

    With ``alsFeed: streamed`` (or ``pio train --als-feed streamed``) and
    a ready snapshot, ``als_data`` comes back as a ``parallel.stream.
    StreamedALSData`` block store packed straight from the snapshot's
    memmap columns (``reader.snapshot_streamed_als_data``) --
    ``fit_with_checkpoint`` dispatches it to ALX device-resident
    streamed epochs. Without a snapshot the streamed feed degrades to
    the resident pack with a warning: feed choice tunes memory, it must
    never fail a train.
    """
    from predictionio_tpu.parallel.als import ALSConfig
    from predictionio_tpu.parallel.reader import build_als_data_sharded

    config = ALSConfig(
        max_len=preparator_params.get_or("maxEventsPerUser", None),
        buckets=preparator_params.get_or("buckets", 1),
    )
    if resolve_als_feed(preparator_params, runtime_conf) == "streamed":
        from predictionio_tpu.parallel.reader import snapshot_streamed_als_data

        _agree_until_time(handle)
        snap = _snapshot_for_handle(handle, runtime_conf)
        if snap is not None:
            return snapshot_streamed_als_data(
                snap, config, mesh=mesh,
                model_shards=mesh.shape.get("model", 1) if mesh is not None else 1,
                chunk_rows=handle.chunk_rows,
                event_values=event_values,
            )
        logger.warning(
            "alsFeed 'streamed' needs a training snapshot (--snapshot-mode"
            " use|refresh); falling back to the resident feed"
        )
    source, users_enc, items_enc = streaming_coo_source(
        handle, runtime_conf=runtime_conf, event_values=event_values
    )
    als_data = build_als_data_sharded(
        source, None, None, config, mesh,
        model_shards=mesh.shape.get("model", 1),
    )
    return users_enc, items_enc, als_data
