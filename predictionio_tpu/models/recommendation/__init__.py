"""Recommendation template: ALS matrix factorization on the TPU mesh.

Reference counterpart: predictionio-template-recommender (MLlib ALS engine:
DataSource reading rate/buy events, ALSAlgorithm wrapping
``org.apache.spark.mllib.recommendation.ALS``, top-k serving) -- SURVEY.md
section 2.5 #37 and BASELINE.json configs #1. The math lives in
``predictionio_tpu.parallel.als``; this module is the DASE packaging.
"""

from predictionio_tpu.models.recommendation.engine import (
    ALSAlgorithm,
    RecommendationDataSource,
    RecommendationPreparator,
    engine_factory,
)

__all__ = [
    "ALSAlgorithm",
    "RecommendationDataSource",
    "RecommendationPreparator",
    "engine_factory",
]
