"""DASE components of the recommendation template.

Query contract (reference template quickstart):
``{"user": "u1", "num": 4}`` -> ``{"itemScores": [{"item": ..., "score": ...}]}``
plus item-based queries ``{"items": [...], "num": k}`` for similarity.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

import numpy as np

from predictionio_tpu.controller import (
    DataSource,
    Engine,
    EvalInfo,
    FirstServing,
    Preparator,
    TPUAlgorithm,
)
from predictionio_tpu.controller.base import SanityCheck
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.parallel.als import (
    ALSConfig,
    ALSModel,
    als_fit,
    build_als_data,
)

logger = logging.getLogger("pio.recommendation")


@dataclass
class RatingsData(SanityCheck):
    """COO interactions + id vocabularies."""

    users: np.ndarray       # int indices
    items: np.ndarray
    ratings: np.ndarray     # float32
    times: np.ndarray       # float64 epoch seconds
    user_ids: list[str]
    item_ids: list[str]

    def sanity_check(self) -> None:
        if self.users.size == 0:
            raise ValueError(
                "no rating events found -- check appName and eventNames"
            )

    @property
    def num_users(self) -> int:
        return len(self.user_ids)

    @property
    def num_items(self) -> int:
        return len(self.item_ids)


class RecommendationDataSource(DataSource):
    """Reads rating-like events into COO form.

    Params: ``appName`` (required), ``eventNames`` (default ["rate", "buy"]),
    ``ratingKey`` (property holding the rating; "buy"-style events without it
    score 1.0), ``evalK``/``evalFolds`` for read_eval.
    """

    def _read(self) -> RatingsData:
        event_names = self.params.get_or("eventNames", ["rate", "buy"])
        ds = PEventStore.dataset(
            self.params.appName,
            rating_key=self.params.get_or("ratingKey", "rating"),
            event_names=event_names,
            target_entity_type="item",
        )
        ratings = np.nan_to_num(ds.ratings, nan=1.0)  # implicit events -> 1.0
        valid = ds.target_entity_ids >= 0
        return RatingsData(
            users=ds.entity_ids[valid],
            items=ds.target_entity_ids[valid],
            ratings=ratings[valid],
            times=ds.event_times[valid],
            user_ids=ds.entity_id_vocab,
            item_ids=ds.target_entity_id_vocab,
        )

    def read_training(self, ctx) -> RatingsData:
        return self._read()

    def read_eval(self, ctx):
        """Time-ordered k-fold: hold out each fold's interactions as
        (query, actual) pairs asking for top-`evalK` recommendations."""
        data = self._read()
        folds = self.params.get_or("evalFolds", 3)
        eval_k = self.params.get_or("evalK", 10)
        out = []
        for f in range(folds):
            test_mask = (np.arange(data.users.size) % folds) == f
            train = RatingsData(
                users=data.users[~test_mask],
                items=data.items[~test_mask],
                ratings=data.ratings[~test_mask],
                times=data.times[~test_mask],
                user_ids=data.user_ids,
                item_ids=data.item_ids,
            )
            qa = {}
            for u, i in zip(data.users[test_mask], data.items[test_mask]):
                qa.setdefault(u, set()).add(i)
            pairs = [
                (
                    {"user": data.user_ids[u], "num": eval_k},
                    [data.item_ids[i] for i in items],
                )
                for u, items in qa.items()
            ]
            out.append((train, EvalInfo(fold=f), pairs))
        return out


class RecommendationPreparator(Preparator):
    """Packs COO ratings into padded CSR blocks sized for the mesh."""

    def prepare(self, ctx, training_data: RatingsData):
        config = ALSConfig(max_len=self.params.get_or("maxEventsPerUser", None))
        num_shards = 1
        try:
            num_shards = ctx.mesh.shape.get("data", 1)
        except Exception:
            pass  # no devices available (pure-host tests)
        als_data = build_als_data(
            training_data.users,
            training_data.items,
            training_data.ratings,
            training_data.num_users,
            training_data.num_items,
            config,
            times=training_data.times,
            num_shards=num_shards,
        )
        return training_data, als_data


@dataclass
class RecommendationModel:
    """Host-side serving model: factor matrices + vocab maps.

    Factors are cached host-side for sub-ms top-k scoring (SURVEY.md
    section 7.3: avoid per-request host<->device copies for factor lookups).
    """

    als: ALSModel
    user_index: dict[str, int]
    item_ids: list[str]
    item_index: dict[str, int]
    seen: dict[int, set[int]]  # user -> rated item indices (for filtering)


class ALSAlgorithm(TPUAlgorithm):
    """ALS on the device mesh (MLlib ALS / ALS.trainImplicit parity).

    Params: rank, numIterations, lambda, alpha, implicitPrefs, seed,
    checkpointInterval (iterations between step checkpoints; 0 disables --
    the preemption-safety net `pio train --resume` continues from).
    """

    def _config(self) -> ALSConfig:
        p = self.params
        return ALSConfig(
            rank=p.get_or("rank", 16),
            iterations=p.get_or("numIterations", 10),
            reg=p.get_or("lambda", 0.1),
            alpha=p.get_or("alpha", 40.0),
            implicit=p.get_or("implicitPrefs", False),
            seed=p.get_or("seed", 0),
            # "bfloat16" halves factor HBM/ICI traffic on TPU (ALX-style
            # mixed precision: f32 Grams + solve, bf16 storage/gathers)
            dtype=p.get_or("factorDtype", "float32"),
        )

    def train(self, ctx, prepared) -> RecommendationModel:
        ratings_data, als_data = prepared
        config = self._config()
        mesh = self.mesh_or_none(ctx)
        interval = self.params.get_or("checkpointInterval", 5)
        checkpoint = ctx.checkpoint_manager("als") if interval > 0 else None
        init, start_iteration, callback = None, 0, None
        if checkpoint is not None:
            # dataset fingerprint: checkpointed factors are only meaningful
            # against the id vocabularies they were trained on. Events
            # ingested between crash and resume change num_users/num_items
            # -- restoring would crash on shape mismatch or silently
            # misalign factor rows with the new vocabulary. Counts alone
            # are not enough (delete one user + add another keeps the count
            # but renumbers rows), so the vocabularies themselves are
            # hashed too.
            import hashlib

            def vocab_hash(ids: list[str]) -> str:
                h = hashlib.sha256()
                for s in ids:
                    h.update(s.encode())
                    h.update(b"\x00")
                return h.hexdigest()[:16]

            fingerprint = {
                "num_users": ratings_data.num_users,
                "num_items": ratings_data.num_items,
                "user_vocab": vocab_hash(ratings_data.user_ids),
                "item_vocab": vocab_hash(ratings_data.item_ids),
                "rank": config.rank,
            }
            latest = checkpoint.latest_step()
            if latest is not None:  # only a --resume run can see a step here
                meta = checkpoint.read_meta()
                if meta != fingerprint:
                    logger.warning(
                        "als checkpoint fingerprint %s does not match current"
                        " dataset %s (events changed between crash and"
                        " resume?); discarding checkpoints and training fresh",
                        meta,
                        fingerprint,
                    )
                    checkpoint.reset()
                else:
                    state = checkpoint.restore(
                        {
                            "users": np.zeros(
                                (ratings_data.num_users, config.rank), np.float32
                            ),
                            "items": np.zeros(
                                (ratings_data.num_items, config.rank), np.float32
                            ),
                            "iteration": 0,
                        }
                    )
                    init = (state["users"], state["items"])
                    start_iteration = int(state["iteration"]) + 1
            checkpoint.write_meta(fingerprint)

            def callback(it, users_np, items_np):
                checkpoint.save(
                    it, {"users": users_np, "items": items_np, "iteration": it}
                )

        model = als_fit(
            als_data,
            config,
            mesh,
            callback=callback,
            callback_interval=interval,
            init=init,
            start_iteration=start_iteration,
        )
        if checkpoint is not None:
            checkpoint.close()
        seen: dict[int, set[int]] = {}
        for u, i in zip(ratings_data.users, ratings_data.items):
            seen.setdefault(int(u), set()).add(int(i))
        return RecommendationModel(
            als=model,
            user_index={uid: idx for idx, uid in enumerate(ratings_data.user_ids)},
            item_ids=ratings_data.item_ids,
            item_index={iid: idx for idx, iid in enumerate(ratings_data.item_ids)},
            seen=seen,
        )

    def predict(self, model: RecommendationModel, query) -> dict:
        num = int(query.get("num", 10))
        if "user" in query:
            return self._recommend_for_user(model, query, num)
        if "items" in query:
            return self._similar_items(model, query, num)
        raise ValueError("query must contain 'user' or 'items'")

    def batch_predict(self, model: RecommendationModel, queries):
        """Vectorized bulk scoring: all known-user recommendation queries in
        one chunk score as a SINGLE [B, K] @ [K, items] matmul instead of B
        gemvs + python per query (the reference's P2LAlgorithm.batchPredict
        parallelism, as one MXU-shaped product). Cold users and
        item-similarity queries fall back to predict(); malformed queries
        raise predict()'s normal error (the batch-predict workflow converts
        those to per-row error records)."""
        user_rows = []  # (qid, query, user_idx)
        fallback = []
        for qid, q in queries:
            user_idx = (
                model.user_index.get(str(q["user"]))
                if isinstance(q, dict) and "user" in q
                else None
            )
            if user_idx is None:
                fallback.append((qid, q))
            else:
                user_rows.append((qid, q, user_idx))
        out = []
        if user_rows:
            # slice so the [rows, items] score matrix stays ~200 MB f32
            # regardless of catalog size (a fixed row count would scale
            # memory with num_items)
            num_items = model.als.item_factors.shape[0]
            rows_per_slice = max(64, 50_000_000 // max(num_items, 1))
            for start in range(0, len(user_rows), rows_per_slice):
                part = user_rows[start : start + rows_per_slice]
                idxs = np.fromiter((u for _, _, u in part), dtype=np.int64)
                scores = model.als.user_factors[idxs] @ model.als.item_factors.T
                for row, (qid, q, user_idx) in enumerate(part):
                    out.append(
                        (
                            qid,
                            self._topk_response(
                                model, scores[row], q, int(q.get("num", 10)), user_idx
                            ),
                        )
                    )
        out.extend((qid, self.predict(model, q)) for qid, q in fallback)
        return out

    @staticmethod
    def _topk_response(
        model: RecommendationModel, scores: np.ndarray, query, num: int, user_idx: int
    ) -> dict:
        """Shared filter + top-k over one user's item scores (predict and
        the vectorized batch path must rank identically)."""
        # blackList always applies; the seen-items filter is opt-out
        exclude = {
            model.item_index[b]
            for b in (query.get("blackList") or [])
            if b in model.item_index
        }
        if query.get("unseenOnly", True):
            exclude |= model.seen.get(user_idx, set())
        for idx in exclude:
            scores[idx] = -np.inf
        order = np.argsort(-scores)[:num]
        return {
            "itemScores": [
                {"item": model.item_ids[i], "score": float(scores[i])}
                for i in order
                if np.isfinite(scores[i])
            ]
        }

    def _recommend_for_user(self, model: RecommendationModel, query, num: int) -> dict:
        user_idx = model.user_index.get(str(query["user"]))
        if user_idx is None:
            return {"itemScores": []}  # cold user: reference returns empty
        scores = model.als.score_items_for_user(user_idx)
        return self._topk_response(model, scores, query, num, user_idx)

    def _similar_items(self, model: RecommendationModel, query, num: int) -> dict:
        sims = None
        anchors = [
            model.item_index[str(item)]
            for item in query["items"]
            if str(item) in model.item_index
        ]
        if not anchors:
            return {"itemScores": []}
        for idx in anchors:
            s = model.als.similar_items(idx)
            sims = s if sims is None else sims + s
        for idx in anchors:
            sims[idx] = -np.inf
        order = np.argsort(-sims)[:num]
        return {
            "itemScores": [
                {"item": model.item_ids[i], "score": float(sims[i])}
                for i in order
                if np.isfinite(sims[i])
            ]
        }


def engine_factory() -> Engine:
    return Engine(
        data_source_class=RecommendationDataSource,
        preparator_class=RecommendationPreparator,
        algorithm_class_map={"als": ALSAlgorithm},
        serving_class=FirstServing,
    )
