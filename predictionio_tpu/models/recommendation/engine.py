"""DASE components of the recommendation template.

Query contract (reference template quickstart):
``{"user": "u1", "num": 4}`` -> ``{"itemScores": [{"item": ..., "score": ...}]}``
plus item-based queries ``{"items": [...], "num": k}`` for similarity.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

import numpy as np

from predictionio_tpu.controller import (
    DataSource,
    Engine,
    EvalInfo,
    FirstServing,
    Preparator,
    TPUAlgorithm,
)
from predictionio_tpu.controller.base import SanityCheck
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.models._als_common import (
    batch_score_known_users,
    build_seen,
    fit_with_checkpoint,
    partition_user_queries,
    prepare_als_data,
    resolve_retrieval,
    retrieval_index,
    score_known_user,
    similar_item_scores,
    topk_item_scores,
    warn_misplaced_packing_params,
)
from predictionio_tpu.models._streaming import (
    StreamingHandle,
    build_streaming_handle,
    streaming_handle_or_none,
)
from predictionio_tpu.parallel.als import ALSConfig, ALSModel

logger = logging.getLogger("pio.recommendation")


@dataclass
class RatingsData(SanityCheck):
    """COO interactions + id vocabularies."""

    users: np.ndarray       # int indices
    items: np.ndarray
    ratings: np.ndarray     # float32
    times: np.ndarray       # float64 epoch seconds
    user_ids: list[str]
    item_ids: list[str]
    #: carried for serving-time live event-store reads (seenFilter "live")
    app_name: str = ""
    event_names: list[str] = None
    #: True when built by the streaming sharded reader: edge arrays are
    #: empty (only the vocabularies are materialized)
    streamed: bool = False
    channel_name: str = None   # non-default channel the data came from
    #: True for read_eval's fold copies: live seen-filtering is downgraded
    #: to the trained-in map there (the held-out events still exist in the
    #: store, and a live read would exclude every 'actual' item)
    eval_fold: bool = False

    def sanity_check(self) -> None:
        if self.users.size == 0:
            raise ValueError(
                "no rating events found -- check appName and eventNames"
            )

    @property
    def num_users(self) -> int:
        return len(self.user_ids)

    @property
    def num_items(self) -> int:
        return len(self.item_ids)


#: the sharded-reader training handle (see models/_streaming): the
#: preparator streams the chunked scan and each process retains only its
#: data-shard's edges; requires seenFilter "live"
StreamingRatings = StreamingHandle


class RecommendationDataSource(DataSource):
    """Reads rating-like events into COO form.

    Params: ``appName`` (required), ``eventNames`` (default ["rate", "buy"]),
    ``ratingKey`` (property holding the rating; "buy"-style events without it
    score 1.0), ``evalK``/``evalFolds`` for read_eval; ``"reader":
    "streaming"`` switches read_training to the retention-bounded sharded
    reader (see StreamingRatings).
    """

    def _read(self) -> RatingsData:
        event_names = self.params.get_or("eventNames", ["rate", "buy"])
        ds = PEventStore.dataset(
            self.params.appName,
            rating_key=self.params.get_or("ratingKey", "rating"),
            event_names=event_names,
            target_entity_type="item",
        )
        ratings = np.nan_to_num(ds.ratings, nan=1.0)  # implicit events -> 1.0
        valid = ds.target_entity_ids >= 0
        return RatingsData(
            users=ds.entity_ids[valid],
            items=ds.target_entity_ids[valid],
            ratings=ratings[valid],
            times=ds.event_times[valid],
            user_ids=ds.entity_id_vocab,
            item_ids=ds.target_entity_id_vocab,
            app_name=self.params.appName,
            event_names=list(event_names),
        )

    def read_training(self, ctx):
        handle = streaming_handle_or_none(
            self.params, ["rate", "buy"],
            empty_message="no rating events found -- check appName and "
            "eventNames",
        )
        return handle if handle is not None else self._read()

    def online_handle(self):
        """The continuous-learning loop's scan descriptor: same identity
        (app/channel/event names/rating key) as the training read, so the
        snapshot the loop refreshes is the one training replays."""
        return build_streaming_handle(
            self.params, ["rate", "buy"],
            empty_message="no rating events found -- check appName and "
            "eventNames",
        )

    def read_eval(self, ctx):
        """Time-ordered k-fold: hold out each fold's interactions as
        (query, actual) pairs asking for top-`evalK` recommendations."""
        data = self._read()
        folds = self.params.get_or("evalFolds", 3)
        eval_k = self.params.get_or("evalK", 10)
        out = []
        for f in range(folds):
            test_mask = (np.arange(data.users.size) % folds) == f
            train = RatingsData(
                users=data.users[~test_mask],
                items=data.items[~test_mask],
                ratings=data.ratings[~test_mask],
                times=data.times[~test_mask],
                user_ids=data.user_ids,
                item_ids=data.item_ids,
                app_name=data.app_name,
                event_names=data.event_names,
                eval_fold=True,
            )
            qa = {}
            for u, i in zip(data.users[test_mask], data.items[test_mask]):
                qa.setdefault(u, set()).add(i)
            pairs = [
                (
                    {"user": data.user_ids[u], "num": eval_k},
                    [data.item_ids[i] for i in items],
                )
                for u, items in qa.items()
            ]
            out.append((train, EvalInfo(fold=f), pairs))
        return out

    def _read_replay_source(self, ctx) -> RatingsData:
        """``_read()``, served from a pinned snapshot generation's memmap
        columns when ``--snapshot-mode`` enables it: the whole replay eval
        (prefix training included) then does zero SQL scans, and reruns
        against the same generation replay identical bytes. Snapshot
        misses degrade to the direct store read, never fail the eval."""
        from predictionio_tpu.data.snapshot import snapshot_settings
        from predictionio_tpu.models._streaming import snapshot_ratings_arrays

        runtime_conf = getattr(ctx, "runtime_conf", None) or {}
        mode, _root = snapshot_settings(runtime_conf)
        if mode != "off":
            handle = build_streaming_handle(
                self.params, ["rate", "buy"],
                empty_message="no rating events found -- check appName and "
                "eventNames",
            )
            arrays = snapshot_ratings_arrays(handle, runtime_conf)
            if arrays is not None:
                users, items, ratings, times, user_ids, item_ids = arrays
                return RatingsData(
                    users=users, items=items, ratings=ratings, times=times,
                    user_ids=user_ids, item_ids=item_ids,
                    app_name=self.params.appName,
                    event_names=list(
                        self.params.get_or("eventNames", ["rate", "buy"])
                    ),
                    channel_name=self.params.get_or("channelName", None),
                )
            logger.warning(
                "replay snapshot unavailable; falling back to the direct"
                " store scan"
            )
        return self._read()

    def read_replay(self, ctx, spec):
        """Time-travel replay fold (``pio eval --replay``): train on
        ratings strictly before the boundary, ask for each held-out
        user's top-``spec.k`` (cold holdout users -- no training events
        -- stay in the fold and score as misses). The fold carries
        ``eval_fold=True`` so a ``seenFilter: "live"`` variant downgrades
        to the trained-in map, exactly like the k-fold path. With
        ``--snapshot-mode use`` the prefix replays a pinned snapshot
        generation's memmaps instead of the SQL store (PR 17's gap)."""
        from predictionio_tpu.eval.split import ReplayFold, split_interactions

        data = self._read_replay_source(ctx)
        cut = split_interactions(data.users, data.items, data.times, spec)
        train = RatingsData(
            users=data.users[cut.train_mask],
            items=data.items[cut.train_mask],
            ratings=data.ratings[cut.train_mask],
            times=data.times[cut.train_mask],
            user_ids=data.user_ids,
            item_ids=data.item_ids,
            app_name=data.app_name,
            event_names=data.event_names,
            eval_fold=True,
        )
        pairs = [
            (
                {"user": data.user_ids[u], "num": spec.k},
                [data.item_ids[int(i)] for i in items],
            )
            for u, items in cut.holdout.items()
        ]
        return ReplayFold(train, pairs, cut.bounds)


class RecommendationPreparator(Preparator):
    """Packs COO ratings into padded CSR blocks sized for the mesh.

    Preparator params: ``buckets`` (length-bucketed packing),
    ``maxEventsPerUser`` (history cap). A StreamingRatings handle (the
    DataSource's ``"reader": "streaming"`` mode) routes through the
    retention-bounded sharded reader instead of full host arrays.
    """

    def prepare(self, ctx, training_data):
        if isinstance(training_data, StreamingRatings):
            return self._prepare_streaming(ctx, training_data)
        als_data = prepare_als_data(
            ctx,
            self.params,
            training_data.users,
            training_data.items,
            training_data.ratings,
            training_data.num_users,
            training_data.num_items,
            times=training_data.times,
        )
        return training_data, als_data

    def _prepare_streaming(self, ctx, src: StreamingRatings):
        from predictionio_tpu.models._streaming import build_streaming_als

        users_enc, items_enc, als_data = build_streaming_als(
            src, self.params, ctx.mesh, runtime_conf=ctx.runtime_conf
        )
        # vocabularies materialized by the scan; edge arrays stay empty --
        # the whole point of the streaming path
        ratings_like = RatingsData(
            users=np.empty(0, np.int64),
            items=np.empty(0, np.int64),
            ratings=np.empty(0, np.float32),
            times=np.empty(0, np.float64),
            user_ids=users_enc.ids,
            item_ids=items_enc.ids,
            app_name=src.app_name,
            event_names=src.event_names,
            streamed=True,
            channel_name=src.channel_name,
        )
        return ratings_like, als_data


@dataclass
class RecommendationModel:
    """Host-side serving model: factor matrices + vocab maps.

    Factors are cached host-side for sub-ms top-k scoring (SURVEY.md
    section 7.3: avoid per-request host<->device copies for factor lookups).
    """

    als: ALSModel
    user_index: dict[str, int]
    item_ids: list[str]
    item_index: dict[str, int]
    seen: dict[int, set[int]]  # user -> rated item indices (for filtering)
    #: "model": the seen map above (O(edges) host memory, zero-latency).
    #: "live": per-query event-store read (the e-commerce template's
    #: pattern) -- the serving model stays O(entities), which is what a
    #: sharded-reader-scale catalog needs. Old pickled blobs predate
    #: these fields; readers go through getattr with defaults.
    seen_mode: str = "model"
    app_name: str = ""
    event_names: list[str] = None
    channel_name: str = None


def _seen_indices(model: "RecommendationModel", query, user_idx: int,
                  cache: dict | None = None) -> set[int]:
    """The user's already-interacted item indices for the unseenOnly filter.

    "model" mode reads the trained-in seen map. "live" mode queries the
    event store per request (the e-commerce template's pattern): the
    serving model stays O(entities) -- required at sharded-reader catalog
    scale, where no single host can hold an O(edges) map -- and newly
    ingested interactions filter immediately without a retrain. A store
    error degrades to "nothing seen" with a log line (serving must not
    500 because a backend blinked).
    """
    if getattr(model, "seen_mode", "model") != "live":
        return model.seen.get(user_idx, set())
    from predictionio_tpu.models._streaming import live_seen_indices

    return live_seen_indices(model, str(query.get("user")), cache)


class ALSAlgorithm(TPUAlgorithm):
    """ALS on the device mesh (MLlib ALS / ALS.trainImplicit parity).

    Params: rank, numIterations, lambda, alpha, implicitPrefs, seed,
    checkpointInterval (iterations between step checkpoints; 0 disables --
    the preemption-safety net `pio train --resume` continues from), and
    ``retrieval`` (``{"mode": "scan"|"mips", ...}``: scan is the full
    [rows, items] host matmul; mips serves through the device-resident
    two-stage quantized top-k of ``ops/mips`` -- docs/templates.md lists
    the knobs and the recall contract).
    """

    @property
    def _retrieval(self):
        conf = getattr(self, "_retrieval_conf", None)
        if conf is None:
            conf = resolve_retrieval(self.params)
            self._retrieval_conf = conf
        return conf

    def _config(self) -> ALSConfig:
        p = self.params
        return ALSConfig(
            rank=p.get_or("rank", 16),
            iterations=p.get_or("numIterations", 10),
            reg=p.get_or("lambda", 0.1),
            alpha=p.get_or("alpha", 40.0),
            implicit=p.get_or("implicitPrefs", False),
            seed=p.get_or("seed", 0),
            # "bfloat16" halves factor HBM/ICI traffic on TPU (ALX-style
            # mixed precision: f32 Grams + solve, bf16 storage/gathers)
            dtype=p.get_or("factorDtype", "float32"),
            # "auto": ALX model-sharded factors whenever pio.mesh_shape
            # configures a model axis > 1 (resolve_factor_sharding)
            factor_sharding=p.get_or("factorSharding", "auto"),
            # "auto": fused Pallas gather->Gram half-step on accelerator
            # meshes, XLA einsums on CPU; `pio train --als-solver` overrides
            solver=p.get_or("alsSolver", "auto"),
        )

    def train(self, ctx, prepared) -> RecommendationModel:
        ratings_data, als_data = prepared
        warn_misplaced_packing_params(self.params, "recommendation")
        self._retrieval  # a retrieval typo fails the build, not a query
        streamed = getattr(ratings_data, "streamed", False)
        seen_mode = self.params.get_or(
            "seenFilter", "live" if streamed else "model"
        )
        if seen_mode not in ("model", "live"):
            raise ValueError(
                f"seenFilter must be 'model' or 'live', got {seen_mode!r}"
            )
        if streamed and seen_mode == "model":
            raise ValueError(
                "the streaming reader materializes no edges, so there is "
                'no O(edges) seen map to train in; use "seenFilter": "live"'
            )
        if seen_mode == "live" and getattr(ratings_data, "eval_fold", False):
            # a live read sees the WHOLE store -- including the held-out
            # test events -- and would score every 'actual' item -inf,
            # collapsing fold metrics to zero. Evaluation folds carry
            # their train-edge arrays, so the trained-in map is both
            # correct and available.
            logger.info(
                "seenFilter 'live' downgraded to 'model' for this "
                "evaluation fold (a live read would exclude held-out items)"
            )
            seen_mode = "model"
        model = fit_with_checkpoint(
            ctx,
            als_data,
            self._config(),
            self.mesh_or_none(ctx),
            user_ids=ratings_data.user_ids,
            item_ids=ratings_data.item_ids,
            interval=self.params.get_or("checkpointInterval", 5),
        )
        # "live" keeps the serving model O(entities): no O(edges) seen map
        seen = (
            build_seen(ratings_data.users, ratings_data.items)
            if seen_mode == "model" else {}
        )
        return RecommendationModel(
            als=model,
            user_index={uid: idx for idx, uid in enumerate(ratings_data.user_ids)},
            item_ids=ratings_data.item_ids,
            item_index={iid: idx for idx, iid in enumerate(ratings_data.item_ids)},
            seen=seen,
            seen_mode=seen_mode,
            app_name=ratings_data.app_name,
            event_names=ratings_data.event_names,
            # without this, a streaming build on a non-default channel
            # serves live seen-filter lookups against the DEFAULT channel
            # (finds nothing, silently stops excluding seen items)
            channel_name=getattr(ratings_data, "channel_name", None),
        )

    def warm_up(self, model: RecommendationModel) -> None:
        model.als.item_norms  # build the similar-items norm cache at deploy
        # mips mode: pack + compile the retrieval index at deploy, not on
        # the first query (dot for user scoring, cosine for similar-items)
        retrieval_index(model.als, self._retrieval)
        retrieval_index(model.als, self._retrieval, kind="cosine")

    supports_fold_in = True

    def shard_model(
        self, model: RecommendationModel, shard: int, num_shards: int
    ) -> RecommendationModel:
        """Keep only the user rows ``shardmap.shard_of`` assigns to
        ``shard``; item factors, item vocab, and the norm caches'
        inputs are replicated untouched.

        Row scoring is per-row (einsum over one user's factor vector), so
        compacting the user table cannot change a kept user's scores by a
        bit. Users filtered OUT of this shard simply miss ``user_index``
        -- the cold-user path -- which is correct because the frontend
        routes their queries to the owning shard; a userless or
        misrouted query sees only replicated state and answers exactly
        as every sibling would.
        """
        if num_shards <= 1:
            return model
        from predictionio_tpu.serving.shardmap import shard_of

        # original row order preserved: renumbering must be a pure
        # compaction, never a reorder
        by_row = sorted(model.user_index.items(), key=lambda kv: kv[1])
        kept = [
            (uid, row) for uid, row in by_row
            if shard_of(uid, num_shards) == shard
        ]
        rank = model.als.user_factors.shape[1] if model.als.user_factors.ndim == 2 else 0
        if kept:
            rows = np.asarray([row for _, row in kept], dtype=np.int64)
            user_factors = np.ascontiguousarray(model.als.user_factors[rows])
        else:
            user_factors = np.empty(
                (0, rank), dtype=model.als.user_factors.dtype
            )
        seen = {
            new_row: model.seen[old_row]
            for new_row, (_, old_row) in enumerate(kept)
            if old_row in model.seen
        }
        return RecommendationModel(
            als=ALSModel(
                user_factors=user_factors,
                item_factors=model.als.item_factors,
            ),
            user_index={uid: new for new, (uid, _) in enumerate(kept)},
            item_ids=model.item_ids,
            item_index=model.item_index,
            seen=seen,
            seen_mode=getattr(model, "seen_mode", "model"),
            app_name=getattr(model, "app_name", ""),
            event_names=getattr(model, "event_names", None),
            channel_name=getattr(model, "channel_name", None),
        )

    def fold_in(self, model: RecommendationModel, delta) -> RecommendationModel | None:
        """Continuous-learning hook (``pio retrain --follow``): re-solve
        the delta window's touched user rows against the frozen item
        factors (``online.foldin``), extend vocabularies for new
        users/items (new items carry zero factors until the next full
        retrain -- the staleness budget bounds how long that lasts), and
        absorb the window into a trained-in seen map. Returns a NEW model;
        the serving swap protocol relies on the old one staying intact."""
        from predictionio_tpu.online.foldin import fold_in_als_model

        result = fold_in_als_model(
            model.als,
            model.user_index,
            model.item_ids,
            model.item_index,
            delta,
            self._config(),
            # the training read scores property-less events 1.0
            rating_default=1.0,
        )
        if result is None:
            return None
        seen = model.seen
        if getattr(model, "seen_mode", "model") == "model" and result.window_pairs is not None:
            seen = {u: set(s) for u, s in model.seen.items()}
            for u, i in result.window_pairs.tolist():
                seen.setdefault(int(u), set()).add(int(i))
        return RecommendationModel(
            als=result.als,
            user_index=result.user_index,
            item_ids=result.item_ids,
            item_index=result.item_index,
            seen=seen,
            seen_mode=getattr(model, "seen_mode", "model"),
            app_name=getattr(model, "app_name", ""),
            event_names=getattr(model, "event_names", None),
            channel_name=getattr(model, "channel_name", None),
        )

    def predict(self, model: RecommendationModel, query) -> dict:
        num = int(query.get("num", 10))
        if "user" in query:
            return self._recommend_for_user(model, query, num)
        if "items" in query:
            return self._similar_items(model, query, num)
        raise ValueError("query must contain 'user' or 'items'")

    def batch_predict(self, model: RecommendationModel, queries):
        """Vectorized bulk scoring: all known-user recommendation queries in
        one chunk score as a SINGLE [B, K] @ [K, items] matmul instead of B
        gemvs + python per query (the reference's P2LAlgorithm.batchPredict
        parallelism, as one MXU-shaped product). Cold users and
        item-similarity queries fall back to predict(); malformed queries
        raise predict()'s normal error (the batch-predict workflow converts
        those to per-row error records)."""
        user_rows, fallback = partition_user_queries(model.user_index, queries)
        # live seen-filter: one store lookup per DISTINCT user for the
        # whole bulk run, not one per row (the scoring itself is still a
        # single matmul; batch-heavy deployments preferring zero lookups
        # should train with seenFilter "model")
        seen_memo: dict = {}

        def seen_for(q, user_idx):
            return _seen_indices(model, q, user_idx, cache=seen_memo)

        out = batch_score_known_users(
            model.als,
            user_rows,
            lambda scores, qid, q, user_idx: (
                qid,
                self._topk_response(
                    model, scores, q, int(q.get("num", 10)), user_idx,
                    seen=seen_for(q, user_idx),
                ),
            ),
            retrieval=self._retrieval,
        )
        out.extend((qid, self.predict(model, q)) for qid, q in fallback)
        return out

    @staticmethod
    def _topk_response(
        model: RecommendationModel, scores: np.ndarray, query, num: int,
        user_idx: int, seen: set | None = None,
    ) -> dict:
        """Shared filter + top-k over one user's item scores (predict and
        the vectorized batch path must rank identically). ``seen`` lets
        the batch path pass a memoized lookup; None resolves per call."""
        # blackList always applies; the seen-items filter is opt-out
        exclude = {
            model.item_index[b]
            for b in (query.get("blackList") or [])
            if b in model.item_index
        }
        if query.get("unseenOnly", True):
            exclude |= (
                seen if seen is not None
                else _seen_indices(model, query, user_idx)
            )
        for idx in exclude:
            scores[idx] = -np.inf
        return topk_item_scores(model.item_ids, scores, num)

    def _recommend_for_user(self, model: RecommendationModel, query, num: int) -> dict:
        user_idx = model.user_index.get(str(query["user"]))
        if user_idx is None:
            return {"itemScores": []}  # cold user: reference returns empty
        scores = score_known_user(model.als, user_idx, self._retrieval)
        return self._topk_response(model, scores, query, num, user_idx)

    def _similar_items(self, model: RecommendationModel, query, num: int) -> dict:
        anchors = [
            model.item_index[str(item)]
            for item in query["items"]
            if str(item) in model.item_index
        ]
        if not anchors:
            return {"itemScores": []}
        sims = similar_item_scores(model.als, anchors, self._retrieval)
        for idx in anchors:
            sims[idx] = -np.inf
        return topk_item_scores(model.item_ids, sims, num)


def engine_factory() -> Engine:
    return Engine(
        data_source_class=RecommendationDataSource,
        preparator_class=RecommendationPreparator,
        algorithm_class_map={"als": ALSAlgorithm},
        serving_class=FirstServing,
    )
