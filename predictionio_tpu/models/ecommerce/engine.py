"""DASE components of the e-commerce recommendation template.

The fourth stock template of the reference's model zoo (SURVEY.md §2.5 #37:
``predictionio-template-ecom-recommender``): implicit-feedback ALS over
view/buy events, with the business rules the plain recommendation template
lacks, applied at serving time:

- ``categories`` filter (item properties ingested via ``$set`` events),
- ``whiteList`` / ``blackList`` in the query,
- a live *unavailable items* constraint: a ``$set`` on the constraint
  entity ``unavailableItems`` read from the event store **per query**, so
  ops can pull items from every deployed server without retraining,
- cold-start users served from their recently-viewed items (also a live
  event-store read), scored through ALS item-space similarity.

Query contract:
``{"user": "u1", "num": 4, "categories": [...], "whiteList": [...],
"blackList": [...]}`` -> ``{"itemScores": [{"item": ..., "score": ...}]}``.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field

import numpy as np

from predictionio_tpu.controller import (
    DataSource,
    Engine,
    EvalInfo,
    FirstServing,
    Preparator,
    TPUAlgorithm,
)
from predictionio_tpu.controller.base import SanityCheck
from predictionio_tpu.data.store import LEventStore, PEventStore
from predictionio_tpu.models._als_common import (
    Shortlist,
    batch_score_known_users,
    build_seen,
    fit_with_checkpoint,
    partition_user_queries,
    prepare_als_data,
    resolve_retrieval,
    retrieval_index,
    score_known_user,
    similar_item_scores,
    topk_item_scores,
    warn_misplaced_packing_params,
)
from predictionio_tpu.models._streaming import (
    StreamingHandle,
    build_streaming_handle,
    streaming_handle_or_none,
)
from predictionio_tpu.parallel.als import ALSConfig, ALSModel

logger = logging.getLogger("pio.ecommerce")


@dataclass
class ECommerceData(SanityCheck):
    """Implicit interactions + per-item categories from ``$set`` properties."""

    users: np.ndarray
    items: np.ndarray
    weights: np.ndarray      # buy-weighted implicit confidence
    times: np.ndarray
    user_ids: list[str]
    item_ids: list[str]
    app_name: str = ""       # carried to the model for live serving reads
    categories: dict[str, list[str]] = field(default_factory=dict)
    channel_name: str = None
    event_names: list[str] = None  # the types this model trained on
    streamed: bool = False   # built by the sharded reader: edge arrays empty

    def sanity_check(self) -> None:
        if self.users.size == 0:
            raise ValueError("no view/buy events found -- check appName")


def _buy_confidences(params, event_names: list[str]) -> dict[str, float]:
    """event type -> implicit confidence (exact buy names boosted)."""
    buy_weight = float(params.get_or("buyWeight", 2.0))
    buy_events = set(params.get_or("buyEvents", ["buy"]))
    return {
        n: buy_weight if n in buy_events else 1.0 for n in event_names
    }


def _load_categories(app_name: str, channel_name=None) -> dict[str, list[str]]:
    props = PEventStore.aggregate_properties(
        app_name, "item", channel_name=channel_name
    )
    return {
        item_id: list(pm.get("categories", []) or [])
        for item_id, pm in props.items()
        if pm.get("categories", None)
    }


def _category_index(
    categories: dict[str, list[str]], item_index: dict[str, int]
) -> dict[str, np.ndarray]:
    """category -> sorted item indices: the inverted index behind the
    query-time ``categories`` filter (shared by train and fold-in)."""
    by_cat: dict[str, list[int]] = {}
    for item_id, cats in categories.items():
        j = item_index.get(item_id)
        if j is not None:
            for c in cats:
                by_cat.setdefault(str(c), []).append(j)
    return {
        c: np.asarray(sorted(js), dtype=np.int64) for c, js in by_cat.items()
    }


class ECommerceDataSource(DataSource):
    """Params: appName (required), eventNames (default ["view", "buy"]),
    buyEvents (exact event names carrying purchase-strength confidence,
    default ["buy"]), buyWeight (their confidence multiplier, default 2.0)."""

    def _read(self) -> ECommerceData:
        event_names = self.params.get_or("eventNames", ["view", "buy"])
        ds = PEventStore.dataset(
            self.params.appName,
            event_names=event_names,
            target_entity_type="item",
        )
        valid = ds.target_entity_ids >= 0
        # implicit confidence: views count 1, buys count more.
        # event_names is dictionary-encoded -- match codes, not strings;
        # exact names only (substring matching would give "unbuy"-style
        # cancellation events the purchase boost)
        buy_weight = float(self.params.get_or("buyWeight", 2.0))
        buy_events = set(self.params.get_or("buyEvents", ["buy"]))
        weights = np.ones(int(valid.sum()), dtype=np.float32)
        buy_codes = [
            code
            for code, name in enumerate(ds.event_name_vocab)
            if name in buy_events
        ]
        weights[np.isin(ds.event_names[valid], buy_codes)] = buy_weight
        categories = _load_categories(self.params.appName)
        return ECommerceData(
            users=ds.entity_ids[valid],
            items=ds.target_entity_ids[valid],
            weights=weights,
            times=ds.event_times[valid],
            user_ids=ds.entity_id_vocab,
            item_ids=ds.target_entity_id_vocab,
            app_name=self.params.appName,
            categories=categories,
        )

    def read_training(self, ctx):
        handle = streaming_handle_or_none(
            self.params, ["view", "buy"],
            empty_message="no view/buy events found -- check appName",
        )
        if handle is not None:
            # DATASOURCE knobs the streaming build needs (DASE keeps
            # per-component params separate)
            handle.extras["event_values"] = _buy_confidences(
                self.params, handle.event_names
            )
            return handle
        return self._read()

    def online_handle(self):
        """Continuous-learning scan descriptor; the confidence map rides
        ``extras`` exactly like the streaming-training handle, so fold-in
        weighs a buy the same way training does."""
        handle = build_streaming_handle(
            self.params, ["view", "buy"],
            empty_message="no view/buy events found -- check appName",
        )
        handle.extras["event_values"] = _buy_confidences(
            self.params, handle.event_names
        )
        return handle

    def read_eval(self, ctx):
        """Hold out each user's latest interaction as the actual."""
        data = self._read()
        data.sanity_check()
        order = np.lexsort((data.times, data.users))
        users, items = data.users[order], data.items[order]
        last = np.r_[users[1:] != users[:-1], True]
        train = ECommerceData(
            users=users[~last],
            items=items[~last],
            weights=data.weights[order][~last],
            times=data.times[order][~last],
            user_ids=data.user_ids,
            item_ids=data.item_ids,
            app_name=data.app_name,
            categories=data.categories,
        )
        pairs = [
            (
                {"user": data.user_ids[int(u)], "num": self.params.get_or("evalK", 10)},
                [data.item_ids[int(i)]],
            )
            for u, i in zip(users[last], items[last])
        ]
        return [(train, EvalInfo(fold=0), pairs)]

    def read_replay(self, ctx, spec):
        """Time-travel replay fold (``pio eval --replay``): implicit
        interactions strictly before the boundary train the fold's model
        (array-backed, so the trained-in seen map covers exactly the
        prefix -- live-serving filter parity without seeing the held-out
        events); each held-out user asks for their top-``spec.k``."""
        from predictionio_tpu.eval.split import ReplayFold, split_interactions

        data = self._read()
        cut = split_interactions(data.users, data.items, data.times, spec)
        train = ECommerceData(
            users=data.users[cut.train_mask],
            items=data.items[cut.train_mask],
            weights=data.weights[cut.train_mask],
            times=data.times[cut.train_mask],
            user_ids=data.user_ids,
            item_ids=data.item_ids,
            app_name=data.app_name,
            categories=data.categories,
        )
        pairs = [
            (
                {"user": data.user_ids[u], "num": spec.k},
                [data.item_ids[int(i)] for i in items],
            )
            for u, items in cut.holdout.items()
        ]
        return ReplayFold(train, pairs, cut.bounds)


class ECommercePreparator(Preparator):
    """Packs interactions into mesh-sized padded CSR blocks (ALX layout).

    A StreamingHandle (datasource ``"reader": "streaming"``) routes
    through the retention-bounded sharded reader with the buy-weighted
    implicit confidences applied per event type in the stream.
    """

    def prepare(self, ctx, data):
        if isinstance(data, StreamingHandle):
            return self._prepare_streaming(ctx, data)
        als_data = prepare_als_data(
            ctx,
            self.params,
            data.users,
            data.items,
            data.weights,
            len(data.user_ids),
            len(data.item_ids),
            times=data.times,
        )
        return data, als_data

    def _prepare_streaming(self, ctx, src: StreamingHandle):
        import numpy as _np

        from predictionio_tpu.models._streaming import build_streaming_als

        # the DATASOURCE's confidence scheme, applied in-stream (it rides
        # the handle: preparator params are a different DASE component)
        event_values = src.extras.get("event_values") or {
            n: 1.0 for n in src.event_names
        }
        users_enc, items_enc, als_data = build_streaming_als(
            src, self.params, ctx.mesh, event_values=event_values,
            runtime_conf=ctx.runtime_conf,
        )
        categories = _load_categories(src.app_name, src.channel_name)
        data = ECommerceData(
            users=_np.empty(0, _np.int64),
            items=_np.empty(0, _np.int64),
            weights=_np.empty(0, _np.float32),
            times=_np.empty(0, _np.float64),
            user_ids=users_enc.ids,
            item_ids=items_enc.ids,
            app_name=src.app_name,
            categories=categories,
            channel_name=src.channel_name,
            event_names=list(src.event_names),
            streamed=True,
        )
        return data, als_data


@dataclass
class ECommerceModel:
    """Host-cached factors + the inverted category index for O(1) filters."""

    als: ALSModel
    app_name: str
    user_index: dict[str, int]
    item_ids: list[str]
    item_index: dict[str, int]
    seen: dict[int, set[int]]
    #: category -> sorted item indices (query-time mask building)
    category_items: dict[str, np.ndarray]
    similar_events: list[str]
    #: "model": the trained-in seen map; "live": per-query event-store
    #: read (streaming-reader serving contract -- O(entities) model).
    #: Old pickles predate these fields; readers use getattr defaults.
    seen_mode: str = "model"
    channel_name: str = None
    event_names: list[str] = None


class ECommAlgorithm(TPUAlgorithm):
    """Implicit ALS + serving-time business rules.

    Params: rank, numIterations, lambda, alpha, seed, unseenOnly (default
    True), similarEvents (events anchoring cold users, default ["view"]),
    recentCount (how many recent views to anchor on, default 10; a query
    may override it), checkpointInterval (iterations between step
    checkpoints; 0 disables), retrieval ({"mode": "scan"|"mips", ...} --
    the two-stage quantized device retrieval of ``ops/mips``; see
    docs/templates.md for the knobs and the recall contract).
    """

    def _config(self) -> ALSConfig:
        p = self.params
        return ALSConfig(
            rank=p.get_or("rank", 16),
            iterations=p.get_or("numIterations", 10),
            reg=p.get_or("lambda", 0.05),
            alpha=p.get_or("alpha", 10.0),
            implicit=p.get_or("implicitPrefs", True),
            seed=p.get_or("seed", 0),
            dtype=p.get_or("factorDtype", "float32"),
            # "auto": ALX model-sharded factors on a model-axis mesh
            factor_sharding=p.get_or("factorSharding", "auto"),
            # "auto": fused Pallas gather->Gram half-step on accelerator
            # meshes, XLA einsums on CPU; `pio train --als-solver` overrides
            solver=p.get_or("alsSolver", "auto"),
        )

    @property
    def _retrieval(self):
        conf = getattr(self, "_retrieval_conf", None)
        if conf is None:
            conf = resolve_retrieval(self.params)
            self._retrieval_conf = conf
        return conf

    def train(self, ctx, prepared) -> ECommerceModel:
        data, als_data = prepared
        warn_misplaced_packing_params(self.params, "ecommerce")
        self._retrieval  # a retrieval typo fails the build, not a query
        model = fit_with_checkpoint(
            ctx,
            als_data,
            self._config(),
            self.mesh_or_none(ctx),
            user_ids=data.user_ids,
            item_ids=data.item_ids,
            interval=self.params.get_or("checkpointInterval", 5),
            name="ecomm-als",
        )
        streamed = getattr(data, "streamed", False)
        seen = {} if streamed else build_seen(data.users, data.items)
        item_index = {iid: j for j, iid in enumerate(data.item_ids)}
        return ECommerceModel(
            als=model,
            app_name=self.params.get_or("appName", None) or data.app_name,
            user_index={uid: k for k, uid in enumerate(data.user_ids)},
            item_ids=data.item_ids,
            item_index=item_index,
            seen=seen,
            category_items=_category_index(data.categories, item_index),
            similar_events=self.params.get_or("similarEvents", ["view"]),
            seen_mode="live" if streamed else "model",
            channel_name=getattr(data, "channel_name", None),
            event_names=getattr(data, "event_names", None),
        )

    supports_fold_in = True

    def fold_in(self, model: ECommerceModel, delta) -> ECommerceModel | None:
        """Continuous-learning hook: implicit fold-in of the delta window
        (frozen item factors, per-event confidences from the datasource's
        map riding ``delta.extras``). New items carry zero factors until
        the next full retrain (the staleness budget's item-growth bound
        caps that); the CATEGORY index no longer waits that long -- when
        the window's touched events include item ``$set`` records, the
        ``$set`` aggregate is rescanned and the inverted index rebuilt
        against the (possibly just-extended) item vocabulary, so a
        category change is serveable one fold-in cycle later. A window of
        ONLY ``$set`` records still publishes: the factor core passes
        through unchanged with a fresh index."""
        from predictionio_tpu.online.foldin import fold_in_als_model

        event_values = delta.extras.get("event_values") or {}
        result = fold_in_als_model(
            model.als,
            model.user_index,
            model.item_ids,
            model.item_index,
            delta,
            self._config(),
            event_values=event_values,
        )
        refresh_categories = "item" in (
            getattr(delta, "set_entity_types", None) or ()
        )
        if result is None and not refresh_categories:
            return None
        item_index = result.item_index if result else model.item_index
        category_items = model.category_items
        if refresh_categories:
            category_items = _category_index(
                _load_categories(
                    model.app_name, getattr(model, "channel_name", None)
                ),
                item_index,
            )
        seen = model.seen
        if (
            result is not None
            and getattr(model, "seen_mode", "model") == "model"
            and result.window_pairs is not None
        ):
            seen = {u: set(s) for u, s in model.seen.items()}
            for u, i in result.window_pairs.tolist():
                seen.setdefault(int(u), set()).add(int(i))
        import dataclasses

        return dataclasses.replace(
            model,
            als=result.als if result else model.als,
            user_index=result.user_index if result else model.user_index,
            item_ids=result.item_ids if result else model.item_ids,
            item_index=item_index,
            category_items=category_items,
            seen=seen,
        )

    # ------------------------------------------------------------------
    # serving
    # ------------------------------------------------------------------

    def _unavailable_items(self, model: ECommerceModel) -> set[int]:
        """Latest ``$set`` on constraint entity ``unavailableItems``, read
        live so deployed servers react without retraining. Any storage
        error degrades to "nothing unavailable" (serving must not 500
        because the metadata store blinked)."""
        if not model.app_name:
            return set()
        try:
            events = list(
                LEventStore.find_by_entity(
                    model.app_name,
                    entity_type="constraint",
                    entity_id="unavailableItems",
                    channel_name=getattr(model, "channel_name", None),
                    event_names=["$set"],
                    limit=1,
                    latest=True,
                )
            )
        except Exception:
            logger.warning("unavailableItems lookup failed; serving unfiltered",
                           exc_info=True)
            return set()
        if not events:
            return set()
        items = events[0].properties.get("items", []) or []
        return {
            model.item_index[str(i)] for i in items if str(i) in model.item_index
        }

    def _recently_viewed(self, model: ECommerceModel, user: str, count: int) -> list[int]:
        """Cold-user anchors: the user's latest ``similarEvents`` items."""
        if not model.app_name:
            return []
        try:
            events = LEventStore.find_by_entity(
                model.app_name,
                entity_type="user",
                entity_id=user,
                channel_name=getattr(model, "channel_name", None),
                event_names=model.similar_events,
                limit=count,
                latest=True,
            )
        except Exception:
            logger.warning("recent-view lookup failed for user %r", user,
                           exc_info=True)
            return []
        out = []
        for e in events:
            j = model.item_index.get(str(e.target_entity_id))
            if j is not None and j not in out:
                out.append(j)
        return out

    def warm_up(self, model: ECommerceModel) -> None:
        model.als.item_norms  # cold-user similarity norm cache, at deploy
        # mips mode: pack + compile the retrieval index at deploy, not on
        # the first query (dot for user scoring, cosine for cold anchors)
        retrieval_index(model.als, self._retrieval)
        retrieval_index(model.als, self._retrieval, kind="cosine")

    @staticmethod
    def _seen(model: ECommerceModel, query, user_idx, cache) -> set[int]:
        """Already-interacted item indices; live mode reads the store
        (memoized per distinct user when the batch path passes a cache)."""
        if getattr(model, "seen_mode", "model") != "live":
            return model.seen.get(user_idx, set())
        from predictionio_tpu.models._streaming import live_seen_indices

        return live_seen_indices(model, str(query.get("user")), cache)

    def _apply_rules(
        self,
        model: ECommerceModel,
        scores: np.ndarray,
        query,
        user_idx,
        anchors,
        unavailable: set[int],
        seen_cache: dict | None = None,
    ) -> dict:
        """Business-rule filtering + ranking shared by predict and
        batch_predict (which resolves ``unavailable`` ONCE per batch and
        memoizes live seen lookups per distinct user)."""
        n_items = scores.shape[0]
        if query.get("whiteList"):
            allowed = np.zeros(n_items, dtype=bool)
            for w in query["whiteList"]:
                j = model.item_index.get(str(w))
                if j is not None:
                    allowed[j] = True
        else:
            allowed = np.ones(n_items, dtype=bool)
        if query.get("categories"):
            cat_mask = np.zeros(n_items, dtype=bool)
            for c in query["categories"]:
                idxs = model.category_items.get(str(c))
                if idxs is not None:
                    cat_mask[idxs] = True
            allowed &= cat_mask
        exclude: set[int] = set(anchors)
        for b in query.get("blackList") or []:
            j = model.item_index.get(str(b))
            if j is not None:
                exclude.add(j)
        exclude |= unavailable
        if user_idx is not None and query.get(
            "unseenOnly", self.params.get_or("unseenOnly", True)
        ):
            exclude |= self._seen(model, query, user_idx, seen_cache)
        if isinstance(scores, Shortlist):
            scores.where_allowed(allowed)  # O(shortlist), stays compact
        else:
            scores = np.where(allowed, scores, -np.inf)
        for j in exclude:
            scores[j] = -np.inf
        return topk_item_scores(model.item_ids, scores, int(query.get("num", 10)))

    def _cold_scores(self, model: ECommerceModel, query, user: str):
        """(anchors, scores) for a user unseen at training time; anchors
        empty means no history at all -> empty response."""
        anchors = self._recently_viewed(
            model,
            user,
            int(query.get("recentCount", self.params.get_or("recentCount", 10))),
        )
        if not anchors:
            return [], None
        return anchors, similar_item_scores(model.als, anchors, self._retrieval)

    def predict(self, model: ECommerceModel, query) -> dict:
        user = str(query.get("user", ""))
        if not user:
            raise ValueError("query must contain 'user'")
        user_idx = model.user_index.get(user)
        anchors: list[int] = []
        if user_idx is not None:
            scores = score_known_user(model.als, user_idx, self._retrieval)
        else:
            anchors, scores = self._cold_scores(model, query, user)
            if scores is None:
                return {"itemScores": []}
        return self._apply_rules(
            model, scores, query, user_idx, anchors, self._unavailable_items(model)
        )

    def batch_predict(self, model: ECommerceModel, queries):
        """Vectorized bulk scoring: known users score as sliced
        [B, K] @ [K, items] matmuls over the host-cached factors, and the
        live unavailable-items constraint is read ONCE per batch instead
        of once per query. Cold users still do their per-user
        recently-viewed lookup; malformed queries raise predict()'s error
        through the fallback loop."""
        user_rows, fallback = partition_user_queries(model.user_index, queries)
        unavailable = self._unavailable_items(model) if queries else set()
        seen_cache: dict = {}
        out = batch_score_known_users(
            model.als,
            user_rows,
            lambda scores, qid, q, user_idx: (
                qid,
                self._apply_rules(
                    model, scores, q, user_idx, [], unavailable,
                    seen_cache=seen_cache,
                ),
            ),
            retrieval=self._retrieval,
        )
        for qid, q in fallback:
            user = str(q.get("user", "")) if isinstance(q, dict) else ""
            if not user:
                out.append((qid, self.predict(model, q)))  # raises like predict
                continue
            anchors, scores = self._cold_scores(model, q, user)
            if scores is None:
                out.append((qid, {"itemScores": []}))
            else:
                out.append(
                    (
                        qid,
                        self._apply_rules(
                            model, scores, q, None, anchors, unavailable
                        ),
                    )
                )
        return out


def engine_factory() -> Engine:
    return Engine(
        data_source_class=ECommerceDataSource,
        preparator_class=ECommercePreparator,
        algorithm_class_map={"ecomm": ECommAlgorithm},
        serving_class=FirstServing,
    )
