from predictionio_tpu.models.ecommerce.engine import engine_factory

__all__ = ["engine_factory"]
