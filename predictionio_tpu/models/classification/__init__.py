"""Classification template: NaiveBayes / LogisticRegression on JAX.

Reference counterpart: predictionio-template-classification (MLlib
NaiveBayes over labeled entity properties) -- SURVEY.md section 2.5 #37,
BASELINE.json config #2 (SMS-spam events). Two data modes:

- "properties": aggregate ``$set`` entity properties; ``attributeFields``
  become features, ``labelField`` the class (stock template parity);
- "text": events carrying a text property (SMS bodies), feature-hashed.
"""

from predictionio_tpu.models.classification.engine import (
    ClassificationDataSource,
    ClassificationPreparator,
    LogisticRegressionAlgorithm,
    NaiveBayesAlgorithm,
    engine_factory,
)

__all__ = [
    "ClassificationDataSource",
    "ClassificationPreparator",
    "LogisticRegressionAlgorithm",
    "NaiveBayesAlgorithm",
    "engine_factory",
]
