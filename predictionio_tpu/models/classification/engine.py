"""DASE components of the classification template.

Query contract: ``{"text": "..."}`` or ``{"features": {...}}`` ->
``{"label": ..., "scores": {label: p, ...}}``.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from predictionio_tpu.controller import (
    DataSource,
    Engine,
    EvalInfo,
    FirstServing,
    Preparator,
    TPUAlgorithm,
)
from predictionio_tpu.controller.base import SanityCheck
from predictionio_tpu.data.store import PEventStore
from predictionio_tpu.ops.classify import (
    train_logistic_regression,
    train_naive_bayes,
)
from predictionio_tpu.ops.features import (
    BinaryVectorizer,
    NumericVectorizer,
    hashing_vectorize,
)


@dataclass
class LabeledRecords(SanityCheck):
    records: list[dict]   # feature dicts (or {"text": ...})
    labels: list[str]
    mode: str             # "text" | "properties"

    def sanity_check(self) -> None:
        if not self.records:
            raise ValueError("no labeled training data found")
        if len(set(self.labels)) < 2:
            raise ValueError("need at least 2 classes to train a classifier")


class ClassificationDataSource(DataSource):
    """Params: appName; mode ("text"|"properties"); textKey/labelKey for text
    events (default eventNames ["train"]); entityType/attributeFields/
    labelField for property mode; evalFolds."""

    def _read(self) -> LabeledRecords:
        mode = self.params.get_or("mode", "text")
        if mode == "text":
            events = PEventStore.find(
                self.params.appName,
                event_names=self.params.get_or("eventNames", ["train"]),
            )
            text_key = self.params.get_or("textKey", "text")
            label_key = self.params.get_or("labelKey", "label")
            records, labels = [], []
            for e in events:
                text = e.properties.get_opt(text_key)
                label = e.properties.get_opt(label_key)
                if text is None or label is None:
                    continue
                records.append({"text": str(text)})
                labels.append(str(label))
            return LabeledRecords(records, labels, "text")
        props = PEventStore.aggregate_properties(
            self.params.appName,
            entity_type=self.params.get_or("entityType", "user"),
        )
        label_field = self.params.get_or("labelField", "label")
        fields = self.params.get_or("attributeFields", None)
        records, labels = [], []
        for pm in props.values():
            if label_field not in pm:
                continue
            d = pm.to_dict()
            label = str(d.pop(label_field))
            if fields:
                d = {k: v for k, v in d.items() if k in fields}
            records.append(d)
            labels.append(label)
        return LabeledRecords(records, labels, "properties")

    def read_training(self, ctx) -> LabeledRecords:
        return self._read()

    def read_eval(self, ctx):
        data = self._read()
        folds = self.params.get_or("evalFolds", 3)
        out = []
        for f in range(folds):
            idx = np.arange(len(data.records))
            test = (idx % folds) == f
            train = LabeledRecords(
                [r for r, t in zip(data.records, test) if not t],
                [l for l, t in zip(data.labels, test) if not t],
                data.mode,
            )
            pairs = [
                (
                    {"text": r["text"]} if data.mode == "text" else {"features": r},
                    l,
                )
                for r, l, t in zip(data.records, data.labels, test)
                if t
            ]
            out.append((train, EvalInfo(fold=f), pairs))
        return out


@dataclass
class FeatureSpace:
    """Everything needed to vectorize one query at serving time."""

    mode: str
    hash_dim: int
    binary: BinaryVectorizer | None
    numeric: NumericVectorizer | None
    classes: list[str]

    def vectorize_records(self, records: list[dict]) -> np.ndarray:
        if self.mode == "text":
            return hashing_vectorize([r["text"] for r in records], self.hash_dim)
        parts = []
        if self.binary and self.binary.dim:
            parts.append(self.binary.transform(records))
        if self.numeric and self.numeric.fields:
            parts.append(self.numeric.transform(records))
        if not parts:
            raise ValueError("no usable features in training records")
        return np.concatenate(parts, axis=1)


class ClassificationPreparator(Preparator):
    """Vectorizes records; params: hashDim (text mode, default 4096)."""

    def prepare(self, ctx, data: LabeledRecords):
        classes = sorted(set(data.labels))
        class_index = {c: i for i, c in enumerate(classes)}
        y = np.array([class_index[l] for l in data.labels], dtype=np.int32)
        if data.mode == "text":
            space = FeatureSpace(
                mode="text",
                hash_dim=self.params.get_or("hashDim", 4096),
                binary=None,
                numeric=None,
                classes=classes,
            )
        else:
            categorical, numeric = [], []
            sample = data.records
            keys = sorted({k for r in sample for k in r})
            for k in keys:
                values = [r[k] for r in sample if k in r]
                if all(isinstance(v, (int, float)) and not isinstance(v, bool) for v in values):
                    numeric.append(k)
                else:
                    categorical.append(k)
            space = FeatureSpace(
                mode="properties",
                hash_dim=0,
                binary=BinaryVectorizer.fit(sample, categorical),
                numeric=NumericVectorizer(numeric),
                classes=classes,
            )
        x = space.vectorize_records(data.records)
        return space, x, y


@dataclass
class ClassifierModel:
    space: FeatureSpace
    inner: object  # NaiveBayesModel | LogisticRegressionModel


class _ClassifierBase(TPUAlgorithm):
    def predict(self, model: ClassifierModel, query) -> dict:
        if "text" in query:
            record = {"text": str(query["text"])}
        elif "features" in query:
            record = dict(query["features"])
        else:
            raise ValueError("query must contain 'text' or 'features'")
        x = model.space.vectorize_records([record])
        raw = model.inner.scores(x)[0]
        # normalize to probabilities for the wire (NB scores are log-space)
        if np.any(raw < 0) or raw.sum() <= 0 or raw.max() > 1:
            e = np.exp(raw - raw.max())
            probs = e / e.sum()
        else:
            probs = raw
        best = int(np.argmax(probs))
        return {
            "label": model.space.classes[best],
            "scores": {
                c: float(p) for c, p in zip(model.space.classes, probs)
            },
        }


class NaiveBayesAlgorithm(_ClassifierBase):
    """Params: smoothing (default 1.0)."""

    def train(self, ctx, prepared) -> ClassifierModel:
        space, x, y = prepared
        mesh = self.mesh_or_none(ctx)  # dp over examples
        model = train_naive_bayes(
            x,
            y,
            len(space.classes),
            smoothing=self.params.get_or("smoothing", 1.0),
            mesh=mesh,
        )
        return ClassifierModel(space=space, inner=model)


class LogisticRegressionAlgorithm(_ClassifierBase):
    """Params: reg, iterations, learningRate."""

    def train(self, ctx, prepared) -> ClassifierModel:
        space, x, y = prepared
        mesh = self.mesh_or_none(ctx)  # dp over examples
        model = train_logistic_regression(
            x,
            y,
            len(space.classes),
            reg=self.params.get_or("reg", 1e-4),
            iterations=self.params.get_or("iterations", 100),
            learning_rate=self.params.get_or("learningRate", 0.1),
            mesh=mesh,
        )
        return ClassifierModel(space=space, inner=model)


def engine_factory() -> Engine:
    return Engine(
        data_source_class=ClassificationDataSource,
        preparator_class=ClassificationPreparator,
        algorithm_class_map={
            "naive-bayes": NaiveBayesAlgorithm,
            "logistic-regression": LogisticRegressionAlgorithm,
        },
        serving_class=FirstServing,
    )
