"""e2: self-contained reference algorithms + evaluation helpers.

Parity role of the reference ``e2/`` module (apache/predictionio layout,
unverified -- SURVEY.md section 2.5 #36): small building blocks templates and
tests compose. ``PythonEngine``'s role (run Python algos under the JVM) is
moot here -- the whole framework is Python; any callable works as a DASE
component.

- :func:`categorical_naive_bayes` -- NB over string-valued feature dicts
  (reference CategoricalNaiveBayes), via BinaryVectorizer + the MXU NB.
- :class:`MarkovChain` -- first-order transition model with additive
  smoothing (reference MarkovChain), trained as one one-hot matmul.
- :func:`cross_validation_folds` -- generic k-fold splitter (reference
  e2.evaluation.CrossValidation).
- :func:`kmeans` -- re-export of the mesh KMeans (``ops.kmeans``), the
  MLlib KMeans counterpart some reference templates cluster with
  (SURVEY.md section 2.8).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from predictionio_tpu.ops.classify import NaiveBayesModel, train_naive_bayes
from predictionio_tpu.ops.features import BinaryVectorizer
from predictionio_tpu.ops.kmeans import KMeansModel, kmeans_fit as kmeans  # noqa: F401


@dataclass
class CategoricalNBModel:
    vectorizer: BinaryVectorizer
    classes: list[str]
    inner: NaiveBayesModel

    def predict(self, record: dict) -> str:
        x = self.vectorizer.transform([record])
        return self.classes[int(self.inner.scores(x)[0].argmax())]

    def log_score(self, record: dict, label: str) -> float:
        x = self.vectorizer.transform([record])
        return float(self.inner.scores(x)[0][self.classes.index(label)])


def categorical_naive_bayes(
    records: list[dict], labels: list[str], smoothing: float = 1.0
) -> CategoricalNBModel:
    fields = sorted({k for r in records for k in r})
    vectorizer = BinaryVectorizer.fit(records, fields)
    classes = sorted(set(labels))
    index = {c: i for i, c in enumerate(classes)}
    y = np.array([index[l] for l in labels], dtype=np.int32)
    inner = train_naive_bayes(
        vectorizer.transform(records), y, len(classes), smoothing=smoothing
    )
    return CategoricalNBModel(vectorizer=vectorizer, classes=classes, inner=inner)


@dataclass
class MarkovChain:
    """First-order Markov chain over an integer state space."""

    transition: np.ndarray  # [S, S] row-stochastic
    states: list[str]

    @classmethod
    def fit(cls, sequences: list[list[str]], smoothing: float = 1e-3) -> "MarkovChain":
        state_index: dict[str, int] = {}
        pairs: list[tuple[int, int]] = []
        for seq in sequences:
            idx = [state_index.setdefault(s, len(state_index)) for s in seq]
            pairs.extend(zip(idx[:-1], idx[1:]))
        n = len(state_index)
        if n == 0:
            raise ValueError("no states in training sequences")
        counts = np.zeros((n, n))
        if pairs:
            src = np.array([p[0] for p in pairs])
            dst = np.array([p[1] for p in pairs])
            # O(P) scatter-add; a one-hot matmul here would materialize
            # [P, S] dense intermediates for no benefit at host scale
            np.add.at(counts, (src, dst), 1.0)
        counts = counts + smoothing
        transition = counts / counts.sum(axis=1, keepdims=True)
        return cls(transition=transition, states=list(state_index))

    def next_distribution(self, state: str) -> dict[str, float]:
        i = self.states.index(state)
        return dict(zip(self.states, self.transition[i].tolist()))

    def most_likely_next(self, state: str) -> str:
        i = self.states.index(state)
        return self.states[int(self.transition[i].argmax())]

    def sequence_log_prob(self, seq: list[str]) -> float:
        total = 0.0
        for a, b in zip(seq[:-1], seq[1:]):
            i, j = self.states.index(a), self.states.index(b)
            total += float(np.log(self.transition[i, j]))
        return total


def cross_validation_folds(n: int, k: int, seed: int = 0):
    """Yield (train_indices, test_indices) for k shuffled folds."""
    rng = np.random.default_rng(seed)
    order = rng.permutation(n)
    for f in range(k):
        test = order[f::k]
        train = np.setdiff1d(order, test)
        yield train, test
