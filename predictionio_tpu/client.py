"""Python client SDK for the Event and Query servers.

Parity role of the reference ecosystem's ``predictionio`` Python SDK
(SURVEY.md §1 L7: the client SDKs live outside the framework repo, but
their WIRE CONTRACT — the event JSON shape, ``accessKey`` auth, the
``/events.json`` and ``/queries.json`` endpoints — is part of this
framework's compatibility surface, see Appendix A). Stdlib-only
(urllib), synchronous, keep-alive is the server's concern.

    from predictionio_tpu.client import EventClient, EngineClient

    events = EventClient("http://localhost:7070", access_key=KEY)
    events.create(event="rate", entity_type="user", entity_id="u1",
                  target_entity_type="item", target_entity_id="i3",
                  properties={"rating": 5})

    engine = EngineClient("http://localhost:8000")
    engine.query({"user": "u1", "num": 4})
"""

from __future__ import annotations

import datetime as _dt
import json
import urllib.error
import urllib.parse
import urllib.request
from typing import Any


class PIOServerError(RuntimeError):
    """Non-2xx response from an event/query server."""

    def __init__(self, status: int, body: str):
        super().__init__(f"HTTP {status}: {body[:300]}")
        self.status = status
        self.body = body


class PIOConnectionError(PIOServerError):
    """The server could not be reached at all (refused, DNS, timeout).

    Subclasses PIOServerError so SDK users have ONE error hierarchy to
    catch; ``status`` is 0 because no HTTP response exists."""

    def __init__(self, reason: str):
        RuntimeError.__init__(self, f"connection failed: {reason}")
        self.status = 0
        self.body = ""


def _request(
    method: str, url: str, payload: Any | None = None, timeout: float = 10.0
) -> Any:
    data = None if payload is None else json.dumps(payload).encode()
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            body = resp.read().decode()
    except urllib.error.HTTPError as exc:
        raise PIOServerError(exc.code, exc.read().decode()) from None
    except (urllib.error.URLError, OSError) as exc:
        # URLError wraps refused/DNS; bare OSError covers socket timeouts
        # and resets mid-read -- all "never reached a response" failures
        raise PIOConnectionError(str(exc)) from None
    return json.loads(body) if body else None


class EventClient:
    """Talks to the Event Server (default :7070) for one app's access key."""

    def __init__(self, url: str, access_key: str, channel: str | None = None,
                 timeout: float = 10.0):
        self.url = url.rstrip("/")
        self.access_key = access_key
        self.channel = channel
        self.timeout = timeout

    def _qs(self, extra: dict | None = None) -> str:
        params = {"accessKey": self.access_key}
        if self.channel:
            params["channel"] = self.channel
        params.update(extra or {})
        return urllib.parse.urlencode(params)

    @staticmethod
    def _event_body(
        event: str,
        entity_type: str,
        entity_id: str,
        target_entity_type: str | None = None,
        target_entity_id: str | None = None,
        properties: dict | None = None,
        event_time: _dt.datetime | str | None = None,
    ) -> dict:
        body: dict[str, Any] = {
            "event": event, "entityType": entity_type, "entityId": entity_id,
        }
        if target_entity_type is not None:
            body["targetEntityType"] = target_entity_type
        if target_entity_id is not None:
            body["targetEntityId"] = target_entity_id
        if properties is not None:
            # an explicit {} must survive to the wire: an empty $set is a
            # legal "touch" (updates lastUpdated) and differs from no field
            body["properties"] = properties
        if event_time is not None:
            body["eventTime"] = (
                event_time.isoformat()
                if isinstance(event_time, _dt.datetime)
                else event_time
            )
        return body

    def create(self, **kwargs) -> str:
        """POST one event; returns its eventId. Kwargs mirror the wire
        contract: event, entity_type, entity_id, target_entity_type,
        target_entity_id, properties, event_time."""
        out = _request(
            "POST",
            f"{self.url}/events.json?{self._qs()}",
            self._event_body(**kwargs),
            self.timeout,
        )
        return out["eventId"]

    def set_properties(self, entity_type: str, entity_id: str, properties: dict) -> str:
        return self.create(event="$set", entity_type=entity_type,
                           entity_id=entity_id, properties=properties)

    def unset_properties(self, entity_type: str, entity_id: str, keys: list[str]) -> str:
        return self.create(event="$unset", entity_type=entity_type,
                           entity_id=entity_id,
                           properties={k: None for k in keys})

    def delete_entity(self, entity_type: str, entity_id: str) -> str:
        return self.create(event="$delete", entity_type=entity_type,
                           entity_id=entity_id)

    def create_batch(self, events: list[dict]) -> list[dict]:
        """POST up to 50 raw event dicts (wire shape); returns the per-item
        status array in order."""
        return _request(
            "POST", f"{self.url}/batch/events.json?{self._qs()}", events,
            self.timeout,
        )

    def get(self, event_id: str) -> dict:
        # explicit ids from imports may carry reserved chars ('/', '?')
        eid = urllib.parse.quote(event_id, safe="")
        return _request(
            "GET", f"{self.url}/events/{eid}.json?{self._qs()}",
            timeout=self.timeout,
        )

    def delete(self, event_id: str) -> None:
        eid = urllib.parse.quote(event_id, safe="")
        _request(
            "DELETE", f"{self.url}/events/{eid}.json?{self._qs()}",
            timeout=self.timeout,
        )

    def find(self, **filters) -> list[dict]:
        """GET /events.json with the reference filter set (camelCase keys:
        startTime, untilTime, entityType, entityId, event, limit, ...)."""
        return _request(
            "GET",
            f"{self.url}/events.json?{self._qs(filters)}",
            timeout=self.timeout,
        )


class EngineClient:
    """Talks to a deployed Query Server (default :8000)."""

    def __init__(self, url: str, timeout: float = 10.0):
        self.url = url.rstrip("/")
        self.timeout = timeout

    def query(self, query: dict) -> dict:
        """POST /queries.json -> the template's PredictedResult JSON."""
        return _request(
            "POST", f"{self.url}/queries.json", query, self.timeout
        )
