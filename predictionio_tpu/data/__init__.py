"""Data layer: event model, property aggregation, storage registry, stores.

Rebuilds the behavior of the reference's ``data/`` module
(apache/predictionio layout: ``data/src/main/scala/org/apache/predictionio/data/``,
unverified against /root/reference -- see SURVEY.md "Provenance warning").
"""

from predictionio_tpu.data.datamap import DataMap, PropertyMap, DataMapError
from predictionio_tpu.data.event import Event, EventValidationError

__all__ = [
    "DataMap",
    "PropertyMap",
    "DataMapError",
    "Event",
    "EventValidationError",
]
