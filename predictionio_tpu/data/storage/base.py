"""Storage DAO contracts + metadata record types.

Behavioral model: reference ``data/.../storage/{Apps,Channels,AccessKeys,
EngineInstances,EvaluationInstances,Models,LEvents}.scala`` (apache/predictionio
layout, unverified -- SURVEY.md section 2.2 #7). The CRUD/query surface is kept;
the implementation and the ``PEvents`` RDD path are replaced by a columnar
batched reader (see ``predictionio_tpu.data.store``).
"""

from __future__ import annotations

import abc
import datetime as _dt
from dataclasses import dataclass, field
from typing import Any, Iterable, Iterator, Optional

from predictionio_tpu.data.datamap import PropertyMap
from predictionio_tpu.data.event import Event

# -- engine/evaluation instance status machine (SURVEY.md section 5.3) -------
STATUS_QUEUED = "QUEUED"
STATUS_RUNNING = "RUNNING"
STATUS_COMPLETED = "COMPLETED"
STATUS_FAILED = "FAILED"
STATUS_ABORTED = "ABORTED"


@dataclass
class App:
    name: str
    description: str = ""
    id: int | None = None


@dataclass
class Channel:
    name: str
    app_id: int
    id: int | None = None

    @staticmethod
    def is_valid_name(name: str) -> bool:
        return bool(name) and name.replace("-", "").replace("_", "").isalnum()


@dataclass
class AccessKey:
    key: str
    app_id: int
    events: list[str] = field(default_factory=list)  # empty = all events allowed


@dataclass
class EngineInstance:
    """One training run; persists params + status for deploy to resolve."""

    id: str | None = None
    status: str = STATUS_QUEUED
    start_time: _dt.datetime = field(
        default_factory=lambda: _dt.datetime.now(_dt.timezone.utc)
    )
    end_time: _dt.datetime | None = None
    engine_id: str = ""
    engine_version: str = ""
    engine_variant: str = ""
    engine_factory: str = ""
    batch: str = ""
    env: dict[str, str] = field(default_factory=dict)
    runtime_conf: dict[str, Any] = field(default_factory=dict)  # engine.json sparkConf analogue
    data_source_params: str = "{}"
    preparator_params: str = "{}"
    algorithms_params: str = "[]"
    serving_params: str = "{}"


@dataclass
class EvaluationInstance:
    id: str | None = None
    status: str = STATUS_QUEUED
    start_time: _dt.datetime = field(
        default_factory=lambda: _dt.datetime.now(_dt.timezone.utc)
    )
    end_time: _dt.datetime | None = None
    evaluation_class: str = ""
    engine_params_generator_class: str = ""
    batch: str = ""
    env: dict[str, str] = field(default_factory=dict)
    evaluator_results: str = ""          # human-readable leaderboard
    evaluator_results_html: str = ""     # dashboard drill-down
    evaluator_results_json: str = ""     # machine-readable


@dataclass
class Model:
    """Serialized model blob keyed by EngineInstance id."""

    id: str
    models: bytes


def safe_blob_name(model_id: str) -> str:
    """Collision-free file/object name for a model id (shared by the
    localfs and s3 blob stores).

    Reversible encoding: ids starting with "x" always take the encoded
    branch, so a literal id can never collide with another id's hex
    encoding."""
    if not model_id.startswith("x") and all(
        c.isalnum() or c in "-_" for c in model_id
    ):
        safe = model_id
    else:
        safe = "x" + model_id.encode("utf-8").hex()
    return f"pio_model_{safe}.bin"


@dataclass
class StorageClientConfig:
    parallel: bool = False
    test: bool = False
    properties: dict[str, str] = field(default_factory=dict)


class BaseStorageClient(abc.ABC):
    """One configured connection to a backend (reference BaseStorageClient)."""

    def __init__(self, config: StorageClientConfig):
        self.config = config

    @abc.abstractmethod
    def get_dao(self, repo: str):
        """Return the DAO for ``repo`` in {apps, channels, access_keys,
        engine_instances, evaluation_instances, models, events}."""

    def close(self) -> None:  # pragma: no cover - backends override as needed
        pass


# -- DAO contracts -----------------------------------------------------------


class Apps(abc.ABC):
    @abc.abstractmethod
    def insert(self, app: App) -> int: ...

    @abc.abstractmethod
    def get(self, app_id: int) -> Optional[App]: ...

    @abc.abstractmethod
    def get_by_name(self, name: str) -> Optional[App]: ...

    @abc.abstractmethod
    def get_all(self) -> list[App]: ...

    @abc.abstractmethod
    def update(self, app: App) -> None: ...

    @abc.abstractmethod
    def delete(self, app_id: int) -> None: ...


class Channels(abc.ABC):
    @abc.abstractmethod
    def insert(self, channel: Channel) -> int: ...

    @abc.abstractmethod
    def get(self, channel_id: int) -> Optional[Channel]: ...

    @abc.abstractmethod
    def get_by_app(self, app_id: int) -> list[Channel]: ...

    @abc.abstractmethod
    def delete(self, channel_id: int) -> None: ...


class AccessKeys(abc.ABC):
    @abc.abstractmethod
    def insert(self, access_key: AccessKey) -> str: ...

    @abc.abstractmethod
    def get(self, key: str) -> Optional[AccessKey]: ...

    @abc.abstractmethod
    def get_all(self) -> list[AccessKey]: ...

    @abc.abstractmethod
    def get_by_app_id(self, app_id: int) -> list[AccessKey]: ...

    @abc.abstractmethod
    def update(self, access_key: AccessKey) -> None: ...

    @abc.abstractmethod
    def delete(self, key: str) -> None: ...


class EngineInstances(abc.ABC):
    @abc.abstractmethod
    def insert(self, instance: EngineInstance) -> str: ...

    @abc.abstractmethod
    def get(self, instance_id: str) -> Optional[EngineInstance]: ...

    @abc.abstractmethod
    def get_all(self) -> list[EngineInstance]: ...

    @abc.abstractmethod
    def get_latest_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> Optional[EngineInstance]: ...

    @abc.abstractmethod
    def get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> list[EngineInstance]: ...

    def get_latest(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> Optional[EngineInstance]:
        """Most recent instance for a variant REGARDLESS of status -- the
        crash-resume lookup (`pio train --resume` reuses a non-COMPLETED
        instance instead of inserting a new one). Default implementation
        scans get_all(); SQL backends override with a WHERE query."""
        candidates = [
            i
            for i in self.get_all()
            if i.engine_id == engine_id
            and i.engine_version == engine_version
            and i.engine_variant == engine_variant
        ]
        if not candidates:
            return None
        epoch = _dt.datetime.min.replace(tzinfo=_dt.timezone.utc)
        return max(candidates, key=lambda i: i.start_time or epoch)

    @abc.abstractmethod
    def update(self, instance: EngineInstance) -> None: ...

    @abc.abstractmethod
    def delete(self, instance_id: str) -> None: ...


class EvaluationInstances(abc.ABC):
    @abc.abstractmethod
    def insert(self, instance: EvaluationInstance) -> str: ...

    @abc.abstractmethod
    def get(self, instance_id: str) -> Optional[EvaluationInstance]: ...

    @abc.abstractmethod
    def get_all(self) -> list[EvaluationInstance]: ...

    @abc.abstractmethod
    def get_completed(self) -> list[EvaluationInstance]: ...

    @abc.abstractmethod
    def update(self, instance: EvaluationInstance) -> None: ...

    @abc.abstractmethod
    def delete(self, instance_id: str) -> None: ...


class Models(abc.ABC):
    @abc.abstractmethod
    def insert(self, model: Model) -> None: ...

    @abc.abstractmethod
    def get(self, model_id: str) -> Optional[Model]: ...

    @abc.abstractmethod
    def delete(self, model_id: str) -> None: ...


class LEvents(abc.ABC):
    """Event-store DAO. ``channel_id=None`` addresses the default channel.

    ``find`` filter surface mirrors the reference ``LEvents.find`` signature
    (SURVEY.md section 2.2 #7).
    """

    @abc.abstractmethod
    def init_channel(self, app_id: int, channel_id: int | None = None) -> bool: ...

    @abc.abstractmethod
    def remove_channel(self, app_id: int, channel_id: int | None = None) -> bool: ...

    @abc.abstractmethod
    def insert(self, event: Event, app_id: int, channel_id: int | None = None) -> str: ...

    @abc.abstractmethod
    def batch_insert(
        self, events: Iterable[Event], app_id: int, channel_id: int | None = None
    ) -> list[str]: ...

    def insert_batch(
        self,
        items: Iterable[tuple[Event, int, Optional[int]]],
        on_duplicate: str = "error",
    ) -> list[str]:
        """Heterogeneous group commit: ``(event, app_id, channel_id)`` tuples
        spanning apps/channels, applied as atomically as the backend allows
        (single transaction on the SQL backends, which override this).

        ``on_duplicate="ignore"`` skips rows whose event_id already exists --
        the WAL-replay idempotence contract (``data/ingest.py``). This loop
        fallback serves the non-SQL backends.
        """
        if on_duplicate not in ("error", "ignore"):
            raise ValueError(f"on_duplicate must be error|ignore, got {on_duplicate!r}")
        ids = []
        for event, app_id, channel_id in items:
            ev = event if event.event_id else event.with_id()
            if (
                on_duplicate == "ignore"
                and self.get(ev.event_id, app_id, channel_id) is not None
            ):
                ids.append(ev.event_id)
                continue
            ids.append(self.insert(ev, app_id, channel_id))
        return ids

    @abc.abstractmethod
    def get(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> Optional[Event]: ...

    @abc.abstractmethod
    def delete(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> bool: ...

    @abc.abstractmethod
    def find(
        self,
        app_id: int,
        channel_id: int | None = None,
        start_time: _dt.datetime | None = None,
        until_time: _dt.datetime | None = None,
        entity_type: str | None = None,
        entity_id: str | None = None,
        event_names: list[str] | None = None,
        target_entity_type: str | None | type(...) = ...,
        target_entity_id: str | None | type(...) = ...,
        limit: int | None = None,
        reversed: bool = False,
    ) -> Iterator[Event]: ...

    def aggregate_properties(
        self,
        app_id: int,
        entity_type: str,
        channel_id: int | None = None,
        start_time: _dt.datetime | None = None,
        until_time: _dt.datetime | None = None,
        required: list[str] | None = None,
    ) -> dict[str, PropertyMap]:
        from predictionio_tpu.data.aggregation import aggregate_properties
        from predictionio_tpu.data.event import SPECIAL_EVENTS

        events = self.find(
            app_id=app_id,
            channel_id=channel_id,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            event_names=sorted(SPECIAL_EVENTS),
        )
        result = aggregate_properties(events)
        if required:
            result = {
                k: v for k, v in result.items() if all(r in v for r in required)
            }
        return result
