"""MySQL implementations of every DAO contract.

The reference's scalikejdbc module (``storage/jdbc/.../JDBC*.scala`` --
apache/predictionio layout, unverified, SURVEY.md section 2.2 #10) serves
PostgreSQL *and* MySQL from one DAO set; this module is the MySQL half of
that contract. The DAO logic is shared with the sqlite/postgres backends via
``sql_common``; only the connection, dialect DDL, identifier quoting, and
conflict-handling statements live here.

Configuration (reference env-var contract, SURVEY.md section 5.6):

    PIO_STORAGE_SOURCES_MYSQL_TYPE=mysql   (or: jdbc with a mysql URL)
    PIO_STORAGE_SOURCES_MYSQL_URL=jdbc:mysql://host:3306/pio
    PIO_STORAGE_SOURCES_MYSQL_USERNAME=pio
    PIO_STORAGE_SOURCES_MYSQL_PASSWORD=...

Driver: PyMySQL (preferred) or MySQLdb/mysqlclient -- optional dependencies;
a clear error is raised when neither is installed.

MySQL dialect notes, relative to the shared DAO SQL:

- ``key`` (access_keys PK column) is a reserved word -> ``sql()`` backtick-
  quotes the bare token via word-boundary rewrite.
- TEXT columns cannot be primary keys -> VARCHAR(191) for id/key columns
  (191 keeps the index under the 767-byte utf8mb4 limit of older InnoDB).
- blobs use LONGBLOB, JSON payloads LONGTEXT.
"""

from __future__ import annotations

import re
import threading
from typing import Iterator

from predictionio_tpu.data.storage import sql_common
from predictionio_tpu.data.storage.base import StorageClientConfig

_SCHEMA_STATEMENTS = [
    """CREATE TABLE IF NOT EXISTS apps (
      id BIGINT AUTO_INCREMENT PRIMARY KEY,
      name VARCHAR(191) UNIQUE NOT NULL,
      description TEXT NOT NULL
    ) DEFAULT CHARSET=utf8mb4 COLLATE=utf8mb4_bin""",
    """CREATE TABLE IF NOT EXISTS channels (
      id BIGINT AUTO_INCREMENT PRIMARY KEY,
      name VARCHAR(191) NOT NULL,
      app_id BIGINT NOT NULL,
      UNIQUE KEY uq_channels (app_id, name)
    ) DEFAULT CHARSET=utf8mb4 COLLATE=utf8mb4_bin""",
    """CREATE TABLE IF NOT EXISTS access_keys (
      `key` VARCHAR(191) PRIMARY KEY,
      app_id BIGINT NOT NULL,
      events LONGTEXT NOT NULL
    ) DEFAULT CHARSET=utf8mb4 COLLATE=utf8mb4_bin""",
    """CREATE TABLE IF NOT EXISTS engine_instances (
      id VARCHAR(191) PRIMARY KEY,
      status VARCHAR(32) NOT NULL,
      start_time VARCHAR(64) NOT NULL,
      end_time VARCHAR(64),
      engine_id VARCHAR(191) NOT NULL,
      engine_version VARCHAR(191) NOT NULL,
      engine_variant TEXT NOT NULL,
      engine_factory TEXT NOT NULL,
      batch TEXT NOT NULL,
      env LONGTEXT NOT NULL,
      runtime_conf LONGTEXT NOT NULL,
      data_source_params LONGTEXT NOT NULL,
      preparator_params LONGTEXT NOT NULL,
      algorithms_params LONGTEXT NOT NULL,
      serving_params LONGTEXT NOT NULL
    ) DEFAULT CHARSET=utf8mb4 COLLATE=utf8mb4_bin""",
    """CREATE TABLE IF NOT EXISTS evaluation_instances (
      id VARCHAR(191) PRIMARY KEY,
      status VARCHAR(32) NOT NULL,
      start_time VARCHAR(64) NOT NULL,
      end_time VARCHAR(64),
      evaluation_class TEXT NOT NULL,
      engine_params_generator_class TEXT NOT NULL,
      batch TEXT NOT NULL,
      env LONGTEXT NOT NULL,
      evaluator_results LONGTEXT NOT NULL,
      evaluator_results_html LONGTEXT NOT NULL,
      evaluator_results_json LONGTEXT NOT NULL
    ) DEFAULT CHARSET=utf8mb4 COLLATE=utf8mb4_bin""",
    """CREATE TABLE IF NOT EXISTS models (
      id VARCHAR(191) PRIMARY KEY,
      models LONGBLOB NOT NULL
    ) DEFAULT CHARSET=utf8mb4 COLLATE=utf8mb4_bin""",
    """CREATE TABLE IF NOT EXISTS event_channels (
      app_id BIGINT NOT NULL,
      channel_id BIGINT NOT NULL,
      PRIMARY KEY (app_id, channel_id)
    ) DEFAULT CHARSET=utf8mb4 COLLATE=utf8mb4_bin""",
    """CREATE TABLE IF NOT EXISTS events (
      event_id VARCHAR(191) NOT NULL,
      app_id BIGINT NOT NULL,
      channel_id BIGINT NOT NULL,
      event VARCHAR(191) NOT NULL,
      entity_type VARCHAR(191) NOT NULL,
      entity_id TEXT NOT NULL,
      target_entity_type TEXT,
      target_entity_id TEXT,
      properties LONGTEXT NOT NULL,
      event_time VARCHAR(64) NOT NULL,
      event_time_ms BIGINT NOT NULL,
      pr_id TEXT,
      creation_time VARCHAR(64) NOT NULL,
      PRIMARY KEY (app_id, channel_id, event_id)
    ) DEFAULT CHARSET=utf8mb4 COLLATE=utf8mb4_bin""",
    """CREATE INDEX idx_events_scan
      ON events (app_id, channel_id, entity_type, event_time_ms)""",
    """CREATE INDEX idx_events_name
      ON events (app_id, channel_id, event, event_time_ms)""",
]

# `key` is reserved in MySQL; the shared DAO SQL uses it bare ONLY as the
# access_keys column. \b keeps access_keys/keys intact; the rewrite is
# scoped to access_keys statements and skips single-quoted string literals,
# so a statement carrying 'key' as data is never mangled.
_KEY_TOKEN = re.compile(r"\bkey\b")
_SQUOTE_LITERAL = re.compile(r"('(?:[^']|'')*')")


def parse_connection_properties(props: dict[str, str]) -> dict:
    """URL/HOST/PORT/DBNAME/USERNAME/PASSWORD properties -> DB-API kwargs.

    Accepts the reference's ``jdbc:mysql://...`` URL form verbatim.
    """
    return sql_common.parse_jdbc_url_properties(
        props,
        schemes=("mysql", "mariadb"),
        backend_name="mysql",
        default_port=3306,
        dbname_key="database",
    )


def _connect(kwargs: dict):
    """PyMySQL first (pure python, commonest), then MySQLdb (mysqlclient)."""
    try:
        import pymysql
    except ImportError:
        pymysql = None
    if pymysql is not None:
        return pymysql.connect(charset="utf8mb4", **kwargs)
    try:
        import MySQLdb
    except ImportError as exc:
        raise RuntimeError(
            "the mysql storage backend requires PyMySQL or mysqlclient;"
            " install one or switch PIO_STORAGE_SOURCES_*_TYPE to 'sqlite'"
        ) from exc
    kwargs = dict(kwargs)
    kwargs["db"] = kwargs.pop("database")
    if "password" in kwargs:
        kwargs["passwd"] = kwargs.pop("password")
    return MySQLdb.connect(charset="utf8mb4", **kwargs)


class StorageClient(sql_common.SQLStorageClient):
    """Thread-safe MySQL connection with DDL auto-create."""

    placeholder = "%s"
    INSERT_IGNORE_EVENT_CHANNELS = (
        "INSERT IGNORE INTO event_channels (app_id, channel_id) VALUES (?, ?)"
    )
    UPSERT_MODEL = (
        "INSERT INTO models (id, models) VALUES (?, ?)"
        " ON DUPLICATE KEY UPDATE models = VALUES(models)"
    )
    INSERT_EVENTS_IGNORE_PREFIX = "INSERT IGNORE INTO events"
    INSERT_EVENTS_IGNORE_SUFFIX = ""
    # MySQL's JSON_TYPE vocabulary is uppercase and splits the numeric kinds
    JSON_NUMBER_EXPR = (
        "CASE WHEN JSON_TYPE(JSON_EXTRACT(properties, ?)) IN"
        " ('INTEGER', 'DOUBLE', 'DECIMAL', 'UNSIGNED INTEGER')"
        " THEN JSON_EXTRACT(properties, ?) END"
    )
    # MOD(), not the % operator: pymysql/mysqlclient %-interpolation would
    # eat a bare % in statement text (same truncated semantics)
    TIME_MOD_EXPR = "MOD(event_time_ms, {mod})"

    def __init__(self, config: StorageClientConfig):
        super().__init__(config)
        kwargs = parse_connection_properties(config.properties)
        self._connect_kwargs = kwargs
        self._conn = _connect(kwargs)
        self._lock = threading.RLock()
        with self._lock:
            cur = self._conn.cursor()
            for stmt in _SCHEMA_STATEMENTS:
                try:
                    cur.execute(stmt)
                except Exception as exc:
                    # MySQL's CREATE INDEX has no IF NOT EXISTS; only the
                    # duplicate-index-name error (1061) on re-connect is
                    # expected -- anything else (permissions, disk, lost
                    # connection) must surface
                    code = exc.args[0] if exc.args else None
                    if code != 1061:
                        raise
            cur.close()
            self._conn.commit()

    def sql(self, statement: str) -> str:
        if "access_keys" in statement:
            statement = "".join(
                part
                if part.startswith("'")
                else _KEY_TOKEN.sub("`key`", part)
                for part in _SQUOTE_LITERAL.split(statement)
            )
        return statement.replace("?", self.placeholder)

    def execute(self, sql: str, params: tuple = ()):
        with self._lock:
            cur = self._conn.cursor()
            try:
                cur.execute(sql, params)
                self._conn.commit()
                return sql_common.CursorResult(cur.rowcount)
            except Exception:
                self._conn.rollback()
                raise
            finally:
                cur.close()

    def executemany(self, sql: str, rows: list[tuple]):
        with self._lock:
            cur = self._conn.cursor()
            try:
                cur.executemany(sql, rows)
                self._conn.commit()
                return sql_common.CursorResult(cur.rowcount)
            except Exception:
                self._conn.rollback()
                raise
            finally:
                cur.close()

    def insert_returning_id(self, sql: str, params: tuple) -> int:
        with self._lock:
            cur = self._conn.cursor()
            try:
                cur.execute(sql, params)
                self._conn.commit()
                return cur.lastrowid
            except Exception:
                self._conn.rollback()
                raise
            finally:
                cur.close()

    def query(self, sql: str, params: tuple = ()) -> list[tuple]:
        with self._lock:
            cur = self._conn.cursor()
            try:
                cur.execute(sql, params)
                rows = cur.fetchall()
                # end the implicit read transaction: under InnoDB REPEATABLE
                # READ a never-committed reader keeps a frozen snapshot and
                # stops seeing other processes' committed writes
                self._conn.commit()
                return rows
            except Exception:
                self._conn.rollback()
                raise
            finally:
                cur.close()

    def query_iter(self, sql: str, params: tuple = ()) -> Iterator[tuple]:
        """Stream on a dedicated connection with an unbuffered cursor so a
        multi-GB event scan never materializes client-side (the PyMySQL
        SSCursor / MySQLdb SSCursor server-side streaming cursor)."""
        conn = _connect(self._connect_kwargs)
        try:
            cursor_cls = None
            try:
                from pymysql.cursors import SSCursor as cursor_cls  # noqa: F811
            except ImportError:
                try:
                    from MySQLdb.cursors import SSCursor as cursor_cls  # noqa: F811
                except ImportError:
                    pass
            cur = conn.cursor(cursor_cls) if cursor_cls else conn.cursor()
            try:
                cur.execute(sql, params)
                while True:
                    rows = cur.fetchmany(1024)
                    if not rows:
                        return
                    yield from rows
            finally:
                cur.close()
        finally:
            conn.close()

    def close(self) -> None:
        with self._lock:
            self._conn.close()


