"""MySQL storage backend (TYPE=mysql)."""

from predictionio_tpu.data.storage.mysql.client import StorageClient

__all__ = ["StorageClient"]
