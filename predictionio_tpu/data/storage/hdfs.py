"""HDFS model blob store over the WebHDFS REST API.

Parity role of reference ``storage/hdfs/.../HDFSModels.scala`` (apache/
predictionio layout, unverified -- SURVEY.md section 2.2 #11): a
``Models``-only backend writing one file per engine instance. The reference
used the Hadoop FileSystem client library; a JVM-free rebuild speaks
WebHDFS (the namenode's stock REST endpoint) directly over urllib -- no
driver dependency at all.

Configuration (reference env-var contract, SURVEY.md section 5.6):

    PIO_STORAGE_SOURCES_HDFS_TYPE=hdfs
    PIO_STORAGE_SOURCES_HDFS_HOSTS=namenode      (WebHDFS host)
    PIO_STORAGE_SOURCES_HDFS_PORTS=9870          (9870 Hadoop 3.x, 50070 2.x)
    PIO_STORAGE_SOURCES_HDFS_PATH=/pio/models    (base directory)
    PIO_STORAGE_SOURCES_HDFS_USERNAME=pio        (optional user.name= auth)
    PIO_STORAGE_SOURCES_HDFS_TRANSPORT=fake      (in-memory; CI only)

WebHDFS protocol notes: CREATE/OPEN are two-step -- the namenode answers
with a redirect to a datanode. urllib follows the GET redirect natively;
for PUT we request ``noredirect=true`` (Hadoop 2.8+: 200 + JSON Location)
and fall back to reading the 307 Location header.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request
from typing import Optional

from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import Model, StorageClientConfig


class WebHDFSTransport:
    """Minimal WebHDFS client: write / read / delete one file."""

    def __init__(self, base_url: str, user: str = "", timeout: float = 60.0):
        self.base_url = base_url.rstrip("/")
        self.user = user
        self.timeout = timeout

    def _url(self, path: str, op: str, **params) -> str:
        q = {"op": op, **params}
        if self.user:
            q["user.name"] = self.user
        return (
            f"{self.base_url}/webhdfs/v1{urllib.parse.quote(path)}"
            f"?{urllib.parse.urlencode(q)}"
        )

    def _request(self, method: str, url: str, data: bytes | None = None):
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Content-Type", "application/octet-stream")
        return urllib.request.urlopen(req, timeout=self.timeout)

    def write(self, path: str, data: bytes) -> None:
        url = self._url(path, "CREATE", overwrite="true", noredirect="true")
        location = None
        try:
            with self._request("PUT", url) as resp:
                payload = resp.read()
                if payload:
                    location = json.loads(payload).get("Location")
        except urllib.error.HTTPError as exc:
            if exc.code != 307:  # older namenodes redirect instead
                raise
            location = exc.headers.get("Location")
        if not location:
            raise RuntimeError(
                f"webhdfs CREATE for {path!r} returned no datanode location"
            )
        with self._request("PUT", location, data=data) as resp:
            if resp.status not in (200, 201):
                raise RuntimeError(
                    f"webhdfs datanode write for {path!r} failed: {resp.status}"
                )

    def read(self, path: str) -> bytes | None:
        try:
            # urllib follows the namenode->datanode redirect for GET
            with self._request("GET", self._url(path, "OPEN")) as resp:
                return resp.read()
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                return None
            raise

    def delete(self, path: str) -> bool:
        try:
            with self._request("DELETE", self._url(path, "DELETE")) as resp:
                return bool(json.loads(resp.read()).get("boolean"))
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                return False
            raise


class FakeTransport:
    """In-memory WebHDFS stand-in (this CI image has no HDFS; SURVEY.md
    section 4 tier 2 runs the same DAO suite against real backends)."""

    def __init__(self):
        self.files: dict[str, bytes] = {}

    def write(self, path: str, data: bytes) -> None:
        self.files[path] = bytes(data)

    def read(self, path: str) -> bytes | None:
        return self.files.get(path)

    def delete(self, path: str) -> bool:
        return self.files.pop(path, None) is not None


class StorageClient(base.BaseStorageClient):
    def __init__(self, config: StorageClientConfig, transport=None):
        super().__init__(config)
        props = config.properties
        self.base_path = "/" + props.get("PATH", "/pio/models").strip("/")
        if transport is not None:
            self.transport = transport
        elif props.get("TRANSPORT", "").lower() == "fake":
            self.transport = FakeTransport()
        else:
            host = (props.get("HOSTS", "localhost")).split(",")[0]
            port = (props.get("PORTS", "9870")).split(",")[0]
            scheme = (props.get("SCHEMES", "http")).split(",")[0]
            self.transport = WebHDFSTransport(
                f"{scheme}://{host}:{port}", user=props.get("USERNAME", "")
            )

    def get_dao(self, repo: str):
        if repo != "models":
            raise NotImplementedError(
                f"hdfs backend only provides the 'models' repository, not {repo!r}"
            )
        return HDFSModels(self.transport, self.base_path)

    def close(self) -> None:
        pass


class HDFSModels(base.Models):
    def __init__(self, transport, base_path: str):
        self.transport = transport
        self.base_path = base_path

    def _path(self, model_id: str) -> str:
        return f"{self.base_path}/{base.safe_blob_name(model_id)}"

    def insert(self, model: Model) -> None:
        self.transport.write(self._path(model.id), model.models)

    def get(self, model_id: str) -> Optional[Model]:
        data = self.transport.read(self._path(model_id))
        return Model(id=model_id, models=data) if data is not None else None

    def delete(self, model_id: str) -> None:
        self.transport.delete(self._path(model_id))
