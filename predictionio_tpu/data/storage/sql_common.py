"""Shared SQL DAO implementations, parameterized by dialect.

One copy of the relational mapping serves every SQL backend (parity role of
the reference's scalikejdbc-based JDBC module, ``storage/jdbc/.../JDBC*.scala``
-- apache/predictionio layout, unverified, SURVEY.md section 2.2 #10, which
likewise serves PostgreSQL and MySQL from one DAO set). Backends subclass
:class:`SQLStorageClient` and provide a DB-API connection plus the few
statements that differ by dialect (auto-id inserts, upserts, schema DDL).

DAO SQL is written with ``?`` placeholders; the client rewrites them to the
backend's paramstyle. None of the statements embed a literal ``?``.
"""

from __future__ import annotations

import abc
import datetime as _dt
import json
import secrets
import uuid
from typing import Iterable, Iterator, Optional

from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EvaluationInstance,
    Model,
)

#: channel_id column value for the default channel (reference uses None).
DEFAULT_CHANNEL = 0

#: find(limit=N) at or under this uses the plain materializing query path --
#: a handful of rows never justifies a dedicated streaming connection (the
#: event server's GET /events.json hot path runs find(limit=20) per request)
SMALL_SCAN_LIMIT = 1000


class CursorResult:
    """Minimal ``rowcount`` carrier for backends whose cursors are closed
    before the DAO inspects the result."""

    def __init__(self, rowcount: int):
        self.rowcount = rowcount


def parse_jdbc_url_properties(
    props: dict[str, str],
    schemes: tuple[str, ...],
    backend_name: str,
    default_port: int,
    dbname_key: str = "dbname",
    query_keys: tuple[str, ...] = ("user", "password", "connect_timeout"),
) -> dict:
    """Shared URL/HOST/PORT/DBNAME/USERNAME/PASSWORD -> DB-API kwargs parsing.

    One copy serves every SQL dialect (the reference's JDBCUtils analogue):
    accepts the reference's ``jdbc:<scheme>://...`` URL form verbatim, with
    explicit HOST/PORT/DBNAME/USERNAME/PASSWORD properties overriding URL
    parts, and scheme validation against the dialect's accepted set.
    """
    from urllib.parse import parse_qs, urlparse

    kwargs: dict = {}
    url = props.get("URL", "")
    if url:
        if url.startswith("jdbc:"):
            url = url[len("jdbc:"):]
        parsed = urlparse(url)
        if parsed.scheme not in schemes:
            raise ValueError(
                f"unsupported URL scheme {parsed.scheme!r} for {backend_name} storage"
            )
        if parsed.hostname:
            kwargs["host"] = parsed.hostname
        if parsed.port:
            kwargs["port"] = parsed.port
        dbname = (parsed.path or "").lstrip("/")
        if dbname:
            kwargs[dbname_key] = dbname
        if parsed.username:
            kwargs["user"] = parsed.username
        if parsed.password:
            kwargs["password"] = parsed.password
        for key, values in parse_qs(parsed.query).items():
            if key in query_keys:
                value = values[-1]
                # MySQL drivers require a real int for connect_timeout;
                # credentials must stay strings even when all-digit
                if key == "connect_timeout" and value.isdigit():
                    kwargs[key] = int(value)
                else:
                    kwargs[key] = value
    if props.get("HOST"):
        kwargs["host"] = props["HOST"]
    if props.get("PORT"):
        kwargs["port"] = int(props["PORT"])
    if props.get("DBNAME"):
        kwargs[dbname_key] = props["DBNAME"]
    if props.get("USERNAME"):
        kwargs["user"] = props["USERNAME"]
    if props.get("PASSWORD"):
        kwargs["password"] = props["PASSWORD"]
    kwargs.setdefault("host", "localhost")
    kwargs.setdefault("port", default_port)
    kwargs.setdefault(dbname_key, "pio")
    return kwargs


def ts_to_str(ts: _dt.datetime | None) -> str | None:
    # normalize to UTC with fixed precision so text ORDER BY is chronological
    if ts is None:
        return None
    if ts.tzinfo is None:
        ts = ts.replace(tzinfo=_dt.timezone.utc)
    return ts.astimezone(_dt.timezone.utc).isoformat(timespec="microseconds")


def ts_from_str(s: str | None) -> _dt.datetime | None:
    return _dt.datetime.fromisoformat(s) if s else None


def ts_ms(ts: _dt.datetime) -> int:
    # same naive-means-UTC rule as Event.__post_init__, so stored values and
    # find() bounds agree on any host timezone
    if ts.tzinfo is None:
        ts = ts.replace(tzinfo=_dt.timezone.utc)
    return int(ts.timestamp() * 1000)


class SQLStorageClient(base.BaseStorageClient):
    """Backend contract the shared DAOs run against.

    Subclasses implement the five statement runners and set the dialect
    statements below. ``?`` placeholders in DAO SQL are rewritten via
    :meth:`sql` before execution.
    """

    #: paramstyle placeholder ("?" for sqlite, "%s" for postgres)
    placeholder = "?"
    #: insert-or-ignore into event_channels(app_id, channel_id)
    INSERT_IGNORE_EVENT_CHANNELS = (
        "INSERT OR IGNORE INTO event_channels (app_id, channel_id) VALUES (?, ?)"
    )
    #: upsert into models(id, models)
    UPSERT_MODEL = "INSERT OR REPLACE INTO models (id, models) VALUES (?, ?)"
    #: events insert that silently skips duplicate (app_id, channel_id,
    #: event_id) rows -- the WAL-replay idempotence statement. sqlite form
    #: here; postgres/mysql override. (prefix/suffix split because the
    #: dialects disagree on where the ignore clause goes.)
    INSERT_EVENTS_IGNORE_PREFIX = "INSERT OR IGNORE INTO events"
    INSERT_EVENTS_IGNORE_SUFFIX = ""
    #: dialect JSON extraction over the properties column, NUMBERS ONLY --
    #: NULL for strings/bools/objects, matching EventDataset.from_events'
    #: isinstance(int|float)-and-not-bool rating rule exactly. Placeholders
    #: bind to :meth:`json_number_params` in order. (sqlite form here;
    #: postgres/mysql override.)
    JSON_NUMBER_EXPR = (
        "CASE WHEN json_type(properties, ?) IN ('integer', 'real')"
        " THEN json_extract(properties, ?) END"
    )
    #: dialect modulo over event_time_ms ({mod} formatted in) -- the
    #: snapshot digest's per-row checksum term. sqlite has only the ``%``
    #: operator (MOD() needs a math-functions build); the %s-paramstyle
    #: dialects override with MOD(): a bare ``%`` in statement text would
    #: be eaten by psycopg2/pymysql's client-side interpolation. All three
    #: forms use TRUNCATED (sign-of-dividend) semantics.
    TIME_MOD_EXPR = "event_time_ms % {mod}"

    @classmethod
    def json_number_params(cls, key: str) -> tuple:
        """Bind values for JSON_NUMBER_EXPR's placeholders, in order."""
        path = cls._json_path(key)
        return (path, path)

    @staticmethod
    def _json_path(key: str) -> str:
        # JSON-path escaping is backslash-style (doubling quotes is SQL
        # string escaping and silently matches nothing in sqlite)
        escaped = key.replace("\\", "\\\\").replace('"', '\\"')
        return f'$."{escaped}"'

    def sql(self, statement: str) -> str:
        if self.placeholder == "?":
            return statement
        return statement.replace("?", self.placeholder)

    @abc.abstractmethod
    def execute(self, sql: str, params: tuple = ()):
        """Run one write statement; returns an object with ``rowcount``."""

    @abc.abstractmethod
    def executemany(self, sql: str, rows: list[tuple]): ...

    @abc.abstractmethod
    def insert_returning_id(self, sql: str, params: tuple) -> int:
        """Run an INSERT on a table with an auto-increment ``id``; return it."""

    @abc.abstractmethod
    def query(self, sql: str, params: tuple = ()) -> list[tuple]: ...

    @abc.abstractmethod
    def query_iter(self, sql: str, params: tuple = ()) -> Iterator[tuple]: ...

    def get_dao(self, repo: str):
        return {
            "apps": SQLApps,
            "channels": SQLChannels,
            "access_keys": SQLAccessKeys,
            "engine_instances": SQLEngineInstances,
            "evaluation_instances": SQLEvaluationInstances,
            "models": SQLModels,
            "events": SQLLEvents,
        }[repo](self)


class SQLApps(base.Apps):
    def __init__(self, client: SQLStorageClient):
        self.c = client

    def insert(self, app: App) -> int:
        app.id = self.c.insert_returning_id(
            self.c.sql("INSERT INTO apps (name, description) VALUES (?, ?)"),
            (app.name, app.description),
        )
        return app.id

    def get(self, app_id: int) -> Optional[App]:
        rows = self.c.query(
            self.c.sql("SELECT id, name, description FROM apps WHERE id=?"), (app_id,)
        )
        return App(id=rows[0][0], name=rows[0][1], description=rows[0][2]) if rows else None

    def get_by_name(self, name: str) -> Optional[App]:
        rows = self.c.query(
            self.c.sql("SELECT id, name, description FROM apps WHERE name=?"), (name,)
        )
        return App(id=rows[0][0], name=rows[0][1], description=rows[0][2]) if rows else None

    def get_all(self) -> list[App]:
        rows = self.c.query("SELECT id, name, description FROM apps ORDER BY id")
        return [App(id=r[0], name=r[1], description=r[2]) for r in rows]

    def update(self, app: App) -> None:
        self.c.execute(
            self.c.sql("UPDATE apps SET name=?, description=? WHERE id=?"),
            (app.name, app.description, app.id),
        )

    def delete(self, app_id: int) -> None:
        self.c.execute(self.c.sql("DELETE FROM apps WHERE id=?"), (app_id,))


class SQLChannels(base.Channels):
    def __init__(self, client: SQLStorageClient):
        self.c = client

    def insert(self, channel: Channel) -> int:
        channel.id = self.c.insert_returning_id(
            self.c.sql("INSERT INTO channels (name, app_id) VALUES (?, ?)"),
            (channel.name, channel.app_id),
        )
        return channel.id

    def get(self, channel_id: int) -> Optional[Channel]:
        rows = self.c.query(
            self.c.sql("SELECT id, name, app_id FROM channels WHERE id=?"),
            (channel_id,),
        )
        return Channel(id=rows[0][0], name=rows[0][1], app_id=rows[0][2]) if rows else None

    def get_by_app(self, app_id: int) -> list[Channel]:
        rows = self.c.query(
            self.c.sql(
                "SELECT id, name, app_id FROM channels WHERE app_id=? ORDER BY id"
            ),
            (app_id,),
        )
        return [Channel(id=r[0], name=r[1], app_id=r[2]) for r in rows]

    def delete(self, channel_id: int) -> None:
        self.c.execute(self.c.sql("DELETE FROM channels WHERE id=?"), (channel_id,))


class SQLAccessKeys(base.AccessKeys):
    def __init__(self, client: SQLStorageClient):
        self.c = client

    def insert(self, access_key: AccessKey) -> str:
        key = access_key.key or secrets.token_urlsafe(48)
        self.c.execute(
            self.c.sql("INSERT INTO access_keys (key, app_id, events) VALUES (?, ?, ?)"),
            (key, access_key.app_id, json.dumps(access_key.events)),
        )
        access_key.key = key
        return key

    def get(self, key: str) -> Optional[AccessKey]:
        rows = self.c.query(
            self.c.sql("SELECT key, app_id, events FROM access_keys WHERE key=?"),
            (key,),
        )
        if not rows:
            return None
        return AccessKey(key=rows[0][0], app_id=rows[0][1], events=json.loads(rows[0][2]))

    def get_all(self) -> list[AccessKey]:
        # must go through sql(): `key` is reserved on MySQL
        rows = self.c.query(
            self.c.sql("SELECT key, app_id, events FROM access_keys")
        )
        return [AccessKey(key=r[0], app_id=r[1], events=json.loads(r[2])) for r in rows]

    def get_by_app_id(self, app_id: int) -> list[AccessKey]:
        rows = self.c.query(
            self.c.sql("SELECT key, app_id, events FROM access_keys WHERE app_id=?"),
            (app_id,),
        )
        return [AccessKey(key=r[0], app_id=r[1], events=json.loads(r[2])) for r in rows]

    def update(self, access_key: AccessKey) -> None:
        self.c.execute(
            self.c.sql("UPDATE access_keys SET app_id=?, events=? WHERE key=?"),
            (access_key.app_id, json.dumps(access_key.events), access_key.key),
        )

    def delete(self, key: str) -> None:
        self.c.execute(self.c.sql("DELETE FROM access_keys WHERE key=?"), (key,))


class SQLEngineInstances(base.EngineInstances):
    _COLS = (
        "id, status, start_time, end_time, engine_id, engine_version, engine_variant,"
        " engine_factory, batch, env, runtime_conf, data_source_params,"
        " preparator_params, algorithms_params, serving_params"
    )

    def __init__(self, client: SQLStorageClient):
        self.c = client

    def _row_to_instance(self, r: tuple) -> EngineInstance:
        return EngineInstance(
            id=r[0],
            status=r[1],
            start_time=ts_from_str(r[2]),
            end_time=ts_from_str(r[3]),
            engine_id=r[4],
            engine_version=r[5],
            engine_variant=r[6],
            engine_factory=r[7],
            batch=r[8],
            env=json.loads(r[9]),
            runtime_conf=json.loads(r[10]),
            data_source_params=r[11],
            preparator_params=r[12],
            algorithms_params=r[13],
            serving_params=r[14],
        )

    def insert(self, instance: EngineInstance) -> str:
        instance.id = instance.id or uuid.uuid4().hex
        self.c.execute(
            self.c.sql(
                f"INSERT INTO engine_instances ({self._COLS}) VALUES "
                "(?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)"
            ),
            (
                instance.id,
                instance.status,
                ts_to_str(instance.start_time),
                ts_to_str(instance.end_time),
                instance.engine_id,
                instance.engine_version,
                instance.engine_variant,
                instance.engine_factory,
                instance.batch,
                json.dumps(instance.env),
                json.dumps(instance.runtime_conf),
                instance.data_source_params,
                instance.preparator_params,
                instance.algorithms_params,
                instance.serving_params,
            ),
        )
        return instance.id

    def get(self, instance_id: str) -> Optional[EngineInstance]:
        rows = self.c.query(
            self.c.sql(f"SELECT {self._COLS} FROM engine_instances WHERE id=?"),
            (instance_id,),
        )
        return self._row_to_instance(rows[0]) if rows else None

    def get_all(self) -> list[EngineInstance]:
        rows = self.c.query(
            f"SELECT {self._COLS} FROM engine_instances ORDER BY start_time DESC"
        )
        return [self._row_to_instance(r) for r in rows]

    def get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> list[EngineInstance]:
        rows = self.c.query(
            self.c.sql(
                f"SELECT {self._COLS} FROM engine_instances WHERE status=? AND engine_id=?"
                " AND engine_version=? AND engine_variant=? ORDER BY start_time DESC"
            ),
            (base.STATUS_COMPLETED, engine_id, engine_version, engine_variant),
        )
        return [self._row_to_instance(r) for r in rows]

    def get_latest_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> Optional[EngineInstance]:
        completed = self.get_completed(engine_id, engine_version, engine_variant)
        return completed[0] if completed else None

    def get_latest(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> Optional[EngineInstance]:
        rows = self.c.query(
            self.c.sql(
                f"SELECT {self._COLS} FROM engine_instances WHERE engine_id=?"
                " AND engine_version=? AND engine_variant=?"
                " ORDER BY start_time DESC LIMIT 1"
            ),
            (engine_id, engine_version, engine_variant),
        )
        return self._row_to_instance(rows[0]) if rows else None

    def update(self, instance: EngineInstance) -> None:
        self.c.execute(
            self.c.sql(
                "UPDATE engine_instances SET status=?, start_time=?, end_time=?,"
                " engine_id=?, engine_version=?, engine_variant=?, engine_factory=?,"
                " batch=?, env=?, runtime_conf=?, data_source_params=?,"
                " preparator_params=?, algorithms_params=?, serving_params=? WHERE id=?"
            ),
            (
                instance.status,
                ts_to_str(instance.start_time),
                ts_to_str(instance.end_time),
                instance.engine_id,
                instance.engine_version,
                instance.engine_variant,
                instance.engine_factory,
                instance.batch,
                json.dumps(instance.env),
                json.dumps(instance.runtime_conf),
                instance.data_source_params,
                instance.preparator_params,
                instance.algorithms_params,
                instance.serving_params,
                instance.id,
            ),
        )

    def delete(self, instance_id: str) -> None:
        self.c.execute(
            self.c.sql("DELETE FROM engine_instances WHERE id=?"), (instance_id,)
        )


class SQLEvaluationInstances(base.EvaluationInstances):
    _COLS = (
        "id, status, start_time, end_time, evaluation_class,"
        " engine_params_generator_class, batch, env, evaluator_results,"
        " evaluator_results_html, evaluator_results_json"
    )

    def __init__(self, client: SQLStorageClient):
        self.c = client

    def _row_to_instance(self, r: tuple) -> EvaluationInstance:
        return EvaluationInstance(
            id=r[0],
            status=r[1],
            start_time=ts_from_str(r[2]),
            end_time=ts_from_str(r[3]),
            evaluation_class=r[4],
            engine_params_generator_class=r[5],
            batch=r[6],
            env=json.loads(r[7]),
            evaluator_results=r[8],
            evaluator_results_html=r[9],
            evaluator_results_json=r[10],
        )

    def insert(self, instance: EvaluationInstance) -> str:
        instance.id = instance.id or uuid.uuid4().hex
        self.c.execute(
            self.c.sql(
                f"INSERT INTO evaluation_instances ({self._COLS}) VALUES"
                " (?,?,?,?,?,?,?,?,?,?,?)"
            ),
            (
                instance.id,
                instance.status,
                ts_to_str(instance.start_time),
                ts_to_str(instance.end_time),
                instance.evaluation_class,
                instance.engine_params_generator_class,
                instance.batch,
                json.dumps(instance.env),
                instance.evaluator_results,
                instance.evaluator_results_html,
                instance.evaluator_results_json,
            ),
        )
        return instance.id

    def get(self, instance_id: str) -> Optional[EvaluationInstance]:
        rows = self.c.query(
            self.c.sql(f"SELECT {self._COLS} FROM evaluation_instances WHERE id=?"),
            (instance_id,),
        )
        return self._row_to_instance(rows[0]) if rows else None

    def get_all(self) -> list[EvaluationInstance]:
        rows = self.c.query(
            f"SELECT {self._COLS} FROM evaluation_instances ORDER BY start_time DESC"
        )
        return [self._row_to_instance(r) for r in rows]

    def get_completed(self) -> list[EvaluationInstance]:
        rows = self.c.query(
            self.c.sql(
                f"SELECT {self._COLS} FROM evaluation_instances WHERE status=?"
                " ORDER BY start_time DESC"
            ),
            (base.STATUS_COMPLETED,),
        )
        return [self._row_to_instance(r) for r in rows]

    def update(self, instance: EvaluationInstance) -> None:
        self.c.execute(
            self.c.sql(
                "UPDATE evaluation_instances SET status=?, start_time=?, end_time=?,"
                " evaluation_class=?, engine_params_generator_class=?, batch=?, env=?,"
                " evaluator_results=?, evaluator_results_html=?, evaluator_results_json=?"
                " WHERE id=?"
            ),
            (
                instance.status,
                ts_to_str(instance.start_time),
                ts_to_str(instance.end_time),
                instance.evaluation_class,
                instance.engine_params_generator_class,
                instance.batch,
                json.dumps(instance.env),
                instance.evaluator_results,
                instance.evaluator_results_html,
                instance.evaluator_results_json,
                instance.id,
            ),
        )

    def delete(self, instance_id: str) -> None:
        self.c.execute(
            self.c.sql("DELETE FROM evaluation_instances WHERE id=?"), (instance_id,)
        )


class SQLModels(base.Models):
    def __init__(self, client: SQLStorageClient):
        self.c = client

    def insert(self, model: Model) -> None:
        self.c.execute(self.c.sql(self.c.UPSERT_MODEL), (model.id, model.models))

    def get(self, model_id: str) -> Optional[Model]:
        rows = self.c.query(
            self.c.sql("SELECT id, models FROM models WHERE id=?"), (model_id,)
        )
        return Model(id=rows[0][0], models=bytes(rows[0][1])) if rows else None

    def delete(self, model_id: str) -> None:
        self.c.execute(self.c.sql("DELETE FROM models WHERE id=?"), (model_id,))


class SQLLEvents(base.LEvents):
    def __init__(self, client: SQLStorageClient):
        self.c = client

    @staticmethod
    def _ch(channel_id: int | None) -> int:
        return DEFAULT_CHANNEL if channel_id is None else channel_id

    def init_channel(self, app_id: int, channel_id: int | None = None) -> bool:
        self.c.execute(
            self.c.sql(self.c.INSERT_IGNORE_EVENT_CHANNELS),
            (app_id, self._ch(channel_id)),
        )
        return True

    def remove_channel(self, app_id: int, channel_id: int | None = None) -> bool:
        ch = self._ch(channel_id)
        self.c.execute(
            self.c.sql("DELETE FROM events WHERE app_id=? AND channel_id=?"),
            (app_id, ch),
        )
        self.c.execute(
            self.c.sql("DELETE FROM event_channels WHERE app_id=? AND channel_id=?"),
            (app_id, ch),
        )
        return True

    _EVENT_INSERT_COLS = (
        "(event_id, app_id, channel_id, event,"
        " entity_type, entity_id, target_entity_type, target_entity_id,"
        " properties, event_time, event_time_ms, pr_id, creation_time)"
        " VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?)"
    )

    def _event_row(self, ev: Event, app_id: int, channel_id: int | None) -> tuple:
        return (
            ev.event_id,
            app_id,
            self._ch(channel_id),
            ev.event,
            ev.entity_type,
            ev.entity_id,
            ev.target_entity_type,
            ev.target_entity_id,
            json.dumps(ev.properties.to_dict()),
            ev.event_time.isoformat(),
            ts_ms(ev.event_time),
            ev.pr_id,
            ev.creation_time.isoformat(),
        )

    def insert(self, event: Event, app_id: int, channel_id: int | None = None) -> str:
        return self.batch_insert([event], app_id, channel_id)[0]

    def batch_insert(
        self, events: Iterable[Event], app_id: int, channel_id: int | None = None
    ) -> list[str]:
        return self.insert_batch((ev, app_id, channel_id) for ev in events)

    def insert_batch(
        self,
        items: Iterable[tuple[Event, int, int | None]],
        on_duplicate: str = "error",
    ) -> list[str]:
        """One ``executemany`` (= one transaction on every SQL backend) for a
        group commit spanning apps/channels -- the ingest pipeline's flush
        path. ``on_duplicate="error"`` keeps the append-only contract: a
        duplicate event_id is a caller bug and surfaces as an IntegrityError;
        ``"ignore"`` is the WAL-replay idempotence mode."""
        if on_duplicate not in ("error", "ignore"):
            raise ValueError(f"on_duplicate must be error|ignore, got {on_duplicate!r}")
        rows, ids = [], []
        for ev, app_id, channel_id in items:
            ev = ev if ev.event_id else ev.with_id()
            ids.append(ev.event_id)
            rows.append(self._event_row(ev, app_id, channel_id))
        if not rows:
            return ids
        prefix = (
            self.c.INSERT_EVENTS_IGNORE_PREFIX
            if on_duplicate == "ignore"
            else "INSERT INTO events"
        )
        suffix = self.c.INSERT_EVENTS_IGNORE_SUFFIX if on_duplicate == "ignore" else ""
        self.c.executemany(
            self.c.sql(f"{prefix} {self._EVENT_INSERT_COLS}{suffix}"), rows
        )
        return ids

    @staticmethod
    def _row_to_event(r: tuple) -> Event:
        return Event(
            event_id=r[0],
            event=r[1],
            entity_type=r[2],
            entity_id=r[3],
            target_entity_type=r[4],
            target_entity_id=r[5],
            properties=DataMap(json.loads(r[6])),
            event_time=_dt.datetime.fromisoformat(r[7]),
            pr_id=r[8],
            creation_time=_dt.datetime.fromisoformat(r[9]),
        )

    _EVENT_COLS = (
        "event_id, event, entity_type, entity_id, target_entity_type,"
        " target_entity_id, properties, event_time, pr_id, creation_time"
    )

    def get(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> Optional[Event]:
        rows = self.c.query(
            self.c.sql(
                f"SELECT {self._EVENT_COLS} FROM events"
                " WHERE app_id=? AND channel_id=? AND event_id=?"
            ),
            (app_id, self._ch(channel_id), event_id),
        )
        return self._row_to_event(rows[0]) if rows else None

    def delete(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> bool:
        cur = self.c.execute(
            self.c.sql(
                "DELETE FROM events WHERE app_id=? AND channel_id=? AND event_id=?"
            ),
            (app_id, self._ch(channel_id), event_id),
        )
        return cur.rowcount > 0

    @staticmethod
    def _append_filters(
        sql: list,
        params: list,
        *,
        start_time: _dt.datetime | None = None,
        until_time: _dt.datetime | None = None,
        entity_type: str | None = None,
        entity_id: str | None = None,
        event_names: list[str] | None = None,
        target_entity_type=...,
        target_entity_id=...,
    ) -> None:
        """WHERE-clause builder shared by find() and scan_interactions():
        one definition so the row and columnar paths cannot desynchronize
        their filter semantics."""
        if start_time is not None:
            sql.append("AND event_time_ms >= ?")
            params.append(ts_ms(start_time))
        if until_time is not None:
            sql.append("AND event_time_ms < ?")
            params.append(ts_ms(until_time))
        if entity_type is not None:
            sql.append("AND entity_type = ?")
            params.append(entity_type)
        if entity_id is not None:
            sql.append("AND entity_id = ?")
            params.append(entity_id)
        if event_names:
            sql.append(f"AND event IN ({','.join('?' * len(event_names))})")
            params.extend(event_names)
        if target_entity_type is not ...:
            if target_entity_type is None:
                sql.append("AND target_entity_type IS NULL")
            else:
                sql.append("AND target_entity_type = ?")
                params.append(target_entity_type)
        if target_entity_id is not ...:
            if target_entity_id is None:
                sql.append("AND target_entity_id IS NULL")
            else:
                sql.append("AND target_entity_id = ?")
                params.append(target_entity_id)

    def find(
        self,
        app_id: int,
        channel_id: int | None = None,
        start_time: _dt.datetime | None = None,
        until_time: _dt.datetime | None = None,
        entity_type: str | None = None,
        entity_id: str | None = None,
        event_names: list[str] | None = None,
        target_entity_type=...,
        target_entity_id=...,
        limit: int | None = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        sql = [
            f"SELECT {self._EVENT_COLS} FROM events WHERE app_id=? AND channel_id=?"
        ]
        params: list = [app_id, self._ch(channel_id)]
        self._append_filters(
            sql,
            params,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            entity_id=entity_id,
            event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id,
        )
        sql.append(f"ORDER BY event_time_ms {'DESC' if reversed else 'ASC'}")
        if limit is not None and limit >= 0:
            sql.append("LIMIT ?")
            params.append(limit)
        # small bounded scans (the event server's GET hot path runs
        # find(limit=20) per request) take the plain query path; only
        # unbounded/large scans pay for a dedicated streaming connection
        small = limit is not None and 0 <= limit <= SMALL_SCAN_LIMIT
        runner = self.c.query if small else self.c.query_iter
        for r in runner(self.c.sql(" ".join(sql)), tuple(params)):
            yield self._row_to_event(r)

    def scan_interactions(
        self,
        app_id: int,
        channel_id: int | None = None,
        event_names: list[str] | None = None,
        target_entity_type=...,
        start_time: _dt.datetime | None = None,
        until_time: _dt.datetime | None = None,
        rating_key: str = "rating",
    ):
        """Columnar training scan: the dataset-builder's fast path.

        Returns ``(entity_ids, target_entity_ids, event_names,
        event_times_iso, ratings_raw)`` -- five python lists -- WITHOUT
        constructing an Event (or json-parsing properties) per row: the
        rating is extracted server-side via the dialect's numbers-only JSON
        expression, so string/bool ratings come back NULL exactly like the
        row path's isinstance check. ``event_times_iso`` carries the stored
        ISO8601 strings (full microsecond precision; event_time_ms would
        truncate sub-ms ordering the row path preserves). Time-ordered like
        ``find`` (event_time_ms ASC, event_id tie-break). At ML-20M scale
        this is the difference between seconds and minutes of ``pio
        train`` read time.
        """
        cols: tuple[list, ...] = ([], [], [], [], [])
        for chunk in self.iter_interaction_chunks(
            app_id=app_id,
            channel_id=channel_id,
            event_names=event_names,
            target_entity_type=target_entity_type,
            start_time=start_time,
            until_time=until_time,
            rating_key=rating_key,
        ):
            for acc, part in zip(cols, chunk):
                acc.extend(part)
        return cols

    def count_interactions(
        self,
        app_id: int,
        channel_id: int | None = None,
        event_names: list[str] | None = None,
        target_entity_type=...,
        start_time: _dt.datetime | None = None,
        until_time: _dt.datetime | None = None,
    ) -> int:
        """Row count of one bounded interaction scan -- a single SQL
        aggregate, no row transfer. The snapshot layer uses it to verify
        that a snapshot's covered prefix still matches the event table
        (late-arriving or deleted events force a full rebuild instead of
        an inexact append refresh). Shares find()/scan_interactions()'s
        filter builder so the three paths cannot disagree on semantics.
        """
        sql = ["SELECT COUNT(*) FROM events WHERE app_id=? AND channel_id=?"]
        params: list = [app_id, self._ch(channel_id)]
        self._append_filters(
            sql,
            params,
            start_time=start_time,
            until_time=until_time,
            event_names=event_names,
            target_entity_type=target_entity_type,
        )
        return int(self.c.query(self.c.sql(" ".join(sql)), tuple(params))[0][0])

    def interaction_digest(
        self,
        app_id: int,
        channel_id: int | None = None,
        event_names: list[str] | None = None,
        target_entity_type=...,
        start_time: _dt.datetime | None = None,
        until_time: _dt.datetime | None = None,
    ) -> tuple[int, int]:
        """``(row count, sum of event_time_ms %% TIME_DIGEST_MOD)`` over one
        bounded scan -- a single aggregate query, no row transfer. The
        snapshot refresh path compares it against the digest accumulated
        at spill time: a deletion balanced by a late-arriving insert keeps
        the COUNT but (outside sum collisions) not the time checksum, so
        an inexact append refresh is caught and rebuilt instead. The
        per-row modulus keeps the sum exact in any dialect's 64-bit
        integer SUM (no bigint overflow / float fallback).
        """
        from predictionio_tpu.data.snapshot import TIME_DIGEST_MOD

        mod_expr = self.c.TIME_MOD_EXPR.format(mod=TIME_DIGEST_MOD)
        sql = [
            f"SELECT COUNT(*), COALESCE(SUM({mod_expr}), 0)"
            " FROM events WHERE app_id=? AND channel_id=?"
        ]
        params: list = [app_id, self._ch(channel_id)]
        self._append_filters(
            sql,
            params,
            start_time=start_time,
            until_time=until_time,
            event_names=event_names,
            target_entity_type=target_entity_type,
        )
        row = self.c.query(self.c.sql(" ".join(sql)), tuple(params))[0]
        return int(row[0]), int(row[1])

    def iter_interaction_chunks(
        self,
        app_id: int,
        channel_id: int | None = None,
        event_names: list[str] | None = None,
        target_entity_type=...,
        start_time: _dt.datetime | None = None,
        until_time: _dt.datetime | None = None,
        rating_key: str = "rating",
        chunk_rows: int = 262_144,
    ):
        """``scan_interactions`` as a bounded-memory stream: yields the same
        five columns in chunks of at most ``chunk_rows`` rows, riding the
        dialect's streaming cursor (server-side for Postgres) instead of
        materializing the full result. Ordering is DETERMINISTIC across
        repeated scans and across processes (event_time_ms, event_id) --
        the sharded multi-host reader replays this stream on every process
        and must assign identical vocabulary ids and identical tie-breaks.
        """
        select = (
            "SELECT entity_id, target_entity_id, event, event_time,"
            f" {self.c.JSON_NUMBER_EXPR} FROM events"
        )
        sql = [select, "WHERE app_id=? AND channel_id=?"]
        # the JSON expr's placeholders appear FIRST in the statement
        params: list = [
            *self.c.json_number_params(rating_key),
            app_id,
            self._ch(channel_id),
        ]
        self._append_filters(
            sql,
            params,
            start_time=start_time,
            until_time=until_time,
            event_names=event_names,
            target_entity_type=target_entity_type,
        )
        sql.append("ORDER BY event_time_ms ASC, event_id ASC")
        cols: tuple[list, ...] = ([], [], [], [], [])
        for r in self.c.query_iter(self.c.sql(" ".join(sql)), tuple(params)):
            for acc, v in zip(cols, r):
                acc.append(v)
            if len(cols[0]) >= chunk_rows:
                yield cols
                cols = ([], [], [], [], [])
        if cols[0]:
            yield cols
