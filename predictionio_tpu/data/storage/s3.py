"""S3 model blob store.

Parity role of reference ``storage/s3/.../S3Models.scala`` (apache/
predictionio layout, unverified -- SURVEY.md section 2.2 #11): a
``Models``-only backend writing one object per engine instance.

Configuration:

    PIO_STORAGE_SOURCES_S3_TYPE=s3
    PIO_STORAGE_SOURCES_S3_BUCKET_NAME=my-bucket
    PIO_STORAGE_SOURCES_S3_BASE_PATH=models        (optional key prefix)
    PIO_STORAGE_SOURCES_S3_ENDPOINT=...            (optional, e.g. minio)
    PIO_STORAGE_SOURCES_S3_REGION=...              (optional)

Credentials come from the standard AWS chain (env/instance profile).
Driver: boto3 (optional dependency -- a clear error is raised when absent).
"""

from __future__ import annotations

from typing import Optional

from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import Model, StorageClientConfig


class StorageClient(base.BaseStorageClient):
    def __init__(self, config: StorageClientConfig):
        super().__init__(config)
        try:
            import boto3
        except ImportError as exc:
            raise RuntimeError(
                "the s3 storage backend requires boto3; install it or switch"
                " PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE to a localfs/sqlite"
                " source"
            ) from exc
        props = config.properties
        bucket = props.get("BUCKET_NAME")
        if not bucket:
            raise RuntimeError(
                "s3 storage source is missing BUCKET_NAME"
                " (PIO_STORAGE_SOURCES_<S>_BUCKET_NAME)"
            )
        client_kwargs = {}
        if props.get("ENDPOINT"):
            client_kwargs["endpoint_url"] = props["ENDPOINT"]
        if props.get("REGION"):
            client_kwargs["region_name"] = props["REGION"]
        self._s3 = boto3.client("s3", **client_kwargs)
        self._bucket = bucket
        self._prefix = props.get("BASE_PATH", "").strip("/")

    def get_dao(self, repo: str):
        if repo != "models":
            raise NotImplementedError(
                f"s3 backend only provides the 'models' repository, not {repo!r}"
            )
        return S3Models(self._s3, self._bucket, self._prefix)

    def close(self) -> None:
        pass


class S3Models(base.Models):
    def __init__(self, s3_client, bucket: str, prefix: str):
        self.s3 = s3_client
        self.bucket = bucket
        self.prefix = prefix

    def _key(self, model_id: str) -> str:
        name = base.safe_blob_name(model_id)
        return f"{self.prefix}/{name}" if self.prefix else name

    def insert(self, model: Model) -> None:
        self.s3.put_object(
            Bucket=self.bucket, Key=self._key(model.id), Body=model.models
        )

    def get(self, model_id: str) -> Optional[Model]:
        try:
            resp = self.s3.get_object(Bucket=self.bucket, Key=self._key(model_id))
        except Exception as exc:
            # boto3 surfaces missing keys as ClientError NoSuchKey; match on
            # the error code without importing botocore at module scope
            code = getattr(exc, "response", {}).get("Error", {}).get("Code", "")
            if code in ("NoSuchKey", "404"):
                return None
            raise
        return Model(id=model_id, models=resp["Body"].read())

    def delete(self, model_id: str) -> None:
        self.s3.delete_object(Bucket=self.bucket, Key=self._key(model_id))
