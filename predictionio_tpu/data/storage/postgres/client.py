"""PostgreSQL implementations of every DAO contract.

Parity role of the reference's scalikejdbc module ``storage/jdbc/.../
JDBC{Apps,AccessKeys,Channels,EngineInstances,EvaluationInstances,LEvents,
PEvents,Models}.scala`` (apache/predictionio layout, unverified -- SURVEY.md
section 2.2 #10): a full-stack backend (events + metadata + models) for
PostgreSQL, with DDL auto-create on first connect. The DAO logic is shared
with the sqlite backend via ``sql_common``; only the connection, paramstyle,
and dialect DDL live here.

Configuration (reference env-var contract, SURVEY.md section 5.6):

    PIO_STORAGE_SOURCES_PGSQL_TYPE=postgres   (or: jdbc)
    PIO_STORAGE_SOURCES_PGSQL_URL=jdbc:postgresql://host:5432/pio
    PIO_STORAGE_SOURCES_PGSQL_USERNAME=pio
    PIO_STORAGE_SOURCES_PGSQL_PASSWORD=...

``URL`` accepts both ``jdbc:postgresql://`` (reference form) and plain
``postgresql://`` URLs; HOST/PORT/DBNAME properties may be used instead.
Driver: psycopg2 (optional dependency -- a clear error is raised when it is
not installed; nothing else in the framework depends on it).
"""

from __future__ import annotations

import threading
import uuid
from typing import Iterator

from predictionio_tpu.data.storage import sql_common
from predictionio_tpu.data.storage.base import StorageClientConfig

_SCHEMA_STATEMENTS = [
    """CREATE TABLE IF NOT EXISTS apps (
      id BIGSERIAL PRIMARY KEY,
      name TEXT UNIQUE NOT NULL,
      description TEXT NOT NULL DEFAULT ''
    )""",
    """CREATE TABLE IF NOT EXISTS channels (
      id BIGSERIAL PRIMARY KEY,
      name TEXT NOT NULL,
      app_id BIGINT NOT NULL,
      UNIQUE(app_id, name)
    )""",
    """CREATE TABLE IF NOT EXISTS access_keys (
      key TEXT PRIMARY KEY,
      app_id BIGINT NOT NULL,
      events TEXT NOT NULL DEFAULT '[]'
    )""",
    """CREATE TABLE IF NOT EXISTS engine_instances (
      id TEXT PRIMARY KEY,
      status TEXT NOT NULL,
      start_time TEXT NOT NULL,
      end_time TEXT,
      engine_id TEXT NOT NULL,
      engine_version TEXT NOT NULL,
      engine_variant TEXT NOT NULL,
      engine_factory TEXT NOT NULL,
      batch TEXT NOT NULL DEFAULT '',
      env TEXT NOT NULL DEFAULT '{}',
      runtime_conf TEXT NOT NULL DEFAULT '{}',
      data_source_params TEXT NOT NULL DEFAULT '{}',
      preparator_params TEXT NOT NULL DEFAULT '{}',
      algorithms_params TEXT NOT NULL DEFAULT '[]',
      serving_params TEXT NOT NULL DEFAULT '{}'
    )""",
    """CREATE TABLE IF NOT EXISTS evaluation_instances (
      id TEXT PRIMARY KEY,
      status TEXT NOT NULL,
      start_time TEXT NOT NULL,
      end_time TEXT,
      evaluation_class TEXT NOT NULL,
      engine_params_generator_class TEXT NOT NULL,
      batch TEXT NOT NULL DEFAULT '',
      env TEXT NOT NULL DEFAULT '{}',
      evaluator_results TEXT NOT NULL DEFAULT '',
      evaluator_results_html TEXT NOT NULL DEFAULT '',
      evaluator_results_json TEXT NOT NULL DEFAULT ''
    )""",
    """CREATE TABLE IF NOT EXISTS models (
      id TEXT PRIMARY KEY,
      models BYTEA NOT NULL
    )""",
    """CREATE TABLE IF NOT EXISTS event_channels (
      app_id BIGINT NOT NULL,
      channel_id BIGINT NOT NULL,
      PRIMARY KEY (app_id, channel_id)
    )""",
    """CREATE TABLE IF NOT EXISTS events (
      event_id TEXT NOT NULL,
      app_id BIGINT NOT NULL,
      channel_id BIGINT NOT NULL,
      event TEXT NOT NULL,
      entity_type TEXT NOT NULL,
      entity_id TEXT NOT NULL,
      target_entity_type TEXT,
      target_entity_id TEXT,
      properties TEXT NOT NULL DEFAULT '{}',
      event_time TEXT NOT NULL,
      event_time_ms BIGINT NOT NULL,
      pr_id TEXT,
      creation_time TEXT NOT NULL,
      PRIMARY KEY (app_id, channel_id, event_id)
    )""",
    """CREATE INDEX IF NOT EXISTS idx_events_scan
      ON events (app_id, channel_id, entity_type, event_time_ms)""",
    """CREATE INDEX IF NOT EXISTS idx_events_name
      ON events (app_id, channel_id, event, event_time_ms)""",
]


def parse_connection_properties(props: dict[str, str]) -> dict:
    """URL/HOST/PORT/DBNAME/USERNAME/PASSWORD properties -> psycopg2 kwargs.

    Accepts the reference's ``jdbc:postgresql://...`` URL form verbatim,
    including JDBC-style query params (?user=..&password=..&sslmode=..).
    """
    return sql_common.parse_jdbc_url_properties(
        props,
        schemes=("postgresql", "postgres"),
        backend_name="postgres",
        default_port=5432,
        dbname_key="dbname",
        query_keys=("user", "password", "sslmode", "connect_timeout"),
    )


class StorageClient(sql_common.SQLStorageClient):
    """Thread-safe psycopg2 connection with DDL auto-create."""

    placeholder = "%s"
    INSERT_IGNORE_EVENT_CHANNELS = (
        "INSERT INTO event_channels (app_id, channel_id) VALUES (?, ?)"
        " ON CONFLICT DO NOTHING"
    )
    UPSERT_MODEL = (
        "INSERT INTO models (id, models) VALUES (?, ?)"
        " ON CONFLICT (id) DO UPDATE SET models = EXCLUDED.models"
    )
    INSERT_EVENTS_IGNORE_PREFIX = "INSERT INTO events"
    INSERT_EVENTS_IGNORE_SUFFIX = " ON CONFLICT (app_id, channel_id, event_id) DO NOTHING"
    # properties is TEXT holding JSON; -> / ->> want jsonb and a bare key.
    # jsonb_typeof gate keeps string/bool ratings NULL (from_events parity)
    JSON_NUMBER_EXPR = (
        "CASE WHEN jsonb_typeof(properties::jsonb -> ?) = 'number'"
        " THEN (properties::jsonb ->> ?) END"
    )
    # MOD(), not the % operator: psycopg2's client-side interpolation
    # would eat a bare % in statement text (same truncated semantics)
    TIME_MOD_EXPR = "MOD(event_time_ms, {mod})"

    @classmethod
    def json_number_params(cls, key: str) -> tuple:
        return (key, key)

    def __init__(self, config: StorageClientConfig):
        super().__init__(config)
        try:
            import psycopg2
        except ImportError as exc:
            raise RuntimeError(
                "the postgres storage backend requires psycopg2; install it or"
                " switch PIO_STORAGE_SOURCES_*_TYPE to 'sqlite'"
            ) from exc
        kwargs = parse_connection_properties(config.properties)
        self._connect_kwargs = kwargs
        self._conn = psycopg2.connect(**kwargs)
        self._lock = threading.RLock()
        # `with conn:` = one transaction (commit on exit, rollback on error),
        # so batch_insert keeps the sqlite backend's all-or-nothing semantics
        with self._lock, self._conn, self._conn.cursor() as cur:
            for stmt in _SCHEMA_STATEMENTS:
                cur.execute(stmt)

    def execute(self, sql: str, params: tuple = ()):
        with self._lock, self._conn, self._conn.cursor() as cur:
            cur.execute(sql, params)
            return sql_common.CursorResult(cur.rowcount)

    def executemany(self, sql: str, rows: list[tuple]):
        with self._lock, self._conn, self._conn.cursor() as cur:
            cur.executemany(sql, rows)
            return sql_common.CursorResult(cur.rowcount)

    def insert_returning_id(self, sql: str, params: tuple) -> int:
        with self._lock, self._conn, self._conn.cursor() as cur:
            cur.execute(sql + " RETURNING id", params)
            return cur.fetchone()[0]

    def query(self, sql: str, params: tuple = ()) -> list[tuple]:
        with self._lock, self._conn, self._conn.cursor() as cur:
            cur.execute(sql, params)
            return cur.fetchall()

    def query_iter(self, sql: str, params: tuple = ()) -> Iterator[tuple]:
        """Stream via a server-side (named) cursor on a dedicated connection,
        mirroring the sqlite streaming path: a multi-GB event scan (train
        reads, export, aggregate_properties) never materializes client-side
        and never holds the client-wide lock across consumer yields."""
        import psycopg2

        conn = psycopg2.connect(**self._connect_kwargs)
        try:
            with conn, conn.cursor(name=f"pio_scan_{id(self)}_{uuid.uuid4().hex[:8]}") as cur:
                cur.execute(sql, params)
                while True:
                    rows = cur.fetchmany(1024)
                    if not rows:
                        return
                    yield from rows
        finally:
            conn.close()

    def close(self) -> None:
        with self._lock:
            self._conn.close()


