"""PostgreSQL storage backend (reference JDBC-module parity)."""

from predictionio_tpu.data.storage.postgres.client import StorageClient

__all__ = ["StorageClient"]
