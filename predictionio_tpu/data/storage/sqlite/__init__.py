"""SQLite storage backend: full-stack (events + metadata + models).

Plays the parity role of the reference's JDBC backend
(``storage/jdbc/.../JDBC*.scala``, apache/predictionio layout, unverified --
SURVEY.md section 2.2 #10): a single relational source that can host all three
repositories, with DDL auto-create. SQLite is the zero-config dev default;
the same DAO contracts admit server-grade backends.
"""

from predictionio_tpu.data.storage.sqlite.client import StorageClient

__all__ = ["StorageClient"]
