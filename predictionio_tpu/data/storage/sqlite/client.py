"""SQLite implementations of every DAO contract."""

from __future__ import annotations

import datetime as _dt
import json
import secrets
import sqlite3
import threading
import uuid
from typing import Iterable, Iterator, Optional

from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EvaluationInstance,
    Model,
    StorageClientConfig,
)

_SCHEMA = """
CREATE TABLE IF NOT EXISTS apps (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  name TEXT UNIQUE NOT NULL,
  description TEXT NOT NULL DEFAULT ''
);
CREATE TABLE IF NOT EXISTS channels (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  name TEXT NOT NULL,
  app_id INTEGER NOT NULL,
  UNIQUE(app_id, name)
);
CREATE TABLE IF NOT EXISTS access_keys (
  key TEXT PRIMARY KEY,
  app_id INTEGER NOT NULL,
  events TEXT NOT NULL DEFAULT '[]'
);
CREATE TABLE IF NOT EXISTS engine_instances (
  id TEXT PRIMARY KEY,
  status TEXT NOT NULL,
  start_time TEXT NOT NULL,
  end_time TEXT,
  engine_id TEXT NOT NULL,
  engine_version TEXT NOT NULL,
  engine_variant TEXT NOT NULL,
  engine_factory TEXT NOT NULL,
  batch TEXT NOT NULL DEFAULT '',
  env TEXT NOT NULL DEFAULT '{}',
  runtime_conf TEXT NOT NULL DEFAULT '{}',
  data_source_params TEXT NOT NULL DEFAULT '{}',
  preparator_params TEXT NOT NULL DEFAULT '{}',
  algorithms_params TEXT NOT NULL DEFAULT '[]',
  serving_params TEXT NOT NULL DEFAULT '{}'
);
CREATE TABLE IF NOT EXISTS evaluation_instances (
  id TEXT PRIMARY KEY,
  status TEXT NOT NULL,
  start_time TEXT NOT NULL,
  end_time TEXT,
  evaluation_class TEXT NOT NULL,
  engine_params_generator_class TEXT NOT NULL,
  batch TEXT NOT NULL DEFAULT '',
  env TEXT NOT NULL DEFAULT '{}',
  evaluator_results TEXT NOT NULL DEFAULT '',
  evaluator_results_html TEXT NOT NULL DEFAULT '',
  evaluator_results_json TEXT NOT NULL DEFAULT ''
);
CREATE TABLE IF NOT EXISTS models (
  id TEXT PRIMARY KEY,
  models BLOB NOT NULL
);
CREATE TABLE IF NOT EXISTS event_channels (
  app_id INTEGER NOT NULL,
  channel_id INTEGER NOT NULL,
  PRIMARY KEY (app_id, channel_id)
);
CREATE TABLE IF NOT EXISTS events (
  event_id TEXT NOT NULL,
  app_id INTEGER NOT NULL,
  channel_id INTEGER NOT NULL,
  event TEXT NOT NULL,
  entity_type TEXT NOT NULL,
  entity_id TEXT NOT NULL,
  target_entity_type TEXT,
  target_entity_id TEXT,
  properties TEXT NOT NULL DEFAULT '{}',
  event_time TEXT NOT NULL,
  event_time_ms INTEGER NOT NULL,
  pr_id TEXT,
  creation_time TEXT NOT NULL,
  PRIMARY KEY (app_id, channel_id, event_id)
);
CREATE INDEX IF NOT EXISTS idx_events_scan
  ON events (app_id, channel_id, entity_type, event_time_ms);
CREATE INDEX IF NOT EXISTS idx_events_name
  ON events (app_id, channel_id, event, event_time_ms);
"""

#: channel_id column value for the default channel (reference uses None).
_DEFAULT_CHANNEL = 0


def _ts_to_str(ts: _dt.datetime | None) -> str | None:
    # normalize to UTC with fixed precision so text ORDER BY is chronological
    if ts is None:
        return None
    if ts.tzinfo is None:
        ts = ts.replace(tzinfo=_dt.timezone.utc)
    return ts.astimezone(_dt.timezone.utc).isoformat(timespec="microseconds")


def _ts_from_str(s: str | None) -> _dt.datetime | None:
    return _dt.datetime.fromisoformat(s) if s else None


def _ts_ms(ts: _dt.datetime) -> int:
    # same naive-means-UTC rule as Event.__post_init__, so stored values and
    # find() bounds agree on any host timezone
    if ts.tzinfo is None:
        ts = ts.replace(tzinfo=_dt.timezone.utc)
    return int(ts.timestamp() * 1000)


class StorageClient(base.BaseStorageClient):
    """Thread-safe sqlite connection; one file holds all repositories."""

    def __init__(self, config: StorageClientConfig):
        super().__init__(config)
        path = config.properties.get("PATH", ":memory:")
        self._path = path
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA synchronous=NORMAL")
        self._lock = threading.RLock()
        with self._lock, self._conn:
            self._conn.executescript(_SCHEMA)

    def get_dao(self, repo: str):
        return {
            "apps": SQLiteApps,
            "channels": SQLiteChannels,
            "access_keys": SQLiteAccessKeys,
            "engine_instances": SQLiteEngineInstances,
            "evaluation_instances": SQLiteEvaluationInstances,
            "models": SQLiteModels,
            "events": SQLiteLEvents,
        }[repo](self)

    def execute(self, sql: str, params: tuple = ()) -> sqlite3.Cursor:
        with self._lock, self._conn:
            return self._conn.execute(sql, params)

    def executemany(self, sql: str, rows: list[tuple]) -> sqlite3.Cursor:
        with self._lock, self._conn:
            return self._conn.executemany(sql, rows)

    def query(self, sql: str, params: tuple = ()) -> list[tuple]:
        with self._lock:
            return self._conn.execute(sql, params).fetchall()

    def query_iter(self, sql: str, params: tuple = ()):
        """Stream rows without blocking writers.

        Opens a dedicated read connection (WAL mode gives it a consistent
        snapshot independent of concurrent writes on the shared connection).
        An in-memory database is private to its connection, so there we fall
        back to a single locked fetchall.
        """
        if self._path == ":memory:":
            yield from self.query(sql, params)
            return
        conn = sqlite3.connect(self._path, check_same_thread=False)
        try:
            cursor = conn.execute(sql, params)
            while True:
                rows = cursor.fetchmany(1024)
                if not rows:
                    return
                yield from rows
        finally:
            conn.close()

    def close(self) -> None:
        with self._lock:
            self._conn.close()


class SQLiteApps(base.Apps):
    def __init__(self, client: StorageClient):
        self.c = client

    def insert(self, app: App) -> int:
        cur = self.c.execute(
            "INSERT INTO apps (name, description) VALUES (?, ?)",
            (app.name, app.description),
        )
        app.id = cur.lastrowid
        return app.id

    def get(self, app_id: int) -> Optional[App]:
        rows = self.c.query("SELECT id, name, description FROM apps WHERE id=?", (app_id,))
        return App(id=rows[0][0], name=rows[0][1], description=rows[0][2]) if rows else None

    def get_by_name(self, name: str) -> Optional[App]:
        rows = self.c.query("SELECT id, name, description FROM apps WHERE name=?", (name,))
        return App(id=rows[0][0], name=rows[0][1], description=rows[0][2]) if rows else None

    def get_all(self) -> list[App]:
        rows = self.c.query("SELECT id, name, description FROM apps ORDER BY id")
        return [App(id=r[0], name=r[1], description=r[2]) for r in rows]

    def update(self, app: App) -> None:
        self.c.execute(
            "UPDATE apps SET name=?, description=? WHERE id=?",
            (app.name, app.description, app.id),
        )

    def delete(self, app_id: int) -> None:
        self.c.execute("DELETE FROM apps WHERE id=?", (app_id,))


class SQLiteChannels(base.Channels):
    def __init__(self, client: StorageClient):
        self.c = client

    def insert(self, channel: Channel) -> int:
        cur = self.c.execute(
            "INSERT INTO channels (name, app_id) VALUES (?, ?)",
            (channel.name, channel.app_id),
        )
        channel.id = cur.lastrowid
        return channel.id

    def get(self, channel_id: int) -> Optional[Channel]:
        rows = self.c.query("SELECT id, name, app_id FROM channels WHERE id=?", (channel_id,))
        return Channel(id=rows[0][0], name=rows[0][1], app_id=rows[0][2]) if rows else None

    def get_by_app(self, app_id: int) -> list[Channel]:
        rows = self.c.query(
            "SELECT id, name, app_id FROM channels WHERE app_id=? ORDER BY id", (app_id,)
        )
        return [Channel(id=r[0], name=r[1], app_id=r[2]) for r in rows]

    def delete(self, channel_id: int) -> None:
        self.c.execute("DELETE FROM channels WHERE id=?", (channel_id,))


class SQLiteAccessKeys(base.AccessKeys):
    def __init__(self, client: StorageClient):
        self.c = client

    def insert(self, access_key: AccessKey) -> str:
        key = access_key.key or secrets.token_urlsafe(48)
        self.c.execute(
            "INSERT INTO access_keys (key, app_id, events) VALUES (?, ?, ?)",
            (key, access_key.app_id, json.dumps(access_key.events)),
        )
        access_key.key = key
        return key

    def get(self, key: str) -> Optional[AccessKey]:
        rows = self.c.query(
            "SELECT key, app_id, events FROM access_keys WHERE key=?", (key,)
        )
        if not rows:
            return None
        return AccessKey(key=rows[0][0], app_id=rows[0][1], events=json.loads(rows[0][2]))

    def get_all(self) -> list[AccessKey]:
        rows = self.c.query("SELECT key, app_id, events FROM access_keys")
        return [AccessKey(key=r[0], app_id=r[1], events=json.loads(r[2])) for r in rows]

    def get_by_app_id(self, app_id: int) -> list[AccessKey]:
        rows = self.c.query(
            "SELECT key, app_id, events FROM access_keys WHERE app_id=?", (app_id,)
        )
        return [AccessKey(key=r[0], app_id=r[1], events=json.loads(r[2])) for r in rows]

    def update(self, access_key: AccessKey) -> None:
        self.c.execute(
            "UPDATE access_keys SET app_id=?, events=? WHERE key=?",
            (access_key.app_id, json.dumps(access_key.events), access_key.key),
        )

    def delete(self, key: str) -> None:
        self.c.execute("DELETE FROM access_keys WHERE key=?", (key,))


class SQLiteEngineInstances(base.EngineInstances):
    _COLS = (
        "id, status, start_time, end_time, engine_id, engine_version, engine_variant,"
        " engine_factory, batch, env, runtime_conf, data_source_params,"
        " preparator_params, algorithms_params, serving_params"
    )

    def __init__(self, client: StorageClient):
        self.c = client

    def _row_to_instance(self, r: tuple) -> EngineInstance:
        return EngineInstance(
            id=r[0],
            status=r[1],
            start_time=_ts_from_str(r[2]),
            end_time=_ts_from_str(r[3]),
            engine_id=r[4],
            engine_version=r[5],
            engine_variant=r[6],
            engine_factory=r[7],
            batch=r[8],
            env=json.loads(r[9]),
            runtime_conf=json.loads(r[10]),
            data_source_params=r[11],
            preparator_params=r[12],
            algorithms_params=r[13],
            serving_params=r[14],
        )

    def insert(self, instance: EngineInstance) -> str:
        instance.id = instance.id or uuid.uuid4().hex
        self.c.execute(
            f"INSERT INTO engine_instances ({self._COLS}) VALUES "
            "(?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)",
            (
                instance.id,
                instance.status,
                _ts_to_str(instance.start_time),
                _ts_to_str(instance.end_time),
                instance.engine_id,
                instance.engine_version,
                instance.engine_variant,
                instance.engine_factory,
                instance.batch,
                json.dumps(instance.env),
                json.dumps(instance.runtime_conf),
                instance.data_source_params,
                instance.preparator_params,
                instance.algorithms_params,
                instance.serving_params,
            ),
        )
        return instance.id

    def get(self, instance_id: str) -> Optional[EngineInstance]:
        rows = self.c.query(
            f"SELECT {self._COLS} FROM engine_instances WHERE id=?", (instance_id,)
        )
        return self._row_to_instance(rows[0]) if rows else None

    def get_all(self) -> list[EngineInstance]:
        rows = self.c.query(
            f"SELECT {self._COLS} FROM engine_instances ORDER BY start_time DESC"
        )
        return [self._row_to_instance(r) for r in rows]

    def get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> list[EngineInstance]:
        rows = self.c.query(
            f"SELECT {self._COLS} FROM engine_instances WHERE status=? AND engine_id=?"
            " AND engine_version=? AND engine_variant=? ORDER BY start_time DESC",
            (base.STATUS_COMPLETED, engine_id, engine_version, engine_variant),
        )
        return [self._row_to_instance(r) for r in rows]

    def get_latest_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> Optional[EngineInstance]:
        completed = self.get_completed(engine_id, engine_version, engine_variant)
        return completed[0] if completed else None

    def update(self, instance: EngineInstance) -> None:
        self.c.execute(
            "UPDATE engine_instances SET status=?, start_time=?, end_time=?,"
            " engine_id=?, engine_version=?, engine_variant=?, engine_factory=?,"
            " batch=?, env=?, runtime_conf=?, data_source_params=?,"
            " preparator_params=?, algorithms_params=?, serving_params=? WHERE id=?",
            (
                instance.status,
                _ts_to_str(instance.start_time),
                _ts_to_str(instance.end_time),
                instance.engine_id,
                instance.engine_version,
                instance.engine_variant,
                instance.engine_factory,
                instance.batch,
                json.dumps(instance.env),
                json.dumps(instance.runtime_conf),
                instance.data_source_params,
                instance.preparator_params,
                instance.algorithms_params,
                instance.serving_params,
                instance.id,
            ),
        )

    def delete(self, instance_id: str) -> None:
        self.c.execute("DELETE FROM engine_instances WHERE id=?", (instance_id,))


class SQLiteEvaluationInstances(base.EvaluationInstances):
    _COLS = (
        "id, status, start_time, end_time, evaluation_class,"
        " engine_params_generator_class, batch, env, evaluator_results,"
        " evaluator_results_html, evaluator_results_json"
    )

    def __init__(self, client: StorageClient):
        self.c = client

    def _row_to_instance(self, r: tuple) -> EvaluationInstance:
        return EvaluationInstance(
            id=r[0],
            status=r[1],
            start_time=_ts_from_str(r[2]),
            end_time=_ts_from_str(r[3]),
            evaluation_class=r[4],
            engine_params_generator_class=r[5],
            batch=r[6],
            env=json.loads(r[7]),
            evaluator_results=r[8],
            evaluator_results_html=r[9],
            evaluator_results_json=r[10],
        )

    def insert(self, instance: EvaluationInstance) -> str:
        instance.id = instance.id or uuid.uuid4().hex
        self.c.execute(
            f"INSERT INTO evaluation_instances ({self._COLS}) VALUES"
            " (?,?,?,?,?,?,?,?,?,?,?)",
            (
                instance.id,
                instance.status,
                _ts_to_str(instance.start_time),
                _ts_to_str(instance.end_time),
                instance.evaluation_class,
                instance.engine_params_generator_class,
                instance.batch,
                json.dumps(instance.env),
                instance.evaluator_results,
                instance.evaluator_results_html,
                instance.evaluator_results_json,
            ),
        )
        return instance.id

    def get(self, instance_id: str) -> Optional[EvaluationInstance]:
        rows = self.c.query(
            f"SELECT {self._COLS} FROM evaluation_instances WHERE id=?", (instance_id,)
        )
        return self._row_to_instance(rows[0]) if rows else None

    def get_all(self) -> list[EvaluationInstance]:
        rows = self.c.query(
            f"SELECT {self._COLS} FROM evaluation_instances ORDER BY start_time DESC"
        )
        return [self._row_to_instance(r) for r in rows]

    def get_completed(self) -> list[EvaluationInstance]:
        rows = self.c.query(
            f"SELECT {self._COLS} FROM evaluation_instances WHERE status=?"
            " ORDER BY start_time DESC",
            (base.STATUS_COMPLETED,),
        )
        return [self._row_to_instance(r) for r in rows]

    def update(self, instance: EvaluationInstance) -> None:
        self.c.execute(
            "UPDATE evaluation_instances SET status=?, start_time=?, end_time=?,"
            " evaluation_class=?, engine_params_generator_class=?, batch=?, env=?,"
            " evaluator_results=?, evaluator_results_html=?, evaluator_results_json=?"
            " WHERE id=?",
            (
                instance.status,
                _ts_to_str(instance.start_time),
                _ts_to_str(instance.end_time),
                instance.evaluation_class,
                instance.engine_params_generator_class,
                instance.batch,
                json.dumps(instance.env),
                instance.evaluator_results,
                instance.evaluator_results_html,
                instance.evaluator_results_json,
                instance.id,
            ),
        )

    def delete(self, instance_id: str) -> None:
        self.c.execute("DELETE FROM evaluation_instances WHERE id=?", (instance_id,))


class SQLiteModels(base.Models):
    def __init__(self, client: StorageClient):
        self.c = client

    def insert(self, model: Model) -> None:
        self.c.execute(
            "INSERT OR REPLACE INTO models (id, models) VALUES (?, ?)",
            (model.id, model.models),
        )

    def get(self, model_id: str) -> Optional[Model]:
        rows = self.c.query("SELECT id, models FROM models WHERE id=?", (model_id,))
        return Model(id=rows[0][0], models=rows[0][1]) if rows else None

    def delete(self, model_id: str) -> None:
        self.c.execute("DELETE FROM models WHERE id=?", (model_id,))


class SQLiteLEvents(base.LEvents):
    def __init__(self, client: StorageClient):
        self.c = client

    @staticmethod
    def _ch(channel_id: int | None) -> int:
        return _DEFAULT_CHANNEL if channel_id is None else channel_id

    def init_channel(self, app_id: int, channel_id: int | None = None) -> bool:
        self.c.execute(
            "INSERT OR IGNORE INTO event_channels (app_id, channel_id) VALUES (?, ?)",
            (app_id, self._ch(channel_id)),
        )
        return True

    def remove_channel(self, app_id: int, channel_id: int | None = None) -> bool:
        ch = self._ch(channel_id)
        self.c.execute(
            "DELETE FROM events WHERE app_id=? AND channel_id=?", (app_id, ch)
        )
        self.c.execute(
            "DELETE FROM event_channels WHERE app_id=? AND channel_id=?", (app_id, ch)
        )
        return True

    def insert(self, event: Event, app_id: int, channel_id: int | None = None) -> str:
        return self.batch_insert([event], app_id, channel_id)[0]

    def batch_insert(
        self, events: Iterable[Event], app_id: int, channel_id: int | None = None
    ) -> list[str]:
        ch = self._ch(channel_id)
        rows, ids = [], []
        for ev in events:
            ev = ev if ev.event_id else ev.with_id()
            ids.append(ev.event_id)
            rows.append(
                (
                    ev.event_id,
                    app_id,
                    ch,
                    ev.event,
                    ev.entity_type,
                    ev.entity_id,
                    ev.target_entity_type,
                    ev.target_entity_id,
                    json.dumps(ev.properties.to_dict()),
                    ev.event_time.isoformat(),
                    _ts_ms(ev.event_time),
                    ev.pr_id,
                    ev.creation_time.isoformat(),
                )
            )
        # plain INSERT: the event log is append-only, a duplicate event_id is
        # a caller bug and must surface as an IntegrityError, not overwrite
        self.c.executemany(
            "INSERT INTO events (event_id, app_id, channel_id, event,"
            " entity_type, entity_id, target_entity_type, target_entity_id,"
            " properties, event_time, event_time_ms, pr_id, creation_time)"
            " VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?)",
            rows,
        )
        return ids

    @staticmethod
    def _row_to_event(r: tuple) -> Event:
        return Event(
            event_id=r[0],
            event=r[1],
            entity_type=r[2],
            entity_id=r[3],
            target_entity_type=r[4],
            target_entity_id=r[5],
            properties=DataMap(json.loads(r[6])),
            event_time=_dt.datetime.fromisoformat(r[7]),
            pr_id=r[8],
            creation_time=_dt.datetime.fromisoformat(r[9]),
        )

    _EVENT_COLS = (
        "event_id, event, entity_type, entity_id, target_entity_type,"
        " target_entity_id, properties, event_time, pr_id, creation_time"
    )

    def get(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> Optional[Event]:
        rows = self.c.query(
            f"SELECT {self._EVENT_COLS} FROM events"
            " WHERE app_id=? AND channel_id=? AND event_id=?",
            (app_id, self._ch(channel_id), event_id),
        )
        return self._row_to_event(rows[0]) if rows else None

    def delete(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> bool:
        cur = self.c.execute(
            "DELETE FROM events WHERE app_id=? AND channel_id=? AND event_id=?",
            (app_id, self._ch(channel_id), event_id),
        )
        return cur.rowcount > 0

    def find(
        self,
        app_id: int,
        channel_id: int | None = None,
        start_time: _dt.datetime | None = None,
        until_time: _dt.datetime | None = None,
        entity_type: str | None = None,
        entity_id: str | None = None,
        event_names: list[str] | None = None,
        target_entity_type=...,
        target_entity_id=...,
        limit: int | None = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        sql = [
            f"SELECT {self._EVENT_COLS} FROM events WHERE app_id=? AND channel_id=?"
        ]
        params: list = [app_id, self._ch(channel_id)]
        if start_time is not None:
            sql.append("AND event_time_ms >= ?")
            params.append(_ts_ms(start_time))
        if until_time is not None:
            sql.append("AND event_time_ms < ?")
            params.append(_ts_ms(until_time))
        if entity_type is not None:
            sql.append("AND entity_type = ?")
            params.append(entity_type)
        if entity_id is not None:
            sql.append("AND entity_id = ?")
            params.append(entity_id)
        if event_names:
            sql.append(f"AND event IN ({','.join('?' * len(event_names))})")
            params.extend(event_names)
        if target_entity_type is not ...:
            if target_entity_type is None:
                sql.append("AND target_entity_type IS NULL")
            else:
                sql.append("AND target_entity_type = ?")
                params.append(target_entity_type)
        if target_entity_id is not ...:
            if target_entity_id is None:
                sql.append("AND target_entity_id IS NULL")
            else:
                sql.append("AND target_entity_id = ?")
                params.append(target_entity_id)
        sql.append(f"ORDER BY event_time_ms {'DESC' if reversed else 'ASC'}")
        if limit is not None and limit >= 0:
            sql.append("LIMIT ?")
            params.append(limit)
        for r in self.c.query_iter(" ".join(sql), tuple(params)):
            yield self._row_to_event(r)
