"""SQLite storage backend: the zero-config dev default.

Parity role of the reference's JDBC quickstart path (SURVEY.md section 2.2
#10); the DAO logic itself lives in ``sql_common`` and is shared with the
postgres backend.
"""

from __future__ import annotations

import sqlite3
import threading

from predictionio_tpu.data.storage import sql_common
from predictionio_tpu.data.storage.base import StorageClientConfig

_SCHEMA = """
CREATE TABLE IF NOT EXISTS apps (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  name TEXT UNIQUE NOT NULL,
  description TEXT NOT NULL DEFAULT ''
);
CREATE TABLE IF NOT EXISTS channels (
  id INTEGER PRIMARY KEY AUTOINCREMENT,
  name TEXT NOT NULL,
  app_id INTEGER NOT NULL,
  UNIQUE(app_id, name)
);
CREATE TABLE IF NOT EXISTS access_keys (
  key TEXT PRIMARY KEY,
  app_id INTEGER NOT NULL,
  events TEXT NOT NULL DEFAULT '[]'
);
CREATE TABLE IF NOT EXISTS engine_instances (
  id TEXT PRIMARY KEY,
  status TEXT NOT NULL,
  start_time TEXT NOT NULL,
  end_time TEXT,
  engine_id TEXT NOT NULL,
  engine_version TEXT NOT NULL,
  engine_variant TEXT NOT NULL,
  engine_factory TEXT NOT NULL,
  batch TEXT NOT NULL DEFAULT '',
  env TEXT NOT NULL DEFAULT '{}',
  runtime_conf TEXT NOT NULL DEFAULT '{}',
  data_source_params TEXT NOT NULL DEFAULT '{}',
  preparator_params TEXT NOT NULL DEFAULT '{}',
  algorithms_params TEXT NOT NULL DEFAULT '[]',
  serving_params TEXT NOT NULL DEFAULT '{}'
);
CREATE TABLE IF NOT EXISTS evaluation_instances (
  id TEXT PRIMARY KEY,
  status TEXT NOT NULL,
  start_time TEXT NOT NULL,
  end_time TEXT,
  evaluation_class TEXT NOT NULL,
  engine_params_generator_class TEXT NOT NULL,
  batch TEXT NOT NULL DEFAULT '',
  env TEXT NOT NULL DEFAULT '{}',
  evaluator_results TEXT NOT NULL DEFAULT '',
  evaluator_results_html TEXT NOT NULL DEFAULT '',
  evaluator_results_json TEXT NOT NULL DEFAULT ''
);
CREATE TABLE IF NOT EXISTS models (
  id TEXT PRIMARY KEY,
  models BLOB NOT NULL
);
CREATE TABLE IF NOT EXISTS event_channels (
  app_id INTEGER NOT NULL,
  channel_id INTEGER NOT NULL,
  PRIMARY KEY (app_id, channel_id)
);
CREATE TABLE IF NOT EXISTS events (
  event_id TEXT NOT NULL,
  app_id INTEGER NOT NULL,
  channel_id INTEGER NOT NULL,
  event TEXT NOT NULL,
  entity_type TEXT NOT NULL,
  entity_id TEXT NOT NULL,
  target_entity_type TEXT,
  target_entity_id TEXT,
  properties TEXT NOT NULL DEFAULT '{}',
  event_time TEXT NOT NULL,
  event_time_ms INTEGER NOT NULL,
  pr_id TEXT,
  creation_time TEXT NOT NULL,
  PRIMARY KEY (app_id, channel_id, event_id)
);
CREATE INDEX IF NOT EXISTS idx_events_scan
  ON events (app_id, channel_id, entity_type, event_time_ms);
CREATE INDEX IF NOT EXISTS idx_events_name
  ON events (app_id, channel_id, event, event_time_ms);
"""


class StorageClient(sql_common.SQLStorageClient):
    """Thread-safe sqlite connection; one file holds all repositories."""

    def __init__(self, config: StorageClientConfig):
        super().__init__(config)
        path = config.properties.get("PATH", ":memory:")
        self._path = path
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        # NORMAL (default) never fsyncs on commit in WAL-journal mode --
        # fast, but an OS crash can lose recent commits. FULL fsyncs every
        # commit: the durable per-request baseline the ingestion A/B
        # (ingest_bench) measures group commit against.
        sync_mode = config.properties.get("SYNCHRONOUS", "NORMAL").upper()
        if sync_mode not in ("OFF", "NORMAL", "FULL", "EXTRA"):
            raise ValueError(
                f"SYNCHRONOUS must be OFF|NORMAL|FULL|EXTRA, got {sync_mode!r}"
            )
        self._conn.execute(f"PRAGMA synchronous={sync_mode}")
        self._lock = threading.RLock()
        with self._lock, self._conn:
            self._conn.executescript(_SCHEMA)

    def execute(self, sql: str, params: tuple = ()) -> sqlite3.Cursor:
        with self._lock, self._conn:
            return self._conn.execute(sql, params)

    def executemany(self, sql: str, rows: list[tuple]) -> sqlite3.Cursor:
        with self._lock, self._conn:
            return self._conn.executemany(sql, rows)

    def insert_returning_id(self, sql: str, params: tuple) -> int:
        return self.execute(sql, params).lastrowid

    def query(self, sql: str, params: tuple = ()) -> list[tuple]:
        with self._lock:
            return self._conn.execute(sql, params).fetchall()

    def query_iter(self, sql: str, params: tuple = ()):
        """Stream rows without blocking writers.

        Opens a dedicated read connection (WAL mode gives it a consistent
        snapshot independent of concurrent writes on the shared connection).
        An in-memory database is private to its connection, so there we fall
        back to a single locked fetchall.
        """
        if self._path == ":memory:":
            yield from self.query(sql, params)
            return
        conn = sqlite3.connect(self._path, check_same_thread=False)
        try:
            cursor = conn.execute(sql, params)
            while True:
                rows = cursor.fetchmany(1024)
                if not rows:
                    return
                yield from rows
        finally:
            conn.close()

    def close(self) -> None:
        with self._lock:
            self._conn.close()
