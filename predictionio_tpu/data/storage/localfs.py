"""Local-filesystem model blob store.

Parity role of reference ``storage/localfs/.../LocalFSModels.scala``
(apache/predictionio layout, unverified -- SURVEY.md section 2.2 #11): a
``Models``-only backend writing one blob file per engine instance.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Optional

from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import Model, StorageClientConfig


class StorageClient(base.BaseStorageClient):
    def __init__(self, config: StorageClientConfig):
        super().__init__(config)
        self.base_path = Path(
            config.properties.get("PATH", os.path.expanduser("~/.pio_store/models"))
        )
        self.base_path.mkdir(parents=True, exist_ok=True)

    def get_dao(self, repo: str):
        if repo != "models":
            raise NotImplementedError(
                f"localfs backend only provides the 'models' repository, not {repo!r}"
            )
        return LocalFSModels(self.base_path)


class LocalFSModels(base.Models):
    def __init__(self, base_path: Path):
        self.base_path = base_path

    def _path(self, model_id: str) -> Path:
        return self.base_path / base.safe_blob_name(model_id)

    def insert(self, model: Model) -> None:
        tmp = self._path(model.id).with_suffix(".tmp")
        tmp.write_bytes(model.models)
        tmp.replace(self._path(model.id))

    def get(self, model_id: str) -> Optional[Model]:
        p = self._path(model_id)
        if not p.exists():
            return None
        return Model(id=model_id, models=p.read_bytes())

    def delete(self, model_id: str) -> None:
        p = self._path(model_id)
        if p.exists():
            p.unlink()
