"""In-memory storage backend (test/dev parity role of reference LocalFS+H2).

Reuses the sqlite implementation over an in-memory database so behavior is
identical to the persistent dev backend.
"""

from __future__ import annotations

from predictionio_tpu.data.storage.base import StorageClientConfig
from predictionio_tpu.data.storage.sqlite.client import StorageClient as _SQLiteClient


class StorageClient(_SQLiteClient):
    def __init__(self, config: StorageClientConfig):
        config.properties = dict(config.properties)
        config.properties["PATH"] = ":memory:"
        super().__init__(config)
