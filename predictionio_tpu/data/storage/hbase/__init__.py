"""HBase event-store backend (TYPE=hbase, events only)."""

from predictionio_tpu.data.storage.hbase.client import StorageClient

__all__ = ["StorageClient"]
