"""HBase event-store backend (events only, like the reference module).

Parity role of the reference's event store of record ``storage/hbase/.../
{StorageClient,HBLEvents,HBEventsUtil}.scala`` (apache/predictionio layout,
unverified -- SURVEY.md section 2.2 #8): one table per app/channel
(reference ``pio_event:events_<appId>[_<channelId>]``), rowkeys encoding a
shard prefix + event time so time-range scans are prefix scans. Metadata
and models belong in another backend (the reference deployed HBase for
EVENTDATA with ES/JDBC for METADATA), mirroring how ``localfs`` is a
models-only backend here.

Configuration (reference env-var contract, SURVEY.md section 5.6):

    PIO_STORAGE_SOURCES_HBASE_TYPE=hbase
    PIO_STORAGE_SOURCES_HBASE_HOSTS=localhost    (REST gateway host)
    PIO_STORAGE_SOURCES_HBASE_PORTS=8080
    PIO_STORAGE_SOURCES_HBASE_NAMESPACE=pio_event
    PIO_STORAGE_SOURCES_HBASE_TRANSPORT=fake     (in-memory; CI only)

Row key design (TPU-first simplification of reference HBEventsUtil):
``SSTTTTTTTTTTTTTUUUUUUUUUUUUUUUU`` = 2-digit shard (hash of entity for
write distribution across regions) + 13-digit zero-padded event_time_ms +
16-hex uuid suffix. Within one shard, key order IS time order, so a
time-range find() is N_SHARDS prefix scans heap-merged by (time, key).
Event ids ARE row keys (reference HBase semantics: ids encode the row
key; preset ids on import are re-assigned).
"""

from __future__ import annotations

import datetime as _dt
import heapq
import json
from typing import Iterable, Iterator, Optional

from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import StorageClientConfig
from predictionio_tpu.data.storage.hbase.transport import (
    FakeTransport,
    HttpTransport,
    new_suffix,
)
from predictionio_tpu.data.storage.sql_common import ts_ms

N_SHARDS = 8
_FAMILY = "e"


class StorageClient(base.BaseStorageClient):
    def __init__(self, config: StorageClientConfig, transport=None):
        super().__init__(config)
        props = config.properties
        self.namespace = props.get("NAMESPACE", "pio_event")
        if transport is not None:
            self.transport = transport
        elif props.get("TRANSPORT", "").lower() == "fake":
            self.transport = FakeTransport()
        else:
            host = (props.get("HOSTS", "localhost")).split(",")[0]
            port = (props.get("PORTS", "8080")).split(",")[0]
            scheme = (props.get("SCHEMES", "http")).split(",")[0]
            self.transport = HttpTransport(f"{scheme}://{host}:{port}")

    def get_dao(self, repo: str):
        if repo != "events":
            raise NotImplementedError(
                "the hbase backend stores events only (reference parity:"
                " EVENTDATA on HBase, METADATA/MODELDATA on elasticsearch or"
                f" jdbc); requested repo {repo!r}"
            )
        return HBLEvents(self)


def shard_of(entity_type: str, entity_id: str) -> int:
    from predictionio_tpu.utils.stablehash import stable_bucket

    # same crc32-of-utf8 bytes as the old inline modulus, so existing
    # rowkeys keep their shard prefix
    return stable_bucket(f"{entity_type}\x00{entity_id}", N_SHARDS)


def make_rowkey(event: Event, suffix: str | None = None) -> str:
    shard = shard_of(event.entity_type, event.entity_id)
    return f"{shard:02d}{ts_ms(event.event_time):013d}{suffix or new_suffix()}"


class HBLEvents(base.LEvents):
    def __init__(self, client: StorageClient):
        self.c = client

    def table(self, app_id: int, channel_id: int | None) -> str:
        suffix = f"_{channel_id}" if channel_id else ""
        return f"{self.c.namespace}:events_{app_id}{suffix}"

    def init_channel(self, app_id: int, channel_id: int | None = None) -> bool:
        self.c.transport.create_table(self.table(app_id, channel_id), [_FAMILY])
        return True

    def remove_channel(self, app_id: int, channel_id: int | None = None) -> bool:
        self.c.transport.delete_table(self.table(app_id, channel_id))
        return True

    @staticmethod
    def _to_cells(ev: Event) -> dict[str, bytes]:
        doc = {
            "event": ev.event,
            "entity_type": ev.entity_type,
            "entity_id": ev.entity_id,
            "target_entity_type": ev.target_entity_type,
            "target_entity_id": ev.target_entity_id,
            "properties": ev.properties.to_dict(),
            "event_time": ev.event_time.isoformat(),
            "pr_id": ev.pr_id,
            "creation_time": ev.creation_time.isoformat(),
        }
        # one JSON cell + a couple of raw filter columns: the reference
        # used one column per field; a single document cell round-trips
        # None-vs-absent cleanly through the gateway's base64 layer
        return {
            f"{_FAMILY}:d": json.dumps(doc).encode(),
            f"{_FAMILY}:etype": ev.entity_type.encode(),
            f"{_FAMILY}:name": ev.event.encode(),
        }

    @staticmethod
    def _to_event(rowkey: str, cells: dict[str, bytes]) -> Event:
        doc = json.loads(cells[f"{_FAMILY}:d"])
        return Event(
            event_id=rowkey,
            event=doc["event"],
            entity_type=doc["entity_type"],
            entity_id=doc["entity_id"],
            target_entity_type=doc.get("target_entity_type"),
            target_entity_id=doc.get("target_entity_id"),
            properties=DataMap(doc["properties"]),
            event_time=_dt.datetime.fromisoformat(doc["event_time"]),
            pr_id=doc.get("pr_id"),
            creation_time=_dt.datetime.fromisoformat(doc["creation_time"]),
        )

    def insert(self, event: Event, app_id: int, channel_id: int | None = None) -> str:
        return self.batch_insert([event], app_id, channel_id)[0]

    def batch_insert(
        self, events: Iterable[Event], app_id: int, channel_id: int | None = None
    ) -> list[str]:
        rows, ids = [], []
        for ev in events:
            rowkey = make_rowkey(ev)  # ids ARE row keys (reference semantics)
            ids.append(rowkey)
            rows.append((rowkey, self._to_cells(ev)))
        self.c.transport.put_rows(self.table(app_id, channel_id), rows)
        return ids

    def get(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> Optional[Event]:
        cells = self.c.transport.get_row(self.table(app_id, channel_id), event_id)
        return self._to_event(event_id, cells) if cells else None

    def delete(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> bool:
        return self.c.transport.delete_row(self.table(app_id, channel_id), event_id)

    def find(
        self,
        app_id: int,
        channel_id: int | None = None,
        start_time: _dt.datetime | None = None,
        until_time: _dt.datetime | None = None,
        entity_type: str | None = None,
        entity_id: str | None = None,
        event_names: list[str] | None = None,
        target_entity_type=...,
        target_entity_id=...,
        limit: int | None = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        table = self.table(app_id, channel_id)
        start_ms = ts_ms(start_time) if start_time is not None else 0

        # one prefix scan per shard; entity filters narrow to ONE shard
        # (the rowkey's shard is a pure function of the entity)
        if entity_type is not None and entity_id is not None:
            shards = [shard_of(entity_type, entity_id)]
        else:
            shards = list(range(N_SHARDS))

        def shard_stream(shard: int):
            start_row = f"{shard:02d}{start_ms:013d}"
            if until_time is not None:
                # exclusive end row: keys at exactly until_ms carry a suffix
                # and sort after this, so untilTime stays exclusive
                end_row = f"{shard:02d}{ts_ms(until_time):013d}"
            else:
                # unbounded: the next shard's prefix. A formatted
                # _MAX_TIME_MS+1 here is 14 digits, which sorts BEFORE the
                # 13-digit zero-padded times and made unbounded scans empty
                end_row = f"{shard + 1:02d}"
            for rowkey, cells in self.c.transport.scan(
                table, start_row=start_row, end_row=end_row
            ):
                yield rowkey[2:], rowkey, cells  # merge key: time+suffix

        def matches(ev: Event) -> bool:
            if entity_type is not None and ev.entity_type != entity_type:
                return False
            if entity_id is not None and ev.entity_id != entity_id:
                return False
            if event_names and ev.event not in event_names:
                return False
            if target_entity_type is not ... and ev.target_entity_type != target_entity_type:
                return False
            if target_entity_id is not ... and ev.target_entity_id != target_entity_id:
                return False
            return True

        merged = heapq.merge(*(shard_stream(s) for s in shards))
        if reversed:
            # HBase scanners are forward-only over the REST gateway; a
            # reversed find (the event server's default listing) is served
            # by materializing matches then walking backward. Bounded
            # queries (limit) dominate this path in practice.
            matched = [
                ev
                for _, rowkey, cells in merged
                if matches(ev := self._to_event(rowkey, cells))
            ]
            matched.reverse()
            yield from matched[: limit if limit is not None and limit >= 0 else None]
            return
        emitted = 0
        for _, rowkey, cells in merged:
            ev = self._to_event(rowkey, cells)
            if not matches(ev):
                continue
            yield ev
            emitted += 1
            if limit is not None and 0 <= limit <= emitted:
                return
