"""HBase REST (Stargate) transport: real HTTP + an in-memory fake.

The backend speaks the HBase REST gateway's JSON protocol (cell values
base64-encoded) -- parity role of the reference's HBase client module
``storage/hbase/.../{StorageClient,HBLEvents,HBEventsUtil}.scala``
(apache/predictionio layout, unverified, SURVEY.md section 2.2 #8), which
used the Java HBase RPC client; REST is the gateway every HBase ships for
non-JVM clients.

Endpoints used: table schema PUT/DELETE, row PUT (multi-row), row GET,
row DELETE, scanner PUT/GET/DELETE with startRow/endRow/batch.

``FakeTransport`` models those endpoints over sorted in-memory tables, for
the zero-egress CI image (SURVEY.md section 4 tier 2 runs against a real
pseudo-distributed HBase in containers); the env-gated live test
(``PIO_TEST_HBASE_URL``) drives the identical DAO code over HTTP.
"""

from __future__ import annotations

import base64
import bisect
import json
import threading
import urllib.error
import urllib.request
import uuid
from typing import Optional


def b64(raw: bytes | str) -> str:
    if isinstance(raw, str):
        raw = raw.encode()
    return base64.b64encode(raw).decode()


def unb64(encoded: str) -> bytes:
    return base64.b64decode(encoded)


class HBaseError(RuntimeError):
    pass


class HttpTransport:
    """Minimal Stargate client over urllib."""

    def __init__(self, base_url: str, timeout: float = 30.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    def _request(
        self, method: str, url: str, body: bytes | None = None
    ) -> tuple[int, dict, bytes]:
        req = urllib.request.Request(url, data=body, method=method)
        req.add_header("Accept", "application/json")
        if body is not None:
            req.add_header("Content-Type", "application/json")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return resp.status, dict(resp.headers), resp.read()
        except urllib.error.HTTPError as exc:
            return exc.code, dict(exc.headers or {}), exc.read()

    def create_table(self, table: str, families: list[str]) -> None:
        body = json.dumps(
            {"name": table, "ColumnSchema": [{"name": f} for f in families]}
        ).encode()
        status, _, payload = self._request(
            "PUT", f"{self.base_url}/{table}/schema", body
        )
        if status not in (200, 201):
            raise HBaseError(f"create table {table}: {status} {payload[:200]!r}")

    def delete_table(self, table: str) -> None:
        self._request("DELETE", f"{self.base_url}/{table}/schema")

    def put_rows(self, table: str, rows: list[tuple[str, dict[str, bytes]]]) -> None:
        """rows: [(rowkey, {"family:qualifier": value_bytes})]"""
        payload = {
            "Row": [
                {
                    "key": b64(key),
                    "Cell": [
                        {"column": b64(col), "$": b64(val)}
                        for col, val in cells.items()
                    ],
                }
                for key, cells in rows
            ]
        }
        status, _, raw = self._request(
            "PUT",
            f"{self.base_url}/{table}/fakerow",  # rowkey in body per Stargate multi-put
            json.dumps(payload).encode(),
        )
        if status not in (200, 201):
            raise HBaseError(f"put rows into {table}: {status} {raw[:200]!r}")

    def get_row(self, table: str, rowkey: str) -> Optional[dict[str, bytes]]:
        status, _, payload = self._request(
            "GET", f"{self.base_url}/{table}/{urllib.request.quote(rowkey, safe='')}"
        )
        if status == 404:
            return None
        if status != 200:
            raise HBaseError(f"get row: {status} {payload[:200]!r}")
        doc = json.loads(payload)
        cells = {}
        for row in doc.get("Row", []):
            for cell in row.get("Cell", []):
                cells[unb64(cell["column"]).decode()] = unb64(cell["$"])
        return cells or None

    def delete_row(self, table: str, rowkey: str) -> bool:
        status, _, _ = self._request(
            "DELETE",
            f"{self.base_url}/{table}/{urllib.request.quote(rowkey, safe='')}",
        )
        return status == 200

    def scan(
        self,
        table: str,
        start_row: str | None = None,
        end_row: str | None = None,
        batch: int = 1000,
    ):
        """Yield (rowkey, cells) in key order."""
        spec: dict = {"batch": batch}
        if start_row is not None:
            spec["startRow"] = b64(start_row)
        if end_row is not None:
            spec["endRow"] = b64(end_row)
        status, headers, payload = self._request(
            "PUT", f"{self.base_url}/{table}/scanner", json.dumps(spec).encode()
        )
        if status == 404:
            return
        if status != 201:
            raise HBaseError(f"create scanner: {status} {payload[:200]!r}")
        location = headers.get("Location") or headers.get("location")
        try:
            while True:
                status, _, payload = self._request("GET", location)
                if status == 204 or not payload:
                    return
                if status != 200:
                    raise HBaseError(f"scanner next: {status} {payload[:200]!r}")
                doc = json.loads(payload)
                for row in doc.get("Row", []):
                    key = unb64(row["key"]).decode()
                    cells = {
                        unb64(c["column"]).decode(): unb64(c["$"])
                        for c in row.get("Cell", [])
                    }
                    yield key, cells
        finally:
            self._request("DELETE", location)


class FakeTransport:
    """In-memory Stargate: sorted tables of rowkey -> cells."""

    def __init__(self):
        self.tables: dict[str, dict[str, dict[str, bytes]]] = {}
        self._sorted_keys: dict[str, list[str]] = {}
        self._lock = threading.RLock()

    def create_table(self, table: str, families: list[str]) -> None:
        with self._lock:
            self.tables.setdefault(table, {})
            self._sorted_keys.setdefault(table, [])

    def delete_table(self, table: str) -> None:
        with self._lock:
            self.tables.pop(table, None)
            self._sorted_keys.pop(table, None)

    def put_rows(self, table: str, rows: list[tuple[str, dict[str, bytes]]]) -> None:
        with self._lock:
            if table not in self.tables:
                raise HBaseError(f"table {table!r} does not exist")
            data = self.tables[table]
            keys = self._sorted_keys[table]
            for key, cells in rows:
                if key not in data:
                    bisect.insort(keys, key)
                data.setdefault(key, {}).update(cells)

    def get_row(self, table: str, rowkey: str) -> Optional[dict[str, bytes]]:
        with self._lock:
            row = self.tables.get(table, {}).get(rowkey)
            return dict(row) if row else None

    def delete_row(self, table: str, rowkey: str) -> bool:
        with self._lock:
            data = self.tables.get(table, {})
            if rowkey in data:
                del data[rowkey]
                keys = self._sorted_keys[table]
                keys.pop(bisect.bisect_left(keys, rowkey))
                return True
            return False

    def scan(
        self,
        table: str,
        start_row: str | None = None,
        end_row: str | None = None,
        batch: int = 1000,
    ):
        with self._lock:
            if table not in self.tables:
                return
            keys = self._sorted_keys[table]
            lo = bisect.bisect_left(keys, start_row) if start_row is not None else 0
            hi = bisect.bisect_left(keys, end_row) if end_row is not None else len(keys)
            snapshot = [(k, dict(self.tables[table][k])) for k in keys[lo:hi]]
        yield from snapshot


def new_suffix() -> str:
    return uuid.uuid4().hex[:16]
