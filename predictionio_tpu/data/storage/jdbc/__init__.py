"""TYPE=jdbc: the reference's storage type name, dispatched by URL scheme.

The reference's scalikejdbc module serves PostgreSQL and MySQL behind the one
``jdbc`` TYPE (SURVEY.md section 2.2 #10); here the URL scheme picks the
dialect module. No URL (or a postgres URL) keeps round-1 behavior: postgres.
"""

from __future__ import annotations

from predictionio_tpu.data.storage.base import StorageClientConfig


def StorageClient(config: StorageClientConfig):
    """Factory matching the registry's ``module.StorageClient(config)`` call."""
    url = config.properties.get("URL", "")
    scheme = url[len("jdbc:"):] if url.startswith("jdbc:") else url
    if scheme.startswith(("mysql:", "mariadb:")):
        from predictionio_tpu.data.storage.mysql import client as mysql_client

        return mysql_client.StorageClient(config)
    from predictionio_tpu.data.storage.postgres import client as pg_client

    return pg_client.StorageClient(config)
