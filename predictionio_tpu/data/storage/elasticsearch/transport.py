"""Elasticsearch REST transport: real HTTP + an in-memory fake.

The backend speaks the ES REST JSON API directly (parity role of the
reference's v0.13 REST-client module, ``storage/elasticsearch/.../
{StorageClient,ESUtils}.scala`` -- apache/predictionio layout, unverified,
SURVEY.md section 2.2 #9); no client library is required.

``FakeTransport`` interprets the exact query-DSL subset the DAOs emit
(bool filter: term/terms/range/exists + must_not, sort, size, search_after)
against in-memory indices. It exists because this CI image has no network
egress and no ES server (SURVEY.md section 4 tier 2 runs the same DAO suite
against real backends in containers); the env-gated live test
(``PIO_TEST_ES_URL``) drives the identical DAO code through HttpTransport.
"""

from __future__ import annotations

import base64
import json
import threading
import urllib.error
import urllib.request
from typing import Any


class ESError(RuntimeError):
    def __init__(self, status: int, body: Any):
        super().__init__(f"elasticsearch error {status}: {str(body)[:500]}")
        self.status = status
        self.body = body


class HttpTransport:
    """Minimal ES REST client over urllib (GET/PUT/POST/DELETE + JSON)."""

    def __init__(
        self,
        base_url: str,
        username: str = "",
        password: str = "",
        timeout: float = 30.0,
    ):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self._auth = None
        if username:
            token = base64.b64encode(f"{username}:{password}".encode()).decode()
            self._auth = f"Basic {token}"

    def request(
        self,
        method: str,
        path: str,
        body: dict | None = None,
        params: dict[str, str] | None = None,
    ) -> tuple[int, dict]:
        url = self.base_url + path
        if params:
            url += "?" + "&".join(f"{k}={v}" for k, v in params.items())
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Content-Type", "application/json")
        if self._auth:
            req.add_header("Authorization", self._auth)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                payload = resp.read()
                return resp.status, json.loads(payload) if payload else {}
        except urllib.error.HTTPError as exc:
            payload = exc.read()
            try:
                parsed = json.loads(payload) if payload else {}
            except json.JSONDecodeError:
                parsed = {"raw": payload.decode("utf-8", "replace")}
            if exc.code == 404:
                return 404, parsed
            raise ESError(exc.code, parsed) from exc


class FakeTransport:
    """In-memory ES: documents per index + the DAO query-DSL subset.

    Deliberately strict: unknown endpoints or query clauses raise instead
    of returning empty results, so a DAO change that emits DSL the fake
    does not model fails loudly in CI rather than passing vacuously.
    """

    def __init__(self):
        # index -> doc_id -> {"_source": dict, "_version": int}
        self.indices: dict[str, dict[str, dict]] = {}
        # index -> the explicit mapping body it was created with
        self.mappings: dict[str, dict] = {}
        # template name -> {"index_patterns": [...], "template": {...}}
        self.index_templates: dict[str, dict] = {}
        self._lock = threading.RLock()

    # -- endpoint router -----------------------------------------------------
    def request(
        self,
        method: str,
        path: str,
        body: dict | None = None,
        params: dict[str, str] | None = None,
    ) -> tuple[int, dict]:
        with self._lock:
            parts = [p for p in path.split("/") if p]
            if not parts:
                return 200, {"cluster_name": "fake"}
            if parts[-1] == "_search":
                return self._search("/".join(parts[:-1]), body or {})
            if parts[-1] == "_refresh":
                return 200, {}
            if parts[-1] == "_delete_by_query":
                return self._delete_by_query("/".join(parts[:-1]), body or {})
            if parts[-1] == "_bulk":
                raise NotImplementedError("fake ES: _bulk not modeled")
            if len(parts) == 2 and parts[0] == "_index_template" and method == "PUT":
                self.index_templates[parts[1]] = dict(body or {})
                return 200, {"acknowledged": True}
            if len(parts) == 3 and parts[1] == "_doc":
                index, doc_id = parts[0], parts[2]
                if method in ("PUT", "POST"):
                    return self._index_doc(index, doc_id, body)
                if method == "GET":
                    return self._get_doc(index, doc_id)
                if method == "DELETE":
                    return self._delete_doc(index, doc_id)
            if len(parts) == 4 and parts[1] == "_update":
                raise NotImplementedError("fake ES: _update not modeled")
            if len(parts) == 1 and method == "PUT":  # create index
                if parts[0] in self.indices:
                    # real ES 400s on re-create; the DAO ensure_index path
                    # treats that as success, so model it faithfully
                    raise ESError(
                        400,
                        {"error": {"type": "resource_already_exists_exception"}},
                    )
                self.indices[parts[0]] = {}
                self.mappings[parts[0]] = (body or {}).get("mappings", {})
                return 200, {"acknowledged": True}
            if len(parts) == 1 and method == "DELETE":
                self.indices.pop(parts[0], None)
                self.mappings.pop(parts[0], None)
                return 200, {"acknowledged": True}
            if len(parts) == 1 and method == "HEAD":
                return (200 if parts[0] in self.indices else 404), {}
            raise NotImplementedError(f"fake ES: {method} {path!r} not modeled")

    # -- document ops --------------------------------------------------------
    def _index_doc(self, index: str, doc_id: str, body: dict) -> tuple[int, dict]:
        if index not in self.indices:
            # real ES would auto-create with DYNAMIC mappings here -- the
            # exact failure mode the explicit-mapping contract exists to
            # prevent (analyzed term queries, unsortable ids). Fail loudly
            # so a DAO write path that skipped ensure_index is caught in CI.
            raise NotImplementedError(
                f"fake ES: write to index {index!r} before explicit creation"
                " -- DAO must ensure_index (explicit mappings) first"
            )
        docs = self.indices[index]
        existing = docs.get(doc_id)
        version = (existing["_version"] + 1) if existing else 1
        docs[doc_id] = {"_source": dict(body or {}), "_version": version}
        return 200, {"_id": doc_id, "_version": version, "result": "updated" if existing else "created"}

    def _get_doc(self, index: str, doc_id: str) -> tuple[int, dict]:
        doc = self.indices.get(index, {}).get(doc_id)
        if doc is None:
            return 404, {"found": False}
        return 200, {"_id": doc_id, "found": True, "_source": dict(doc["_source"]), "_version": doc["_version"]}

    def _delete_doc(self, index: str, doc_id: str) -> tuple[int, dict]:
        docs = self.indices.get(index, {})
        if doc_id in docs:
            del docs[doc_id]
            return 200, {"result": "deleted"}
        return 404, {"result": "not_found"}

    def _delete_by_query(self, index: str, body: dict) -> tuple[int, dict]:
        docs = self.indices.get(index, {})
        doomed = [
            doc_id
            for doc_id, doc in docs.items()
            if self._matches(doc["_source"], body.get("query", {"match_all": {}}))
        ]
        for doc_id in doomed:
            del docs[doc_id]
        return 200, {"deleted": len(doomed)}

    # -- search --------------------------------------------------------------
    def _search(self, index: str, body: dict) -> tuple[int, dict]:
        # index may be a comma list or a wildcard pattern
        import fnmatch

        names = []
        for pat in index.split(","):
            if "*" in pat:
                names.extend(n for n in self.indices if fnmatch.fnmatch(n, pat))
            elif pat in self.indices:
                names.append(pat)
        hits = []
        for name in names:
            for doc_id, doc in self.indices[name].items():
                if self._matches(doc["_source"], body.get("query", {"match_all": {}})):
                    hits.append({"_index": name, "_id": doc_id, "_source": dict(doc["_source"])})

        for clause in reversed(body.get("sort", [])):
            if clause == "_doc":
                continue
            [(field, spec)] = clause.items() if isinstance(clause, dict) else [(clause, "asc")]
            order = spec.get("order", "asc") if isinstance(spec, dict) else spec
            hits.sort(
                key=lambda h: (h["_source"].get(field) is None, h["_source"].get(field)),
                reverse=(order == "desc"),
            )
        if body.get("search_after") is not None:
            after = body["search_after"]

            def sort_vals(h):
                vals = []
                for clause in body.get("sort", []):
                    [(field, spec)] = (
                        clause.items() if isinstance(clause, dict) else [(clause, "asc")]
                    )
                    vals.append(h["_source"].get(field))
                return vals

            # emit strictly-after hits in current sort order
            def after_key(h):
                return sort_vals(h)

            passed = []
            for h in hits:
                vals = after_key(h)
                cmp = self._tuple_cmp(vals, after, body.get("sort", []))
                if cmp > 0:
                    passed.append(h)
            hits = passed
        size = body.get("size", 10)
        hits = hits[: int(size)]
        for h in hits:
            h["sort"] = [
                h["_source"].get(next(iter(c))) if isinstance(c, dict) else None
                for c in body.get("sort", [])
            ]
        source_filter = body.get("_source")
        if isinstance(source_filter, list):
            for h in hits:
                h["_source"] = {
                    k: v for k, v in h["_source"].items() if k in source_filter
                }
        return 200, {"hits": {"total": {"value": len(hits)}, "hits": hits}}

    @staticmethod
    def _tuple_cmp(vals, after, sort_clauses) -> int:
        """-1/0/1 of vals vs after under the per-field sort orders."""
        for v, a, clause in zip(vals, after, sort_clauses):
            [(field, spec)] = (
                clause.items() if isinstance(clause, dict) else [(clause, "asc")]
            )
            order = spec.get("order", "asc") if isinstance(spec, dict) else spec
            if v == a:
                continue
            less = (v is None, v) < (a is None, a)
            if order == "desc":
                less = not less
            return -1 if less else 1
        return 0

    def _matches(self, source: dict, query: dict) -> bool:
        [(kind, clause)] = query.items()
        if kind == "match_all":
            return True
        if kind == "term":
            [(field, value)] = clause.items()
            if isinstance(value, dict):
                value = value["value"]
            return source.get(field) == value
        if kind == "terms":
            [(field, values)] = clause.items()
            return source.get(field) in values
        if kind == "range":
            [(field, bounds)] = clause.items()
            value = source.get(field)
            if value is None:
                return False
            if "gte" in bounds and not value >= bounds["gte"]:
                return False
            if "gt" in bounds and not value > bounds["gt"]:
                return False
            if "lte" in bounds and not value <= bounds["lte"]:
                return False
            if "lt" in bounds and not value < bounds["lt"]:
                return False
            return True
        if kind == "exists":
            return source.get(clause["field"]) is not None
        if kind == "bool":
            for sub in clause.get("filter", []):
                if not self._matches(source, sub):
                    return False
            for sub in clause.get("must", []):
                if not self._matches(source, sub):
                    return False
            for sub in clause.get("must_not", []):
                if self._matches(source, sub):
                    return False
            return True
        raise NotImplementedError(f"fake ES: query clause {kind!r} not modeled")
