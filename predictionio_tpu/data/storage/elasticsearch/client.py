"""Elasticsearch implementations of every DAO contract.

Parity role of the reference's metadata-store-of-record module
``storage/elasticsearch/.../{StorageClient,ESApps,ESAccessKeys,ESChannels,
ESEngineInstances,ESEvaluationInstances,ESLEvents,ESSequences,ESUtils}.scala``
(apache/predictionio layout, unverified -- SURVEY.md section 2.2 #9): a
full-stack backend (metadata + events + models) over the ES REST JSON API.

Configuration (reference env-var contract, SURVEY.md section 5.6):

    PIO_STORAGE_SOURCES_ELASTICSEARCH_TYPE=elasticsearch
    PIO_STORAGE_SOURCES_ELASTICSEARCH_HOSTS=localhost
    PIO_STORAGE_SOURCES_ELASTICSEARCH_PORTS=9200
    PIO_STORAGE_SOURCES_ELASTICSEARCH_SCHEMES=http
    PIO_STORAGE_SOURCES_ELASTICSEARCH_USERNAME=...   (optional basic auth)
    PIO_STORAGE_SOURCES_ELASTICSEARCH_PASSWORD=...
    PIO_STORAGE_SOURCES_ELASTICSEARCH_INDEX=pio      (index name prefix)
    PIO_STORAGE_SOURCES_ELASTICSEARCH_TRANSPORT=fake (in-memory; CI only)

Design notes:

- integer ids (apps, channels) come from an ES sequence index whose doc
  ``_version`` increments atomically on every index op -- the reference's
  ESSequences trick.
- every write passes ``refresh=true`` so reads are immediately consistent
  (the DAO contract the rest of the framework assumes; matches reference
  ESUtils' refresh-on-write in metadata paths).
- event scans paginate via ``search_after`` on (event_time_ms, event_id),
  so arbitrarily large scans stream without ES's 10k window cap.
"""

from __future__ import annotations

import datetime as _dt
import json
import secrets
import uuid
from typing import Iterable, Iterator, Optional

from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import base
from predictionio_tpu.data.storage.base import (
    AccessKey,
    App,
    Channel,
    EngineInstance,
    EvaluationInstance,
    Model,
    StorageClientConfig,
)
from predictionio_tpu.data.storage.elasticsearch.transport import (
    ESError,
    FakeTransport,
    HttpTransport,
)
from predictionio_tpu.data.storage.sql_common import ts_from_str, ts_ms, ts_to_str

_SCAN_PAGE = 1000

# -- explicit index mappings (reference ESUtils' not_analyzed mappings) ------
#
# Without these, a live ES dynamic-maps every string to analyzed text: term
# queries on uppercase/spaced values (app names, entity ids) silently miss,
# and sorting on event_id 400s. keyword for ids/names/entities, long for
# *_ms, date for ISO8601 timestamps; large JSON blobs are stored-only
# (text, index:false -- never queried, only read back from _source).

_KW = {"type": "keyword"}
_LONG = {"type": "long"}
_TS = {"type": "date", "format": "strict_date_optional_time"}
_BLOB = {"type": "text", "index": False}


def mapping_for(kind: str) -> dict:
    """ES mapping body for one index kind (``events_*`` share one shape)."""
    if kind.startswith("events"):
        props = {
            "event_id": _KW,
            "event": _KW,
            "entity_type": _KW,
            "entity_id": _KW,
            "target_entity_type": _KW,
            "target_entity_id": _KW,
            "properties": _BLOB,
            "event_time": _TS,
            "event_time_ms": _LONG,
            "pr_id": _KW,
            "creation_time": _TS,
        }
    elif kind == "meta_apps":
        props = {"id": _LONG, "name": _KW, "description": _BLOB}
    elif kind == "meta_channels":
        props = {"id": _LONG, "name": _KW, "app_id": _LONG}
    elif kind == "meta_accesskeys":
        props = {"key": _KW, "app_id": _LONG, "events": _KW}
    elif kind == "meta_engine_instances":
        props = {
            "id": _KW,
            "status": _KW,
            "start_time": _TS,
            "end_time": _TS,
            "engine_id": _KW,
            "engine_version": _KW,
            "engine_variant": _KW,
            "engine_factory": _KW,
            "batch": _BLOB,
            "env": _BLOB,
            "runtime_conf": _BLOB,
            "data_source_params": _BLOB,
            "preparator_params": _BLOB,
            "algorithms_params": _BLOB,
            "serving_params": _BLOB,
        }
    elif kind == "meta_evaluation_instances":
        props = {
            "id": _KW,
            "status": _KW,
            "start_time": _TS,
            "end_time": _TS,
            "evaluation_class": _KW,
            "engine_params_generator_class": _KW,
            "batch": _BLOB,
            "env": _BLOB,
            "evaluator_results": _BLOB,
            "evaluator_results_html": _BLOB,
            "evaluator_results_json": _BLOB,
        }
    elif kind == "models":
        props = {"id": _KW, "models": {"type": "binary"}}
    elif kind == "sequences":
        props = {"n": _LONG}
    else:
        raise KeyError(f"no ES mapping defined for index kind {kind!r}")
    return {"properties": props}


class StorageClient(base.BaseStorageClient):
    def __init__(self, config: StorageClientConfig, transport=None):
        super().__init__(config)
        props = config.properties
        self.prefix = props.get("INDEX", "pio")
        self._ensured: set[str] = set()
        if transport is not None:
            self.transport = transport
        elif props.get("TRANSPORT", "").lower() == "fake":
            self.transport = FakeTransport()
        else:
            host = (props.get("HOSTS", "localhost")).split(",")[0]
            port = (props.get("PORTS", "9200")).split(",")[0]
            scheme = (props.get("SCHEMES", "http")).split(",")[0]
            self.transport = HttpTransport(
                f"{scheme}://{host}:{port}",
                username=props.get("USERNAME", ""),
                password=props.get("PASSWORD", ""),
            )

    # -- shared helpers ------------------------------------------------------
    def index_name(self, kind: str) -> str:
        return f"{self.prefix}_{kind}"

    def ensure_index(self, kind: str) -> None:
        """Create the index with its explicit mapping before first write.

        Relying on ES dynamic mapping would analyze every string field:
        term queries on uppercase/spaced values miss and event_id sorts
        400. Races/pre-existing indices surface as 400
        resource_already_exists, which is success here.
        """
        if kind in self._ensured:
            return
        if kind.startswith("events"):
            # a cluster-side index template covers paths this per-process
            # cache cannot: another process deletes an events index
            # (app data-delete) and our next write auto-creates it --
            # with the template, even auto-create carries the mappings
            self._ensure_events_template()
        try:
            self.transport.request(
                "PUT",
                f"/{self.index_name(kind)}",
                body={"mappings": mapping_for(kind)},
            )
        except ESError as exc:
            error_type = ""
            if isinstance(exc.body, dict):
                error_type = (exc.body.get("error") or {}).get("type", "")
            if exc.status != 400 or "exists" not in error_type:
                raise
        self._ensured.add(kind)

    def _ensure_events_template(self) -> None:
        if getattr(self, "_events_template_done", False):
            return
        name = f"{self.prefix}_events"
        patterns = [f"{self.prefix}_events_*"]
        try:
            self.transport.request(
                "PUT",
                f"/_index_template/{name}",
                body={
                    "index_patterns": patterns,
                    "template": {"mappings": mapping_for("events")},
                },
            )
        except ESError:
            # pre-7.8 clusters only know the legacy endpoint
            self.transport.request(
                "PUT",
                f"/_template/{name}",
                body={"index_patterns": patterns, "mappings": mapping_for("events")},
            )
        self._events_template_done = True

    def drop_index(self, kind: str) -> None:
        self.transport.request("DELETE", f"/{self.index_name(kind)}")
        self._ensured.discard(kind)

    def next_id(self, sequence: str) -> int:
        """Atomic int sequence via ES doc versioning (reference ESSequences)."""
        self.ensure_index("sequences")
        status, body = self.transport.request(
            "PUT",
            f"/{self.index_name('sequences')}/_doc/{sequence}",
            body={"n": 1},
            params={"refresh": "true"},
        )
        return int(body["_version"])

    def put(self, kind: str, doc_id: str, source: dict) -> None:
        self.ensure_index(kind)
        self.transport.request(
            "PUT",
            f"/{self.index_name(kind)}/_doc/{doc_id}",
            body=source,
            params={"refresh": "true"},
        )

    def get_source(self, kind: str, doc_id: str) -> Optional[dict]:
        status, body = self.transport.request(
            "GET", f"/{self.index_name(kind)}/_doc/{doc_id}"
        )
        if status == 404 or not body.get("found"):
            return None
        return body["_source"]

    def delete_doc(self, kind: str, doc_id: str) -> bool:
        status, body = self.transport.request(
            "DELETE",
            f"/{self.index_name(kind)}/_doc/{doc_id}",
            params={"refresh": "true"},
        )
        return status == 200 and body.get("result") == "deleted"

    def search(self, kind: str, query: dict, size: int = 10000, sort=None) -> list[dict]:
        body = {"query": query, "size": size}
        if sort:
            body["sort"] = sort
        status, result = self.transport.request(
            "POST", f"/{self.index_name(kind)}/_search", body=body
        )
        if status == 404:  # index not created yet = no documents
            return []
        return [h["_source"] for h in result["hits"]["hits"]]

    def get_dao(self, repo: str):
        return {
            "apps": ESApps,
            "channels": ESChannels,
            "access_keys": ESAccessKeys,
            "engine_instances": ESEngineInstances,
            "evaluation_instances": ESEvaluationInstances,
            "models": ESModels,
            "events": ESLEvents,
        }[repo](self)


class ESApps(base.Apps):
    KIND = "meta_apps"

    def __init__(self, client: StorageClient):
        self.c = client

    @staticmethod
    def _to_app(source: dict) -> App:
        return App(id=source["id"], name=source["name"], description=source["description"])

    def insert(self, app: App) -> int:
        app.id = app.id or self.c.next_id("apps")
        self.c.put(self.KIND, str(app.id), {
            "id": app.id, "name": app.name, "description": app.description,
        })
        return app.id

    def get(self, app_id: int) -> Optional[App]:
        source = self.c.get_source(self.KIND, str(app_id))
        return self._to_app(source) if source else None

    def get_by_name(self, name: str) -> Optional[App]:
        hits = self.c.search(self.KIND, {"term": {"name": name}}, size=1)
        return self._to_app(hits[0]) if hits else None

    def get_all(self) -> list[App]:
        hits = self.c.search(self.KIND, {"match_all": {}}, sort=[{"id": "asc"}])
        return [self._to_app(h) for h in hits]

    def update(self, app: App) -> None:
        self.c.put(self.KIND, str(app.id), {
            "id": app.id, "name": app.name, "description": app.description,
        })

    def delete(self, app_id: int) -> None:
        self.c.delete_doc(self.KIND, str(app_id))


class ESChannels(base.Channels):
    KIND = "meta_channels"

    def __init__(self, client: StorageClient):
        self.c = client

    @staticmethod
    def _to_channel(source: dict) -> Channel:
        return Channel(id=source["id"], name=source["name"], app_id=source["app_id"])

    def insert(self, channel: Channel) -> int:
        channel.id = channel.id or self.c.next_id("channels")
        self.c.put(self.KIND, str(channel.id), {
            "id": channel.id, "name": channel.name, "app_id": channel.app_id,
        })
        return channel.id

    def get(self, channel_id: int) -> Optional[Channel]:
        source = self.c.get_source(self.KIND, str(channel_id))
        return self._to_channel(source) if source else None

    def get_by_app(self, app_id: int) -> list[Channel]:
        hits = self.c.search(
            self.KIND, {"term": {"app_id": app_id}}, sort=[{"id": "asc"}]
        )
        return [self._to_channel(h) for h in hits]

    def delete(self, channel_id: int) -> None:
        self.c.delete_doc(self.KIND, str(channel_id))


class ESAccessKeys(base.AccessKeys):
    KIND = "meta_accesskeys"

    def __init__(self, client: StorageClient):
        self.c = client

    @staticmethod
    def _to_key(source: dict) -> AccessKey:
        return AccessKey(
            key=source["key"], app_id=source["app_id"], events=list(source["events"])
        )

    def insert(self, access_key: AccessKey) -> str:
        key = access_key.key or secrets.token_urlsafe(48)
        access_key.key = key
        self.c.put(self.KIND, key, {
            "key": key, "app_id": access_key.app_id, "events": access_key.events,
        })
        return key

    def get(self, key: str) -> Optional[AccessKey]:
        source = self.c.get_source(self.KIND, key)
        return self._to_key(source) if source else None

    def get_all(self) -> list[AccessKey]:
        return [self._to_key(h) for h in self.c.search(self.KIND, {"match_all": {}})]

    def get_by_app_id(self, app_id: int) -> list[AccessKey]:
        hits = self.c.search(self.KIND, {"term": {"app_id": app_id}})
        return [self._to_key(h) for h in hits]

    def update(self, access_key: AccessKey) -> None:
        self.c.put(self.KIND, access_key.key, {
            "key": access_key.key,
            "app_id": access_key.app_id,
            "events": access_key.events,
        })

    def delete(self, key: str) -> None:
        self.c.delete_doc(self.KIND, key)


class ESEngineInstances(base.EngineInstances):
    KIND = "meta_engine_instances"

    def __init__(self, client: StorageClient):
        self.c = client

    @staticmethod
    def _to_source(i: EngineInstance) -> dict:
        return {
            "id": i.id,
            "status": i.status,
            "start_time": ts_to_str(i.start_time),
            "end_time": ts_to_str(i.end_time),
            "engine_id": i.engine_id,
            "engine_version": i.engine_version,
            "engine_variant": i.engine_variant,
            "engine_factory": i.engine_factory,
            "batch": i.batch,
            "env": json.dumps(i.env),
            "runtime_conf": json.dumps(i.runtime_conf),
            "data_source_params": i.data_source_params,
            "preparator_params": i.preparator_params,
            "algorithms_params": i.algorithms_params,
            "serving_params": i.serving_params,
        }

    @staticmethod
    def _to_instance(s: dict) -> EngineInstance:
        return EngineInstance(
            id=s["id"],
            status=s["status"],
            start_time=ts_from_str(s["start_time"]),
            end_time=ts_from_str(s.get("end_time")),
            engine_id=s["engine_id"],
            engine_version=s["engine_version"],
            engine_variant=s["engine_variant"],
            engine_factory=s["engine_factory"],
            batch=s["batch"],
            env=json.loads(s["env"]),
            runtime_conf=json.loads(s["runtime_conf"]),
            data_source_params=s["data_source_params"],
            preparator_params=s["preparator_params"],
            algorithms_params=s["algorithms_params"],
            serving_params=s["serving_params"],
        )

    def insert(self, instance: EngineInstance) -> str:
        instance.id = instance.id or uuid.uuid4().hex
        self.c.put(self.KIND, instance.id, self._to_source(instance))
        return instance.id

    def get(self, instance_id: str) -> Optional[EngineInstance]:
        source = self.c.get_source(self.KIND, instance_id)
        return self._to_instance(source) if source else None

    def get_all(self) -> list[EngineInstance]:
        hits = self.c.search(
            self.KIND, {"match_all": {}}, sort=[{"start_time": "desc"}]
        )
        return [self._to_instance(h) for h in hits]

    def _variant_query(self, engine_id, engine_version, engine_variant, status=None):
        filters = [
            {"term": {"engine_id": engine_id}},
            {"term": {"engine_version": engine_version}},
            {"term": {"engine_variant": engine_variant}},
        ]
        if status is not None:
            filters.append({"term": {"status": status}})
        return {"bool": {"filter": filters}}

    def get_completed(self, engine_id, engine_version, engine_variant):
        hits = self.c.search(
            self.KIND,
            self._variant_query(
                engine_id, engine_version, engine_variant, base.STATUS_COMPLETED
            ),
            sort=[{"start_time": "desc"}],
        )
        return [self._to_instance(h) for h in hits]

    def get_latest_completed(self, engine_id, engine_version, engine_variant):
        completed = self.get_completed(engine_id, engine_version, engine_variant)
        return completed[0] if completed else None

    def get_latest(self, engine_id, engine_version, engine_variant):
        hits = self.c.search(
            self.KIND,
            self._variant_query(engine_id, engine_version, engine_variant),
            sort=[{"start_time": "desc"}],
            size=1,
        )
        return self._to_instance(hits[0]) if hits else None

    def update(self, instance: EngineInstance) -> None:
        self.c.put(self.KIND, instance.id, self._to_source(instance))

    def delete(self, instance_id: str) -> None:
        self.c.delete_doc(self.KIND, instance_id)


class ESEvaluationInstances(base.EvaluationInstances):
    KIND = "meta_evaluation_instances"

    def __init__(self, client: StorageClient):
        self.c = client

    @staticmethod
    def _to_source(i: EvaluationInstance) -> dict:
        return {
            "id": i.id,
            "status": i.status,
            "start_time": ts_to_str(i.start_time),
            "end_time": ts_to_str(i.end_time),
            "evaluation_class": i.evaluation_class,
            "engine_params_generator_class": i.engine_params_generator_class,
            "batch": i.batch,
            "env": json.dumps(i.env),
            "evaluator_results": i.evaluator_results,
            "evaluator_results_html": i.evaluator_results_html,
            "evaluator_results_json": i.evaluator_results_json,
        }

    @staticmethod
    def _to_instance(s: dict) -> EvaluationInstance:
        return EvaluationInstance(
            id=s["id"],
            status=s["status"],
            start_time=ts_from_str(s["start_time"]),
            end_time=ts_from_str(s.get("end_time")),
            evaluation_class=s["evaluation_class"],
            engine_params_generator_class=s["engine_params_generator_class"],
            batch=s["batch"],
            env=json.loads(s["env"]),
            evaluator_results=s["evaluator_results"],
            evaluator_results_html=s["evaluator_results_html"],
            evaluator_results_json=s["evaluator_results_json"],
        )

    def insert(self, instance: EvaluationInstance) -> str:
        instance.id = instance.id or uuid.uuid4().hex
        self.c.put(self.KIND, instance.id, self._to_source(instance))
        return instance.id

    def get(self, instance_id: str) -> Optional[EvaluationInstance]:
        source = self.c.get_source(self.KIND, instance_id)
        return self._to_instance(source) if source else None

    def get_all(self) -> list[EvaluationInstance]:
        hits = self.c.search(
            self.KIND, {"match_all": {}}, sort=[{"start_time": "desc"}]
        )
        return [self._to_instance(h) for h in hits]

    def get_completed(self) -> list[EvaluationInstance]:
        hits = self.c.search(
            self.KIND,
            {"term": {"status": base.STATUS_COMPLETED}},
            sort=[{"start_time": "desc"}],
        )
        return [self._to_instance(h) for h in hits]

    def update(self, instance: EvaluationInstance) -> None:
        self.c.put(self.KIND, instance.id, self._to_source(instance))

    def delete(self, instance_id: str) -> None:
        self.c.delete_doc(self.KIND, instance_id)


class ESModels(base.Models):
    """Model blobs, base64-wrapped (ES documents are JSON)."""

    KIND = "models"

    def __init__(self, client: StorageClient):
        self.c = client

    def insert(self, model: Model) -> None:
        import base64

        self.c.put(self.KIND, model.id, {
            "id": model.id, "models": base64.b64encode(model.models).decode(),
        })

    def get(self, model_id: str) -> Optional[Model]:
        import base64

        source = self.c.get_source(self.KIND, model_id)
        if source is None:
            return None
        return Model(id=source["id"], models=base64.b64decode(source["models"]))

    def delete(self, model_id: str) -> None:
        self.c.delete_doc(self.KIND, model_id)


class ESLEvents(base.LEvents):
    """Events: one index per app/channel (reference one-table-per naming:
    ``pio_event:events_<appId>[_<channelId>]``, here ``<prefix>_events_...``)."""

    def __init__(self, client: StorageClient):
        self.c = client

    def _kind(self, app_id: int, channel_id: int | None) -> str:
        suffix = f"_{channel_id}" if channel_id else ""
        return f"events_{app_id}{suffix}"

    def init_channel(self, app_id: int, channel_id: int | None = None) -> bool:
        self.c.ensure_index(self._kind(app_id, channel_id))
        return True

    def remove_channel(self, app_id: int, channel_id: int | None = None) -> bool:
        self.c.drop_index(self._kind(app_id, channel_id))
        return True

    @staticmethod
    def _to_source(ev: Event) -> dict:
        return {
            "event_id": ev.event_id,
            "event": ev.event,
            "entity_type": ev.entity_type,
            "entity_id": ev.entity_id,
            "target_entity_type": ev.target_entity_type,
            "target_entity_id": ev.target_entity_id,
            "properties": json.dumps(ev.properties.to_dict()),
            "event_time": ev.event_time.isoformat(),
            "event_time_ms": ts_ms(ev.event_time),
            "pr_id": ev.pr_id,
            "creation_time": ev.creation_time.isoformat(),
        }

    @staticmethod
    def _to_event(s: dict) -> Event:
        return Event(
            event_id=s["event_id"],
            event=s["event"],
            entity_type=s["entity_type"],
            entity_id=s["entity_id"],
            target_entity_type=s.get("target_entity_type"),
            target_entity_id=s.get("target_entity_id"),
            properties=DataMap(json.loads(s["properties"])),
            event_time=_dt.datetime.fromisoformat(s["event_time"]),
            pr_id=s.get("pr_id"),
            creation_time=_dt.datetime.fromisoformat(s["creation_time"]),
        )

    def insert(self, event: Event, app_id: int, channel_id: int | None = None) -> str:
        return self.batch_insert([event], app_id, channel_id)[0]

    def batch_insert(
        self, events: Iterable[Event], app_id: int, channel_id: int | None = None
    ) -> list[str]:
        kind = self._kind(app_id, channel_id)
        ids = []
        for ev in events:
            ev = ev if ev.event_id else ev.with_id()
            ids.append(ev.event_id)
            self.c.put(kind, ev.event_id, self._to_source(ev))
        return ids

    def get(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> Optional[Event]:
        source = self.c.get_source(self._kind(app_id, channel_id), event_id)
        return self._to_event(source) if source else None

    def delete(
        self, event_id: str, app_id: int, channel_id: int | None = None
    ) -> bool:
        return self.c.delete_doc(self._kind(app_id, channel_id), event_id)

    @staticmethod
    def _build_query(
        start_time=None,
        until_time=None,
        entity_type=None,
        entity_id=None,
        event_names=None,
        target_entity_type=...,
        target_entity_id=...,
    ) -> dict:
        """Filter DSL shared by find() and scan_interactions(): one
        definition so the row and columnar paths cannot desynchronize."""
        filters: list[dict] = []
        must_not: list[dict] = []
        time_range: dict = {}
        if start_time is not None:
            time_range["gte"] = ts_ms(start_time)
        if until_time is not None:
            time_range["lt"] = ts_ms(until_time)
        if time_range:
            filters.append({"range": {"event_time_ms": time_range}})
        if entity_type is not None:
            filters.append({"term": {"entity_type": entity_type}})
        if entity_id is not None:
            filters.append({"term": {"entity_id": entity_id}})
        if event_names:
            filters.append({"terms": {"event": event_names}})
        if target_entity_type is not ...:
            if target_entity_type is None:
                must_not.append({"exists": {"field": "target_entity_type"}})
            else:
                filters.append({"term": {"target_entity_type": target_entity_type}})
        if target_entity_id is not ...:
            if target_entity_id is None:
                must_not.append({"exists": {"field": "target_entity_id"}})
            else:
                filters.append({"term": {"target_entity_id": target_entity_id}})
        return {"bool": {"filter": filters, "must_not": must_not}}

    def _scan(
        self,
        app_id: int,
        channel_id: int | None,
        query: dict,
        reversed: bool = False,
        limit: int | None = None,
        source_fields: list[str] | None = None,
    ) -> Iterator[dict]:
        """search_after-paginated hit stream (sources only)."""
        order = "desc" if reversed else "asc"
        sort = [{"event_time_ms": order}, {"event_id": order}]
        index = self.c.index_name(self._kind(app_id, channel_id))
        remaining = limit if (limit is not None and limit >= 0) else None
        search_after = None
        while True:
            page = _SCAN_PAGE if remaining is None else min(_SCAN_PAGE, remaining)
            if page == 0:
                return
            body = {"query": query, "size": page, "sort": sort}
            if source_fields is not None:
                body["_source"] = source_fields
            if search_after is not None:
                body["search_after"] = search_after
            status, result = self.c.transport.request(
                "POST", f"/{index}/_search", body=body
            )
            if status == 404:
                return
            hits = result["hits"]["hits"]
            for h in hits:
                yield h["_source"]
            if remaining is not None:
                remaining -= len(hits)
                if remaining <= 0:
                    return
            if len(hits) < page:
                return
            search_after = hits[-1]["sort"]

    def find(
        self,
        app_id: int,
        channel_id: int | None = None,
        start_time: _dt.datetime | None = None,
        until_time: _dt.datetime | None = None,
        entity_type: str | None = None,
        entity_id: str | None = None,
        event_names: list[str] | None = None,
        target_entity_type=...,
        target_entity_id=...,
        limit: int | None = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        query = self._build_query(
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            entity_id=entity_id,
            event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id,
        )
        for source in self._scan(
            app_id, channel_id, query, reversed=reversed, limit=limit
        ):
            yield self._to_event(source)

    def scan_interactions(
        self,
        app_id: int,
        channel_id: int | None = None,
        event_names: list[str] | None = None,
        target_entity_type=...,
        start_time: _dt.datetime | None = None,
        until_time: _dt.datetime | None = None,
        rating_key: str = "rating",
    ):
        """Columnar training scan (same contract as the SQL backends'
        ``scan_interactions``): five parallel lists, no Event/DataMap
        construction per hit, ``_source`` filtered to the training columns.
        The rating still needs a host-side parse of the properties JSON
        string, gated on a cheap substring test so unrated events skip it;
        the number-only rule matches ``EventDataset.from_events``.
        """
        query = self._build_query(
            start_time=start_time,
            until_time=until_time,
            event_names=event_names,
            target_entity_type=target_entity_type,
        )
        # the stored properties string came from json.dumps, so build the
        # needle the same way: a non-ASCII key is stored \u-escaped and a
        # raw f'"{key}"' would never match it
        needle = json.dumps(rating_key)
        ents: list = []
        tgts: list = []
        names: list = []
        times: list = []
        ratings: list = []
        for s in self._scan(
            app_id,
            channel_id,
            query,
            source_fields=[
                "entity_id", "target_entity_id", "event", "event_time",
                "properties",
            ],
        ):
            ents.append(s["entity_id"])
            tgts.append(s.get("target_entity_id"))
            names.append(s["event"])
            times.append(s["event_time"])
            rating = None
            props = s.get("properties")
            if props and needle in props:
                value = json.loads(props).get(rating_key)
                if isinstance(value, (int, float)) and not isinstance(value, bool):
                    rating = value
            ratings.append(rating)
        return ents, tgts, names, times, ratings
