"""Elasticsearch storage backend (TYPE=elasticsearch)."""

from predictionio_tpu.data.storage.elasticsearch.client import StorageClient

__all__ = ["StorageClient"]
