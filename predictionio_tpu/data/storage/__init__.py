"""Storage registry: env-configured, pluggable backend discovery.

Behavioral model: reference ``data/.../storage/Storage.scala`` (apache/
predictionio layout, unverified -- SURVEY.md section 2.2 #6). Configuration
plane is identical:

- ``PIO_STORAGE_REPOSITORIES_{METADATA,EVENTDATA,MODELDATA}_{NAME,SOURCE}``
- ``PIO_STORAGE_SOURCES_<SOURCE>_{TYPE,PATH,...}``

Where the reference discovers backends by JVM reflection on a class-name
convention, we resolve ``TYPE`` through an explicit registry dict (extensible
via :func:`register_backend`) and import the backend module lazily.

Defaults (no env set): a sqlite file under ``$PIO_FS_BASEDIR`` (default
``~/.pio_store``) backs all three repositories -- zero-config dev bring-up,
the parity role of the reference's PGSQL quickstart path.
"""

from __future__ import annotations

import importlib
import os
import threading
from typing import Optional

from predictionio_tpu.data.storage.base import (
    AccessKeys,
    Apps,
    BaseStorageClient,
    Channels,
    EngineInstances,
    EvaluationInstances,
    LEvents,
    Models,
    StorageClientConfig,
)

#: TYPE value -> module path providing a StorageClient class.
_BACKENDS: dict[str, str] = {
    "sqlite": "predictionio_tpu.data.storage.sqlite",
    "memory": "predictionio_tpu.data.storage.memory",
    "localfs": "predictionio_tpu.data.storage.localfs",
    "postgres": "predictionio_tpu.data.storage.postgres",
    "mysql": "predictionio_tpu.data.storage.mysql",
    "elasticsearch": "predictionio_tpu.data.storage.elasticsearch",
    "hbase": "predictionio_tpu.data.storage.hbase",
    # reference TYPE name for the scalikejdbc module; URL scheme picks
    # postgres vs mysql (postgres when absent)
    "jdbc": "predictionio_tpu.data.storage.jdbc",
    "s3": "predictionio_tpu.data.storage.s3",
    "hdfs": "predictionio_tpu.data.storage.hdfs",
}

_REPOS = ("METADATA", "EVENTDATA", "MODELDATA")


def register_backend(type_name: str, module_path: str) -> None:
    """Register a third-party backend (module must expose ``StorageClient``)."""
    _BACKENDS[type_name] = module_path


class StorageError(RuntimeError):
    pass


def base_dir() -> str:
    """The filesystem root (``$PIO_FS_BASEDIR``) shared by storage defaults,
    daemon pidfiles/logs, and the native-kernel cache fallback."""
    return os.environ.get("PIO_FS_BASEDIR", os.path.expanduser("~/.pio_store"))


_base_dir = base_dir


class _Registry:
    """Process-wide singleton cache of storage clients and DAOs."""

    def __init__(self):
        self._lock = threading.RLock()
        self._clients: dict[str, BaseStorageClient] = {}

    # -- config resolution --------------------------------------------------
    def _repo_source(self, repo: str) -> str:
        return os.environ.get(f"PIO_STORAGE_REPOSITORIES_{repo}_SOURCE", "PIO_SQLITE")

    def _source_config(self, source: str) -> tuple[str, StorageClientConfig]:
        prefix = f"PIO_STORAGE_SOURCES_{source}_"
        props = {
            k[len(prefix):]: v for k, v in os.environ.items() if k.startswith(prefix)
        }
        type_name = props.pop("TYPE", "sqlite" if source == "PIO_SQLITE" else None)
        if type_name is None:
            raise StorageError(
                f"storage source {source!r} has no {prefix}TYPE configured"
            )
        if type_name == "sqlite" and "PATH" not in props:
            os.makedirs(_base_dir(), exist_ok=True)
            props["PATH"] = os.path.join(_base_dir(), "pio.db")
        if type_name == "localfs" and "PATH" not in props:
            props["PATH"] = os.path.join(_base_dir(), "models")
        return type_name, StorageClientConfig(properties=props)

    def client_for_source(self, source: str) -> BaseStorageClient:
        with self._lock:
            if source not in self._clients:
                type_name, config = self._source_config(source)
                if type_name not in _BACKENDS:
                    raise StorageError(
                        f"unknown storage type {type_name!r}"
                        f" (known: {sorted(_BACKENDS)})"
                    )
                module = importlib.import_module(_BACKENDS[type_name])
                self._clients[source] = module.StorageClient(config)
            return self._clients[source]

    def dao(self, repo_env: str, dao_name: str):
        return self.client_for_source(self._repo_source(repo_env)).get_dao(dao_name)

    def reset(self) -> None:
        with self._lock:
            for client in self._clients.values():
                try:
                    client.close()
                except Exception:
                    pass
            self._clients.clear()


_registry = _Registry()


# -- public accessors (parity: Storage.getLEvents()/getMetaDataApps()/...) ---

def get_l_events() -> LEvents:
    return _registry.dao("EVENTDATA", "events")


def get_meta_data_apps() -> Apps:
    return _registry.dao("METADATA", "apps")


def get_meta_data_channels() -> Channels:
    return _registry.dao("METADATA", "channels")


def get_meta_data_access_keys() -> AccessKeys:
    return _registry.dao("METADATA", "access_keys")


def get_meta_data_engine_instances() -> EngineInstances:
    return _registry.dao("METADATA", "engine_instances")


def get_meta_data_evaluation_instances() -> EvaluationInstances:
    return _registry.dao("METADATA", "evaluation_instances")


def get_model_data_models() -> Models:
    return _registry.dao("MODELDATA", "models")


def reset() -> None:
    """Close cached clients (tests; env changes take effect on next access)."""
    _registry.reset()


#: property keys safe to echo in `pio status` output; anything else
#: (passwords, tokens, connection strings) is redacted
_SAFE_PROPERTY_KEYS = {"PATH", "HOSTS", "PORTS", "HOST", "PORT", "SCHEMES", "INDEX"}


def config_summary() -> dict[str, dict[str, str]]:
    """Resolved repository->source->type mapping (for ``pio status``)."""
    out = {}
    for repo in _REPOS:
        source = _registry._repo_source(repo)
        type_name, cfg = _registry._source_config(source)
        out[repo] = {
            "source": source,
            "type": type_name,
            **{
                k.lower(): (v if k in _SAFE_PROPERTY_KEYS else "<redacted>")
                for k, v in cfg.properties.items()
            },
        }
    return out


def verify_all_data_objects() -> list[str]:
    """Touch every repository; return list of failures (for ``pio status``).

    Parity role of ``Storage.verifyAllDataObjects`` (SURVEY.md section 2.2 #6).
    """
    failures = []
    checks = [
        ("metadata apps", get_meta_data_apps),
        ("metadata channels", get_meta_data_channels),
        ("metadata access keys", get_meta_data_access_keys),
        ("metadata engine instances", get_meta_data_engine_instances),
        ("metadata evaluation instances", get_meta_data_evaluation_instances),
        ("model data", get_model_data_models),
        ("event data", get_l_events),
    ]
    for name, fn in checks:
        try:
            fn()
        except Exception as exc:
            failures.append(f"{name}: {exc}")
    return failures
