"""Event store facades: the API engine templates actually call.

Behavioral model: reference ``data/.../store/{LEventStore,PEventStore}.scala``
(apache/predictionio layout, unverified -- SURVEY.md section 2.2 #12).

- :class:`LEventStore`-style helpers: blocking, app-name-resolved queries for
  serving-time lookups (``find_by_entity``).
- :class:`PEventStore` analogue: where the reference returns ``RDD[Event]``,
  we return an :class:`EventDataset` -- an in-memory columnar batch
  (numpy arrays + string dictionaries) that feeds ``jax.device_put`` sharded
  per mesh axis. This is the host-side batched reader of the north star.
"""

from __future__ import annotations

import datetime as _dt
import logging
from dataclasses import dataclass
from typing import Iterator

import numpy as np

logger = logging.getLogger("pio.store")

from predictionio_tpu.data import storage as storage_registry
from predictionio_tpu.data.datamap import PropertyMap
from predictionio_tpu.data.event import Event


class AppNotFoundError(LookupError):
    pass


class ChannelNotFoundError(LookupError):
    pass


def resolve_app_channel(
    app_name: str, channel_name: str | None = None
) -> tuple[int, int | None]:
    """appName (+channel) -> (appId, channelId), as LEventStore/Common does."""
    apps = storage_registry.get_meta_data_apps()
    app = apps.get_by_name(app_name)
    if app is None:
        raise AppNotFoundError(f"app {app_name!r} not found")
    if channel_name is None:
        return app.id, None
    channels = storage_registry.get_meta_data_channels()
    for ch in channels.get_by_app(app.id):
        if ch.name == channel_name:
            return app.id, ch.id
    raise ChannelNotFoundError(f"channel {channel_name!r} not found in app {app_name!r}")


@dataclass
class EventDataset:
    """Columnar view of an event query result.

    String-valued columns are dictionary-encoded: ``entity_ids[i]`` indexes
    into ``entity_id_vocab``. Numeric columns are dense numpy arrays, ready
    to shard onto a device mesh. ``events`` retains the row objects for
    host-side logic that needs full fidelity (properties etc.) -- it is
    EMPTY when the dataset came through a backend's columnar fast scan
    (``from_columns``), which skips Event construction entirely.
    """

    events: list[Event]
    entity_id_vocab: list[str]
    target_entity_id_vocab: list[str]
    event_name_vocab: list[str]
    entity_ids: np.ndarray        # int32 [n]
    target_entity_ids: np.ndarray # int32 [n], -1 when absent
    event_names: np.ndarray       # int32 [n]
    event_times: np.ndarray       # float64 [n], epoch seconds
    ratings: np.ndarray           # float32 [n], properties["rating"] or NaN

    def __len__(self) -> int:
        return int(self.entity_ids.size)

    @classmethod
    def from_events(cls, events: list[Event], rating_key: str = "rating") -> "EventDataset":
        ent_vocab: dict[str, int] = {}
        tgt_vocab: dict[str, int] = {}
        name_vocab: dict[str, int] = {}
        n = len(events)
        ent = np.empty(n, dtype=np.int32)
        tgt = np.full(n, -1, dtype=np.int32)
        names = np.empty(n, dtype=np.int32)
        times = np.empty(n, dtype=np.float64)
        ratings = np.full(n, np.nan, dtype=np.float32)
        for i, ev in enumerate(events):
            ent[i] = ent_vocab.setdefault(ev.entity_id, len(ent_vocab))
            if ev.target_entity_id is not None:
                tgt[i] = tgt_vocab.setdefault(ev.target_entity_id, len(tgt_vocab))
            names[i] = name_vocab.setdefault(ev.event, len(name_vocab))
            times[i] = ev.event_time.timestamp()
            r = ev.properties.get_opt(rating_key)
            if isinstance(r, (int, float)) and not isinstance(r, bool):
                ratings[i] = float(r)
        return cls(
            events=events,
            entity_id_vocab=list(ent_vocab),
            target_entity_id_vocab=list(tgt_vocab),
            event_name_vocab=list(name_vocab),
            entity_ids=ent,
            target_entity_ids=tgt,
            event_names=names,
            event_times=times,
            ratings=ratings,
        )

    @classmethod
    def from_columns(
        cls, entity_ids, target_entity_ids, event_names, event_times_iso, ratings_raw
    ) -> "EventDataset":
        """Build from a backend columnar scan (``scan_interactions``) --
        no Event objects, no per-row JSON parse. Matches ``from_events``
        output exactly: first-appearance vocabulary order (None targets ->
        the -1 sentinel), microsecond-precision timestamps from the stored
        ISO strings, and ratings pre-filtered to JSON numbers by the
        backend. pandas accelerates the encoding when present (it is not a
        declared dependency); pure-python fallbacks match it bit-for-bit.
        """
        try:
            import pandas as pd
        except ImportError:
            pd = None

        def encode(values) -> tuple[np.ndarray, list[str]]:
            if pd is not None:
                codes, vocab = pd.factorize(np.asarray(values, dtype=object))
                return codes.astype(np.int32), [str(v) for v in vocab]
            vocab_map: dict[str, int] = {}
            codes = np.empty(len(values), dtype=np.int32)
            for i, v in enumerate(values):
                codes[i] = (
                    -1 if v is None else vocab_map.setdefault(v, len(vocab_map))
                )
            return codes, list(vocab_map)

        ent, ent_vocab = encode(entity_ids)
        tgt, tgt_vocab = encode(target_entity_ids)
        names, name_vocab = encode(event_names)

        n = len(entity_ids)
        times = None
        if pd is not None:
            try:
                # as_unit("ns"): pandas 2 may parse into us/ms resolution,
                # and asi8 reports in whatever unit the index landed in.
                # format="ISO8601" and as_unit are pandas>=2 API -- any
                # older-pandas failure drops to the stdlib loop below
                times = (
                    pd.DatetimeIndex(
                        pd.to_datetime(event_times_iso, utc=True, format="ISO8601")
                    )
                    .as_unit("ns")
                    .asi8
                    / 1e9
                )
            except Exception:
                times = None
        if times is None:
            times = np.fromiter(
                (_dt.datetime.fromisoformat(s).timestamp() for s in event_times_iso),
                dtype=np.float64,
                count=n,
            )

        def to_float(v) -> float:
            if v is None:
                return np.nan
            try:
                return float(v)  # drivers may hand numbers back as str/Decimal
            except (TypeError, ValueError):
                return np.nan

        ratings = np.fromiter(
            (to_float(v) for v in ratings_raw), dtype=np.float32, count=n
        )
        return cls(
            events=[],
            entity_id_vocab=ent_vocab,
            target_entity_id_vocab=tgt_vocab,
            event_name_vocab=name_vocab,
            entity_ids=ent,
            target_entity_ids=tgt,
            event_names=names,
            event_times=np.asarray(times, np.float64),
            ratings=ratings,
        )

    @classmethod
    def from_snapshot(cls, snapshot) -> "EventDataset":
        """Build from a columnar training snapshot (``data/snapshot``) --
        zero SQL, zero parsing: the snapshot already holds exactly this
        class's encoding (full-stream first-appearance vocabularies, -1
        sentinel targets, float64 epoch times, NaN-for-absent ratings).
        Columns are copied out of the memmaps so the dataset outlives the
        snapshot files (a later refresh GCs old generations).
        """
        return cls(
            events=[],
            entity_id_vocab=list(snapshot.vocab("users")),
            target_entity_id_vocab=list(snapshot.vocab("items")),
            event_name_vocab=list(snapshot.vocab("names")),
            entity_ids=np.asarray(snapshot.column("users")).astype(np.int32),
            target_entity_ids=np.asarray(snapshot.column("items")).astype(
                np.int32
            ),
            event_names=np.array(snapshot.column("names"), np.int32),
            event_times=np.array(snapshot.column("times"), np.float64),
            ratings=np.asarray(snapshot.column("ratings")).astype(np.float32),
        )


class LEventStore:
    """Blocking serving-time event reads, resolved by app name."""

    @staticmethod
    def find(
        app_name: str,
        entity_type: str | None = None,
        entity_id: str | None = None,
        channel_name: str | None = None,
        event_names: list[str] | None = None,
        target_entity_type=...,
        target_entity_id=...,
        start_time: _dt.datetime | None = None,
        until_time: _dt.datetime | None = None,
        limit: int | None = None,
        latest: bool = True,
    ) -> Iterator[Event]:
        app_id, channel_id = resolve_app_channel(app_name, channel_name)
        return storage_registry.get_l_events().find(
            app_id=app_id,
            channel_id=channel_id,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            entity_id=entity_id,
            event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id,
            limit=limit,
            reversed=latest,
        )

    @staticmethod
    def find_by_entity(
        app_name: str,
        entity_type: str,
        entity_id: str,
        channel_name: str | None = None,
        **kwargs,
    ) -> Iterator[Event]:
        return LEventStore.find(
            app_name,
            entity_type=entity_type,
            entity_id=entity_id,
            channel_name=channel_name,
            **kwargs,
        )


class PEventStore:
    """Training-time bulk reads -> columnar EventDataset (RDD replacement)."""

    @staticmethod
    def find(
        app_name: str,
        channel_name: str | None = None,
        start_time: _dt.datetime | None = None,
        until_time: _dt.datetime | None = None,
        entity_type: str | None = None,
        entity_id: str | None = None,
        event_names: list[str] | None = None,
        target_entity_type=...,
        target_entity_id=...,
    ) -> list[Event]:
        app_id, channel_id = resolve_app_channel(app_name, channel_name)
        return list(
            storage_registry.get_l_events().find(
                app_id=app_id,
                channel_id=channel_id,
                start_time=start_time,
                until_time=until_time,
                entity_type=entity_type,
                entity_id=entity_id,
                event_names=event_names,
                target_entity_type=target_entity_type,
                target_entity_id=target_entity_id,
            )
        )

    #: dataset() filters the columnar fast scan understands; anything else
    #: (entity filters, exotic target matching) falls back to the row path
    _FAST_SCAN_FILTERS = frozenset(
        {"event_names", "target_entity_type", "start_time", "until_time"}
    )

    #: dataset() filters a training snapshot can key on (time filters are
    #: excluded: a snapshot's coverage boundary is its own until bound)
    _SNAPSHOT_FILTERS = frozenset({"event_names", "target_entity_type"})

    @staticmethod
    def dataset(
        app_name: str,
        rating_key: str = "rating",
        channel_name: str | None = None,
        snapshot_mode: str | None = None,
        snapshot_dir: str | None = None,
        **kwargs,
    ) -> EventDataset:
        """Columnar training read. With snapshots enabled (explicit args,
        ``pio.snapshot_*`` runtime conf via ``pio train``, or the
        ``PIO_SNAPSHOT_MODE``/``PIO_SNAPSHOT_DIR`` env), a compatible
        query is served from the on-disk training snapshot: ``use`` mode
        replays the existing spill as-is (bounded at ITS time coverage --
        stale-but-fast by contract), ``refresh`` first appends the events
        since. Everything else falls through to the live scan paths.
        """
        le = storage_registry.get_l_events()
        ds = PEventStore._dataset_from_snapshot(
            le, app_name, rating_key, channel_name,
            snapshot_mode, snapshot_dir, kwargs,
        )
        if ds is not None:
            return ds
        if (
            hasattr(le, "scan_interactions")
            and set(kwargs) <= PEventStore._FAST_SCAN_FILTERS
        ):
            app_id, channel_id = resolve_app_channel(app_name, channel_name)
            try:
                return EventDataset.from_columns(
                    *le.scan_interactions(
                        app_id, channel_id, rating_key=rating_key, **kwargs
                    )
                )
            except Exception:
                # e.g. a stored properties blob the DB's JSON functions
                # reject (python's json accepts NaN, SQL JSON does not):
                # the row path parses it fine, so degrade instead of
                # failing training for the whole app
                logger.warning(
                    "columnar fast scan failed for app %r; falling back to"
                    " the row path",
                    app_name,
                    exc_info=True,
                )
        return EventDataset.from_events(
            PEventStore.find(app_name, channel_name=channel_name, **kwargs),
            rating_key=rating_key,
        )

    @staticmethod
    def _dataset_from_snapshot(
        le, app_name, rating_key, channel_name, snapshot_mode, snapshot_dir,
        kwargs,
    ) -> EventDataset | None:
        """The snapshot-served fast path of :meth:`dataset`, or None when
        snapshots are off / the query or backend is incompatible / the
        snapshot layer fails (training must degrade to the scan)."""
        from predictionio_tpu.data.snapshot import (
            SnapshotSpec,
            SnapshotStore,
            snapshot_settings,
        )

        mode, root = snapshot_settings(
            mode=snapshot_mode, snapshot_dir=snapshot_dir
        )
        if mode == "off" or not set(kwargs) <= PEventStore._SNAPSHOT_FILTERS:
            return None
        if not hasattr(le, "iter_interaction_chunks"):
            return None
        try:
            app_id, channel_id = resolve_app_channel(app_name, channel_name)
            event_names = kwargs.get("event_names")
            spec = SnapshotSpec(
                app_id=app_id,
                channel_id=channel_id,
                event_names=tuple(event_names) if event_names else None,
                rating_key=rating_key,
                target_entity_type=kwargs.get("target_entity_type", ...),
            )
            snap = SnapshotStore(root, spec).ensure(le, mode)
            if snap is None:
                return None
            return EventDataset.from_snapshot(snap)
        except Exception:
            logger.warning(
                "snapshot-served dataset failed for app %r; falling back to"
                " the live scan",
                app_name,
                exc_info=True,
            )
            return None

    @staticmethod
    def aggregate_properties(
        app_name: str,
        entity_type: str,
        channel_name: str | None = None,
        start_time: _dt.datetime | None = None,
        until_time: _dt.datetime | None = None,
        required: list[str] | None = None,
    ) -> dict[str, PropertyMap]:
        app_id, channel_id = resolve_app_channel(app_name, channel_name)
        return storage_registry.get_l_events().aggregate_properties(
            app_id=app_id,
            entity_type=entity_type,
            channel_id=channel_id,
            start_time=start_time,
            until_time=until_time,
            required=required,
        )
