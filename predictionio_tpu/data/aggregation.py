"""Entity-property aggregation: fold ``$set/$unset/$delete`` streams.

Behavioral model: reference ``data/.../storage/LEventAggregator.scala``
(apache/predictionio layout, unverified -- SURVEY.md section 2.2 #5):

- events are folded in ``event_time`` order per entity;
- ``$set`` merges the event's properties over the current map;
- ``$unset`` removes the named keys;
- ``$delete`` clears the entity entirely (a later ``$set`` re-creates it);
- ``first_updated`` / ``last_updated`` track the surviving window -- a
  ``$delete`` resets ``first_updated`` to the next mutation's time;
- an entity whose final state is deleted (or never set) yields no entry.
"""

from __future__ import annotations

from typing import Iterable, Optional

from predictionio_tpu.data.datamap import DataMap, PropertyMap
from predictionio_tpu.data.event import (
    DELETE_EVENT,
    SET_EVENT,
    SPECIAL_EVENTS,
    UNSET_EVENT,
    Event,
)


def aggregate_entity(events: Iterable[Event]) -> Optional[PropertyMap]:
    """Fold one entity's special events into its current PropertyMap.

    ``events`` may arrive in any order; they are sorted by
    ``(event_time, creation_time)`` before folding. Returns ``None`` if the
    entity ends up deleted or was never ``$set``.
    """
    ordered = sorted(events, key=lambda e: (e.event_time, e.creation_time))
    props: DataMap | None = None
    first = last = None
    for ev in ordered:
        if ev.event not in SPECIAL_EVENTS:
            continue
        if ev.event == SET_EVENT:
            props = (props or DataMap()).updated(ev.properties)
        elif ev.event == UNSET_EVENT:
            if props is None:
                continue
            props = props.removed(ev.properties.keys())
        elif ev.event == DELETE_EVENT:
            props = None
            first = last = None
            continue
        if first is None:
            first = ev.event_time
        last = ev.event_time
    if props is None or first is None:
        return None
    return PropertyMap(props.to_dict(), first_updated=first, last_updated=last)


def aggregate_properties(events: Iterable[Event]) -> dict[str, PropertyMap]:
    """Group special events by entity_id and fold each (one entity_type).

    Mirrors the contract of ``LEvents.aggregateProperties`` /
    ``PEventStore.aggregateProperties`` (SURVEY.md section 2.2 #7/#12): the
    caller has already filtered to a single ``entity_type``.
    """
    by_entity: dict[str, list[Event]] = {}
    for ev in events:
        if ev.event in SPECIAL_EVENTS:
            by_entity.setdefault(ev.entity_id, []).append(ev)
    out: dict[str, PropertyMap] = {}
    for entity_id, evs in by_entity.items():
        pm = aggregate_entity(evs)
        if pm is not None:
            out[entity_id] = pm
    return out
