"""DataMap / PropertyMap: typed JSON-object wrappers attached to events.

Behavioral model: reference ``data/.../storage/DataMap.scala`` and
``PropertyMap.scala`` (apache/predictionio layout, unverified -- SURVEY.md
section 2.2 #4/#5). A DataMap wraps the ``properties`` JSON object of an
event and offers typed getters; a PropertyMap is an aggregated DataMap plus
``firstUpdated`` / ``lastUpdated`` timestamps.
"""

from __future__ import annotations

import datetime as _dt
from typing import Any, Iterator, Mapping


class DataMapError(KeyError):
    """Raised when a required field is missing or has the wrong type."""


def _check_type(name: str, value: Any, expected: type | tuple[type, ...]) -> Any:
    # bool is an int subclass in Python; only accept it when bool is expected.
    if expected in (int, float, (int, float)) and isinstance(value, bool):
        raise DataMapError(f"field {name!r} has type bool, expected {expected}")
    if isinstance(value, expected):
        return value
    # JSON has one number type; allow int where float is asked for.
    if expected is float and isinstance(value, int):
        return float(value)
    raise DataMapError(
        f"field {name!r} has type {type(value).__name__}, expected {expected}"
    )


class DataMap(Mapping[str, Any]):
    """Immutable mapping over an event's ``properties`` JSON object."""

    __slots__ = ("_fields",)

    def __init__(self, fields: Mapping[str, Any] | None = None):
        self._fields: dict[str, Any] = dict(fields or {})

    # -- Mapping protocol ---------------------------------------------------
    def __getitem__(self, key: str) -> Any:
        try:
            return self._fields[key]
        except KeyError:
            raise DataMapError(f"required field {key!r} not found") from None

    def __iter__(self) -> Iterator[str]:
        return iter(self._fields)

    def __len__(self) -> int:
        return len(self._fields)

    def __contains__(self, key: object) -> bool:
        return key in self._fields

    def __eq__(self, other: object) -> bool:
        if isinstance(other, DataMap):
            return self._fields == other._fields
        if isinstance(other, Mapping):
            return self._fields == dict(other)
        return NotImplemented

    def __hash__(self) -> int:
        # Event is a frozen dataclass whose generated __hash__ hashes this
        # field; values may be unhashable JSON (lists/objects), so hash a
        # canonical dump instead.
        import json

        return hash(json.dumps(self._fields, sort_keys=True, default=str))

    def __repr__(self) -> str:
        return f"DataMap({self._fields!r})"

    # -- typed getters (reference: DataMap.get[T]/getOpt[T]) ----------------
    def get_string(self, name: str) -> str:
        return _check_type(name, self[name], str)

    def get_int(self, name: str) -> int:
        return _check_type(name, self[name], int)

    def get_double(self, name: str) -> float:
        return _check_type(name, self[name], float)

    def get_boolean(self, name: str) -> bool:
        return _check_type(name, self[name], bool)

    def get_list(self, name: str) -> list:
        # copy so callers cannot mutate the map through the returned list
        return list(_check_type(name, self[name], list))

    def get_string_list(self, name: str) -> list[str]:
        val = self.get_list(name)
        for i, item in enumerate(val):
            _check_type(f"{name}[{i}]", item, str)
        return val

    def get_double_list(self, name: str) -> list[float]:
        val = self.get_list(name)
        return [_check_type(f"{name}[{i}]", v, float) for i, v in enumerate(val)]

    def get_opt(self, name: str, default: Any = None) -> Any:
        return self._fields.get(name, default)

    # -- functional updates (used by the $set/$unset fold) ------------------
    def updated(self, other: "DataMap | Mapping[str, Any]") -> "DataMap":
        merged = dict(self._fields)
        merged.update(dict(other))
        return DataMap(merged)

    def removed(self, keys) -> "DataMap":
        drop = set(keys)
        return DataMap({k: v for k, v in self._fields.items() if k not in drop})

    def to_dict(self) -> dict[str, Any]:
        return dict(self._fields)


class PropertyMap(DataMap):
    """Aggregated entity properties with first/last update times.

    Produced by folding an entity's ``$set/$unset/$delete`` event stream
    (reference ``LEventAggregator.scala`` behavior, SURVEY.md section 2.2 #5).
    """

    __slots__ = ("first_updated", "last_updated")

    def __init__(
        self,
        fields: Mapping[str, Any] | None,
        first_updated: _dt.datetime,
        last_updated: _dt.datetime,
    ):
        super().__init__(fields)
        self.first_updated = first_updated
        self.last_updated = last_updated

    def __repr__(self) -> str:
        return (
            f"PropertyMap({self.to_dict()!r}, "
            f"first_updated={self.first_updated.isoformat()}, "
            f"last_updated={self.last_updated.isoformat()})"
        )
