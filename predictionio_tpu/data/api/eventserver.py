"""Event Server: REST ingestion into the append-only event store.

Behavioral model: reference ``data/.../api/EventServer.scala`` (apache/
predictionio layout, unverified -- SURVEY.md section 2.2 #15 and Appendix A).
Wire contract kept:

- ``POST /events.json?accessKey=K[&channel=ch]`` -> ``201 {"eventId": ...}``
- ``GET  /events.json`` with filters (startTime/untilTime/entityType/entityId/
  event/targetEntityType/targetEntityId/limit/reversed)
- ``GET|DELETE /events/<id>.json``
- ``POST /batch/events.json`` (<=50 per request, per-item status array)
- ``GET  /stats.json`` (when ``--stats``)
- ``POST /webhooks/<connector>.json`` (+ form variant), ``GET`` for status
- auth via ``accessKey`` query param or ``Authorization`` header; per-key
  event whitelists; channels resolved by name
- plugin hook points: input blockers / input sniffers
  (``EventServerPlugin`` parity role)

Default port 7070.
"""

from __future__ import annotations

import json
import logging
import math
import threading
import time
from concurrent.futures import TimeoutError as _FutureTimeout
from dataclasses import dataclass, field
from typing import Any

from predictionio_tpu.data import storage as storage_registry
from predictionio_tpu.data.event import (
    Event,
    EventValidationError,
    parse_event_time,
)
from predictionio_tpu.data.ingest import (
    IngestConfig,
    IngestOverload,
    IngestPipeline,
    PartitionedIngestPipeline,
    replay_partitioned_wal,
)
from predictionio_tpu.data.storage.base import AccessKey
from predictionio_tpu.data.wal import PartitionedWal
from predictionio_tpu.data import webhooks as webhook_registry
from predictionio_tpu.utils.http import (
    Request,
    Response,
    ServiceThread,
    instrumented_router,
    make_server,
)

DEFAULT_PORT = 7070

#: how long a request thread waits for its group-commit ack before giving up
#: with a 503 (a stalled storage backend must not hold sockets forever)
ACK_TIMEOUT_S = 30.0


class EventServerPlugin:
    """Hook points mirroring the reference's EventServerPlugin contract.

    ``input_blocker`` may raise :class:`PluginRejection` to reject an event;
    ``input_sniffer`` observes accepted events.
    """

    def input_blocker(self, event: Event, app_id: int, channel_id: int | None) -> None:
        pass

    def input_sniffer(self, event: Event, app_id: int, channel_id: int | None) -> None:
        pass


class PluginRejection(Exception):
    def __init__(self, message: str, status: int = 403):
        super().__init__(message)
        self.status = status


@dataclass
class _Stats:
    """Per-app event counters since server start (reference Stats actor)."""

    start_time: float = field(default_factory=time.time)
    lock: threading.Lock = field(default_factory=threading.Lock)
    # (app_id, event_name, status) -> count
    counts: dict[tuple[int, str, int], int] = field(default_factory=dict)

    def record(self, app_id: int, event_name: str, status: int) -> None:
        with self.lock:
            key = (app_id, event_name, status)
            self.counts[key] = self.counts.get(key, 0) + 1

    def to_json(self) -> dict[str, Any]:
        with self.lock:
            per_app: dict[int, list[dict[str, Any]]] = {}
            for (app_id, name, status), count in sorted(self.counts.items()):
                per_app.setdefault(app_id, []).append(
                    {"event": name, "status": status, "count": count}
                )
        return {
            "uptime": time.time() - self.start_time,
            "appStatistics": [
                {"appId": app_id, "events": events}
                for app_id, events in per_app.items()
            ],
        }


class EventService:
    """Route handlers bound to the storage registry; server-framework free."""

    def __init__(
        self,
        stats: bool = False,
        plugins: list[EventServerPlugin] | None = None,
        ingest_config: IngestConfig | None = None,
        tracing: bool | None = None,
        trace_sample: float | None = None,
        slow_commit_ms: float | None = None,
        extra_metrics_snapshots=None,
    ):
        self.stats_enabled = stats
        self.stats = _Stats()
        self.plugins = list(plugins or [])
        self.ingest: PartitionedIngestPipeline | IngestPipeline | None = None
        self._wal: PartitionedWal | None = None
        self.router, self.metrics = instrumented_router(
            before_scrape=self._before_scrape, tracing=tracing,
            trace_sample=trace_sample,
            extra_snapshots=extra_metrics_snapshots,
        )
        if slow_commit_ms is not None:
            # one summary line per group commit over the threshold
            self.router.tracer.set_slow_threshold(
                "ingest.commit", slow_commit_ms / 1000.0
            )
        if ingest_config is not None and ingest_config.mode == "wal":
            self._start_ingest(ingest_config)
        r = self.router
        r.add("GET", "/", self.handle_root)
        r.add("POST", "/events.json", self.handle_create_event)
        r.add("GET", "/events.json", self.handle_find_events)
        r.add("GET", "/events/<event_id>.json", self.handle_get_event)
        r.add("DELETE", "/events/<event_id>.json", self.handle_delete_event)
        r.add("POST", "/batch/events.json", self.handle_batch)
        r.add("GET", "/stats.json", self.handle_stats)
        r.add("POST", "/webhooks/<connector>.json", self.handle_webhook_post)
        r.add("GET", "/webhooks/<connector>.json", self.handle_webhook_get)

    # -- ingest pipeline lifecycle ------------------------------------------
    def _start_ingest(self, config: IngestConfig) -> None:
        """WAL + group-commit mode: replay the un-flushed tail left by a
        previous crash (exactly-once PER PARTITION -- each stream has its
        own checkpoint), then start the partition writers. P=1 opens the
        flat single-log layout, so upgrades replay old logs unchanged."""
        self._wal = PartitionedWal(
            config.resolved_wal_dir(),
            partitions=config.wal_partitions,
            segment_bytes=config.segment_bytes,
            fsync_policy=config.fsync_policy,
        )
        replayed = replay_partitioned_wal(
            self._wal, tracer=self.router.tracer
        )
        if replayed:
            logging.getLogger("pio.ingest").warning(
                "replayed %d WAL record(s) into the event store", replayed
            )
        self.ingest = PartitionedIngestPipeline(
            self._wal,
            queue_size=config.queue_size,
            group_commit_ms=config.group_commit_ms,
            max_batch=config.max_batch,
            metrics=self.metrics,
            tracer=self.router.tracer,
        ).start()

    def shutdown_ingest(self) -> None:
        """Drain the queue (every accepted event reaches the WAL + store)
        and close the WAL. Safe to call in sync mode or twice.

        ``self.ingest`` deliberately stays set: handler threads can still be
        mid-request after the listener closes (daemon handler threads), and a
        stopped pipeline answers their submits with IngestOverload -> 429
        rather than an attribute race."""
        if self.ingest is not None:
            self.ingest.stop(drain=True)
        if self._wal is not None:
            self._wal.close()
            self._wal = None

    def _before_scrape(self, registry) -> None:
        ingest = self.ingest
        if ingest is not None:
            registry.set_gauge(
                "pio_ingest_queue_depth",
                float(ingest.depth()),
                help="Events parked in the ingest queue awaiting group commit",
            )
            partitions = getattr(ingest, "partitions", 1)
            registry.set_gauge(
                "pio_ingest_partitions",
                float(partitions),
                help="WAL partition count (hash-sharded durability streams)",
            )
            if hasattr(ingest, "depth_of"):
                for k in range(partitions):
                    registry.set_gauge(
                        "pio_ingest_partition_depth",
                        float(ingest.depth_of(k)),
                        labels={"part": str(k)},
                        help="Events parked per WAL partition awaiting"
                        " group commit",
                    )
        wal = self._wal
        if wal is not None:
            registry.set_counter(
                "pio_wal_appends_total", float(wal.append_count),
                help="Records framed into the WAL",
            )
            registry.set_counter(
                "pio_wal_fsyncs_total", float(wal.fsync_count),
                help="WAL fsync calls (one per group commit under policy"
                " 'always')",
            )
            registry.set_gauge(
                "pio_wal_last_fsync_seconds", wal.last_fsync_s,
                help="Duration of the most recent WAL fsync",
            )

    # -- auth ---------------------------------------------------------------
    def _access_key(self, request: Request) -> str | None:
        if "accessKey" in request.query:
            return request.query["accessKey"]
        auth = request.headers.get("Authorization", "")
        # SDKs send the key as the basic-auth username with empty password
        if auth.startswith("Basic "):
            import base64

            try:
                decoded = base64.b64decode(auth[6:]).decode("utf-8")
                return decoded.split(":", 1)[0]
            except Exception:
                return None
        if auth.startswith("Bearer "):
            return auth[7:]
        return None

    def _authorize(self, request: Request) -> tuple[AccessKey, int | None]:
        """Return (access key record, channel id) or raise _AuthError."""
        key = self._access_key(request)
        if not key:
            raise _AuthError(401, "missing accessKey")
        record = storage_registry.get_meta_data_access_keys().get(key)
        if record is None:
            raise _AuthError(401, "invalid accessKey")
        channel_id = None
        channel_name = request.query.get("channel")
        if channel_name:
            channels = storage_registry.get_meta_data_channels().get_by_app(
                record.app_id
            )
            match = [c for c in channels if c.name == channel_name]
            if not match:
                raise _AuthError(400, f"invalid channel {channel_name!r}")
            channel_id = match[0].id
        return record, channel_id

    def _check_event_allowed(self, record: AccessKey, event_name: str) -> None:
        if record.events and event_name not in record.events:
            raise _AuthError(
                403, f"accessKey is not allowed to write event {event_name!r}"
            )

    # -- handlers -----------------------------------------------------------
    def handle_root(self, request: Request) -> Response:
        return Response(200, {"status": "alive"})

    def _prepare(
        self, obj: Any, record: AccessKey, channel_id: int | None
    ) -> Event | tuple[int, dict[str, Any]]:
        """Validate + authorize + run input blockers on the request thread;
        returns the Event, or the (status, body) rejection."""
        try:
            with self.router.tracer.span("ingest.parse"):
                return self._prepare_inner(obj, record, channel_id)
        except EventValidationError as exc:
            if self.stats_enabled:
                name = obj.get("event", "<invalid>") if isinstance(obj, dict) else "<invalid>"
                self.stats.record(record.app_id, str(name), 400)
            return 400, {"message": str(exc)}
        except _AuthError as exc:
            # whitelist denial: surface in /stats.json like any other outcome
            if self.stats_enabled and isinstance(obj, dict):
                self.stats.record(record.app_id, str(obj.get("event")), exc.status)
            return exc.status, {"message": str(exc)}
        except PluginRejection as exc:
            if self.stats_enabled and isinstance(obj, dict):
                self.stats.record(record.app_id, str(obj.get("event")), exc.status)
            return exc.status, {"message": str(exc)}

    def _prepare_inner(
        self, obj: Any, record: AccessKey, channel_id: int | None
    ) -> Event:
        if isinstance(obj, dict):
            # creationTime is server-assigned on the ingest path; a client
            # (unlike pio import) may not spoof it
            obj = {k: v for k, v in obj.items() if k != "creationTime"}
        event = Event.from_json_obj(obj)
        self._check_event_allowed(record, event.event)
        for plugin in self.plugins:
            plugin.input_blocker(event, record.app_id, channel_id)
        return event

    def _ack(
        self, event: Event, record: AccessKey, channel_id: int | None, event_id: str
    ) -> tuple[int, dict[str, Any]]:
        for plugin in self.plugins:
            plugin.input_sniffer(event, record.app_id, channel_id)
        if self.stats_enabled:
            self.stats.record(record.app_id, event.event, 201)
        self.metrics.inc(
            "pio_events_ingested_total",
            {"app_id": str(record.app_id)},
            help="Events accepted into the event store",
        )
        return 201, {"eventId": event_id}

    def _insert_prepared(
        self, events: list[Event], record: AccessKey, channel_id: int | None
    ) -> list[tuple[int, dict[str, Any]]]:
        """Commit already-validated events. Sync mode: one storage insert
        per event on the request thread (the pre-pipeline behavior). WAL
        mode: submit ALL of them before waiting, so a batch request rides a
        single group commit; a full queue yields per-item 429s."""
        if self.ingest is None:
            out = []
            for ev in events:
                with self.router.tracer.span("storage.insert"):
                    event_id = storage_registry.get_l_events().insert(
                        ev, record.app_id, channel_id
                    )
                out.append(self._ack(ev, record, channel_id, event_id))
            return out
        submitted: list[Any] = []
        for ev in events:
            try:
                submitted.append(self.ingest.submit(ev, record.app_id, channel_id))
            except IngestOverload as exc:
                submitted.append(exc)
        results = []
        # one shared deadline for the whole request: a stalled pipeline must
        # bound the socket hold at ACK_TIMEOUT_S total, not per item
        deadline = time.monotonic() + ACK_TIMEOUT_S
        for ev, fut in zip(events, submitted):
            if isinstance(fut, IngestOverload):
                results.append(
                    (429, {"message": "ingestion queue full, retry later"})
                )
                continue
            try:
                event_id = fut.result(
                    timeout=max(0.0, deadline - time.monotonic())
                )
            except _FutureTimeout:
                results.append(
                    (503, {"message": "ingestion pipeline stalled, retry later"})
                )
                continue
            except IngestOverload:
                results.append(
                    (429, {"message": "ingestion queue full, retry later"})
                )
                continue
            except Exception as exc:
                results.append(
                    (500, {"message": f"ingestion failed: {exc}"})
                )
                continue
            results.append(self._ack(ev, record, channel_id, event_id))
        return results

    def _insert_one(
        self, obj: Any, record: AccessKey, channel_id: int | None
    ) -> tuple[int, dict[str, Any]]:
        prepared = self._prepare(obj, record, channel_id)
        if not isinstance(prepared, Event):
            return prepared
        return self._insert_prepared([prepared], record, channel_id)[0]

    def _retry_after_headers(self, status: int) -> dict[str, str]:
        if status != 429 or self.ingest is None:
            return {}
        return {"Retry-After": str(max(1, math.ceil(self.ingest.retry_after_s)))}

    def handle_create_event(self, request: Request) -> Response:
        try:
            record, channel_id = self._authorize(request)
        except _AuthError as exc:
            return Response(exc.status, {"message": str(exc)})
        try:
            obj = request.json()
        except json.JSONDecodeError:
            return Response(400, {"message": "malformed JSON body"})
        status, body = self._insert_one(obj, record, channel_id)
        return Response(status, body, headers=self._retry_after_headers(status))

    def handle_batch(self, request: Request) -> Response:
        try:
            record, channel_id = self._authorize(request)
        except _AuthError as exc:
            return Response(exc.status, {"message": str(exc)})
        try:
            objs = request.json()
        except json.JSONDecodeError:
            return Response(400, {"message": "malformed JSON body"})
        if not isinstance(objs, list):
            return Response(400, {"message": "request body must be a JSON array"})
        if len(objs) > 50:
            return Response(
                400, {"message": "batch size must be <= 50 events per request"}
            )
        # two-phase so the whole request rides one group commit in WAL mode:
        # prepare (reject invalid items individually), submit the valid ones
        # together, then stitch per-item statuses back in request order
        prepared: list[Event | tuple[int, dict[str, Any]]] = [
            self._prepare(obj, record, channel_id) for obj in objs
        ]
        valid = [p for p in prepared if isinstance(p, Event)]
        committed = iter(self._insert_prepared(valid, record, channel_id))
        results = []
        for p in prepared:
            status, body = next(committed) if isinstance(p, Event) else p
            results.append({"status": status, **body})
        return Response(200, results)

    def handle_get_event(self, request: Request) -> Response:
        try:
            record, channel_id = self._authorize(request)
        except _AuthError as exc:
            return Response(exc.status, {"message": str(exc)})
        event = storage_registry.get_l_events().get(
            request.path_params["event_id"], record.app_id, channel_id
        )
        if event is None:
            return Response(404, {"message": "event not found"})
        return Response(200, event.to_json_obj())

    def handle_delete_event(self, request: Request) -> Response:
        try:
            record, channel_id = self._authorize(request)
        except _AuthError as exc:
            return Response(exc.status, {"message": str(exc)})
        found = storage_registry.get_l_events().delete(
            request.path_params["event_id"], record.app_id, channel_id
        )
        if not found:
            return Response(404, {"message": "event not found"})
        return Response(200, {"message": "deleted"})

    def handle_find_events(self, request: Request) -> Response:
        try:
            record, channel_id = self._authorize(request)
        except _AuthError as exc:
            return Response(exc.status, {"message": str(exc)})
        q = request.query
        try:
            start_time = parse_event_time(q["startTime"]) if "startTime" in q else None
            until_time = parse_event_time(q["untilTime"]) if "untilTime" in q else None
        except EventValidationError as exc:
            return Response(400, {"message": str(exc)})
        limit = None
        if "limit" in q:
            try:
                limit = int(q["limit"])
            except ValueError:
                return Response(400, {"message": "limit must be an integer"})
            if limit < -1:
                return Response(
                    400, {"message": "limit must be -1 (unlimited) or >= 0"}
                )
        event_names = q["event"].split(",") if "event" in q else None
        kwargs: dict[str, Any] = {}
        if "targetEntityType" in q:
            kwargs["target_entity_type"] = q["targetEntityType"]
        if "targetEntityId" in q:
            kwargs["target_entity_id"] = q["targetEntityId"]
        events = storage_registry.get_l_events().find(
            app_id=record.app_id,
            channel_id=channel_id,
            start_time=start_time,
            until_time=until_time,
            entity_type=q.get("entityType"),
            entity_id=q.get("entityId"),
            event_names=event_names,
            # upstream parity: limit=-1 means unlimited (None to the DAO);
            # absent means the default page of 20
            limit=20 if limit is None else (None if limit == -1 else limit),
            reversed=q.get("reversed", "false").lower() == "true",
            **kwargs,
        )
        return Response(200, [e.to_json_obj() for e in events])

    def handle_stats(self, request: Request) -> Response:
        if not self.stats_enabled:
            return Response(
                404, {"message": "stats not enabled (start server with --stats)"}
            )
        return Response(200, self.stats.to_json())

    # -- webhooks -----------------------------------------------------------
    def handle_webhook_post(self, request: Request) -> Response:
        try:
            record, channel_id = self._authorize(request)
        except _AuthError as exc:
            return Response(exc.status, {"message": str(exc)})
        name = request.path_params["connector"]
        content_type = request.headers.get("Content-Type", "")
        try:
            if "application/x-www-form-urlencoded" in content_type:
                connector = webhook_registry.FORM_CONNECTORS.get(name)
                if connector is None:
                    return Response(404, {"message": f"unknown form connector {name!r}"})
                event = connector.to_event(request.form())
            else:
                connector = webhook_registry.JSON_CONNECTORS.get(name)
                if connector is None:
                    return Response(404, {"message": f"unknown connector {name!r}"})
                payload = request.json()
                if not isinstance(payload, dict):
                    return Response(400, {"message": "webhook body must be a JSON object"})
                event = connector.to_event(payload)
        except webhook_registry.ConnectorError as exc:
            return Response(400, {"message": str(exc)})
        except json.JSONDecodeError:
            return Response(400, {"message": "malformed JSON body"})
        status, body = self._insert_one(event.to_json_obj(), record, channel_id)
        return Response(status, body, headers=self._retry_after_headers(status))

    def handle_webhook_get(self, request: Request) -> Response:
        name = request.path_params["connector"]
        known = name in webhook_registry.JSON_CONNECTORS or name in webhook_registry.FORM_CONNECTORS
        if not known:
            return Response(404, {"message": f"unknown connector {name!r}"})
        return Response(200, {"connector": name, "status": "ready"})


class _AuthError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status


def create_event_server(
    host: str = "0.0.0.0",
    port: int = DEFAULT_PORT,
    stats: bool = False,
    plugins: list[EventServerPlugin] | None = None,
    ingest_config: IngestConfig | None = None,
    tracing: bool | None = None,
    trace_sample: float | None = None,
    slow_commit_ms: float | None = None,
) -> ServiceThread:
    service = EventService(
        stats=stats, plugins=plugins, ingest_config=ingest_config,
        tracing=tracing, trace_sample=trace_sample,
        slow_commit_ms=slow_commit_ms,
    )
    server = make_server(service.router, host, port, "pio-eventserver")
    # drain the group-commit queue on stop: every acknowledged event reaches
    # the WAL and the store before the thread reports stopped
    return ServiceThread(server, on_stop=service.shutdown_ingest)


class MultiprocEventServerHandle:
    """Lifecycle wrapper for the multi-process event-server tier: M
    SO_REUSEPORT frontend workers (the PR-8 serving pattern, reused
    verbatim -- ``ScorerBridge`` is generic over any Router) feeding this
    process's ingest pipeline through the dispatcher pool. Defined here,
    NOT in ``workflow/create_server`` -- that module drags in the jax
    engine stack, which an event server must never import."""

    def __init__(self, bridge, service: EventService):
        self._bridge = bridge
        self.service = service

    @property
    def port(self) -> int | None:
        return self._bridge.port

    def stop(self) -> None:
        """Drain frontends FIRST (no new submits can arrive once the
        workers are gone), then drain the group-commit queues -- the
        reverse order would strand in-flight requests on a stopped
        pipeline's 429s mid-drain."""
        self._bridge.stop()
        self.service.shutdown_ingest()


def create_multiproc_event_server(
    host: str = "0.0.0.0",
    port: int = DEFAULT_PORT,
    stats: bool = False,
    plugins: list[EventServerPlugin] | None = None,
    ingest_config: IngestConfig | None = None,
    tracing: bool | None = None,
    trace_sample: float | None = None,
    slow_commit_ms: float | None = None,
    frontend_config=None,
) -> MultiprocEventServerHandle:
    """Multi-process event server: frontends parse HTTP and forward over
    shared-memory rings; this process runs the WAL partitions. Dispatch
    is the SYNC pool (``async_query=None``): an ingest request legitimately
    parks its dispatcher thread on the group-commit future, so
    ``max_inflight`` is the tier's ingest-concurrency bound.

    The returned handle is started; callers print/wait/stop."""
    from predictionio_tpu.serving.procserver import (
        FrontendConfig,
        ScorerBridge,
    )

    if frontend_config is None:
        frontend_config = FrontendConfig(dispatch="sync", max_inflight=32)
    # late-bound cell: the service's /metrics scrape merges worker
    # snapshots, but the bridge needs the service's router first
    bridge_cell: list = []

    def worker_snapshots() -> list[dict]:
        return bridge_cell[0].metric_snapshots() if bridge_cell else []

    service = EventService(
        stats=stats, plugins=plugins, ingest_config=ingest_config,
        tracing=tracing, trace_sample=trace_sample,
        slow_commit_ms=slow_commit_ms,
        extra_metrics_snapshots=worker_snapshots,
    )
    bridge = ScorerBridge(
        service.router, host, port, frontend_config,
        server_name="pio-eventserver", registry=service.metrics,
    )
    bridge_cell.append(bridge)
    try:
        bridge.start()
    except Exception:
        service.shutdown_ingest()
        raise
    return MultiprocEventServerHandle(bridge, service)


def run_event_server(
    host: str = "0.0.0.0",
    port: int = DEFAULT_PORT,
    stats: bool = False,
    ssl_cert: str | None = None,
    ssl_key: str | None = None,
    plugins: list[EventServerPlugin] | None = None,
    ingest_config: IngestConfig | None = None,
    tracing: bool | None = None,
    trace_sample: float | None = None,
    slow_commit_ms: float | None = None,
    frontend_workers: int = 0,
) -> None:
    """Blocking entry point used by ``pio eventserver``."""
    if frontend_workers > 0:
        if ssl_cert or ssl_key:
            # PR-8 precedent: TLS terminates in the worker processes or
            # nowhere; the rings carry parsed frames, not TLS streams
            raise ValueError(
                "--frontend-workers does not support --ssl-cert/--ssl-key;"
                " terminate TLS in front of the frontends"
            )
        from predictionio_tpu.serving.procserver import FrontendConfig

        handle = create_multiproc_event_server(
            host=host, port=port, stats=stats, plugins=plugins,
            ingest_config=ingest_config, tracing=tracing,
            trace_sample=trace_sample, slow_commit_ms=slow_commit_ms,
            frontend_config=FrontendConfig(
                workers=frontend_workers, dispatch="sync", max_inflight=32,
            ),
        )
        service = handle.service
        mode = "wal" if service.ingest is not None else "sync"
        parts = getattr(service.ingest, "partitions", 1)
        print(
            f"Event Server listening on http://{host}:{handle.port}"
            f" (stats={'on' if stats else 'off'}, ingest={mode},"
            f" wal-partitions={parts},"
            f" frontend-workers={frontend_workers},"
            f" plugins={len(service.plugins)})"
        )
        try:
            threading.Event().wait()
        except KeyboardInterrupt:
            pass
        finally:
            handle.stop()
        return
    service = EventService(
        stats=stats, plugins=plugins, ingest_config=ingest_config,
        tracing=tracing, trace_sample=trace_sample,
        slow_commit_ms=slow_commit_ms,
    )
    server = make_server(
        service.router, host, port, "pio-eventserver",
        ssl_cert=ssl_cert, ssl_key=ssl_key,
    )
    scheme = "https" if ssl_cert else "http"
    mode = "wal" if service.ingest is not None else "sync"
    print(
        f"Event Server listening on {scheme}://{host}:{port}"
        f" (stats={'on' if stats else 'off'}, ingest={mode},"
        f" plugins={len(service.plugins)})"
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        server.server_close()
    finally:
        service.shutdown_ingest()
