"""L5 HTTP services for the data layer (Event Server)."""
